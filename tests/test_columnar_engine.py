"""Columnar engine hot path: ColumnarGroups state, Columns payloads,
lazy node state, raw-batch scheduling.

These tests pin the columnar fast paths to the row interpreter's exact
semantics (the contract: vectorization must be unobservable except in
speed). Reference behaviors: reducer semantics src/engine/reduce.rs:78,
consolidation src/engine/dataflow.rs (consolidate_for_output).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from pathway_tpu.engine import (
    ReducerKind,
    Scheduler,
    Scope,
    make_reducer,
    ref_scalar,
)
from pathway_tpu.engine.batch import Columns, DeltaBatch


def _groupby_scope(reducer_specs, row_wise=False):
    scope = Scope()
    sess = scope.input_session(2)
    gb = scope.group_by_table(
        sess,
        by_cols=[0],
        reducers=[(make_reducer(k), cols) for k, cols in reducer_specs],
    )
    if row_wise:
        gb._cg = None
    log: list = []
    scope.subscribe_table(
        gb, on_change=lambda k, r, t, d: log.append((k, r, d))
    )
    return scope, sess, gb, log


class TestColumnarGroups:
    def test_randomized_equivalence_with_row_path(self):
        """Final states and per-commit net effects match the row path over
        a randomized insert/retract stream (both modes, same ops)."""
        rng = random.Random(11)
        live: dict = {}
        ops = []
        for _ in range(25):
            commit = []
            for _ in range(rng.randint(1, 60)):
                if live and rng.random() < 0.3:
                    key = rng.choice(list(live))
                    commit.append(("-", key, live.pop(key)))
                else:
                    key = ref_scalar(("k", rng.randint(0, 10**9)))
                    row = (rng.randint(0, 7), float(rng.randint(-9, 9)))
                    live[key] = row
                    commit.append(("+", key, row))
            ops.append(commit)

        def run(row_wise):
            scope, sess, gb, log = _groupby_scope(
                [(ReducerKind.SUM, [1]), (ReducerKind.COUNT, [])],
                row_wise=row_wise,
            )
            sched = Scheduler(scope)
            for commit in ops:
                for op, key, row in commit:
                    (sess.insert if op == "+" else sess.remove)(key, row)
                sched.commit()
            return dict(gb.current)

        assert run(False) == run(True)

    def test_no_spurious_emission_when_row_unchanged(self):
        """SUM-only groupby: inserting a zero contribution into an existing
        group changes membership but not the visible row — nothing may be
        emitted (the row path's old_row != new_row guard)."""
        scope, sess, gb, log = _groupby_scope([(ReducerKind.SUM, [1])])
        sched = Scheduler(scope)
        sess.insert(ref_scalar(1), (5, 7.0))
        sched.commit()
        assert gb._cg is not None
        log.clear()
        sess.insert(ref_scalar(2), (5, 0.0))  # zero delta, same group
        sched.commit()
        assert log == [], log
        # and the state still reflects both rows' membership
        sess.remove(ref_scalar(1), (5, 7.0))
        sched.commit()
        rows = list(gb.current.values())
        assert rows == [(5, 0.0)], rows

    def test_float_rounding_swallowed_delta_emits_nothing(self):
        scope, sess, gb, log = _groupby_scope([(ReducerKind.SUM, [1])])
        sched = Scheduler(scope)
        sess.insert(ref_scalar(1), (1, 1e18))
        sched.commit()
        log.clear()
        sess.insert(ref_scalar(2), (1, 1.0))  # swallowed by float rounding
        sched.commit()
        assert log == [], log

    def test_dead_group_slots_compact(self):
        """Churning group keys must not grow columnar state unboundedly."""
        scope, sess, gb, log = _groupby_scope([(ReducerKind.COUNT, [])])
        sched = Scheduler(scope)
        for wave in range(20):
            keys = [
                (ref_scalar((wave, i)), (wave * 1000 + i, 0.0))
                for i in range(500)
            ]
            for k, r in keys:
                sess.insert(k, r)
            sched.commit()
            for k, r in keys:
                sess.remove(k, r)
            sched.commit()
        cg = gb._cg
        assert cg is not None
        assert cg.size <= 4096, cg.size
        assert len(gb.current) == 0

    def test_snapshot_does_not_degrade_columnar_state(self):
        scope, sess, gb, log = _groupby_scope(
            [(ReducerKind.SUM, [1]), (ReducerKind.COUNT, [])]
        )
        sched = Scheduler(scope)
        for i in range(400):
            sess.insert(ref_scalar(i), (i % 3, float(i)))
        sched.commit()
        state = gb.op_state()
        assert gb._cg is not None  # snapshot did not degrade
        assert len(state["groups"]) == 3
        # restored state runs the dict path and stays correct
        scope2, sess2, gb2, _ = _groupby_scope(
            [(ReducerKind.SUM, [1]), (ReducerKind.COUNT, [])]
        )
        gb2.restore_op_state(state)
        sched2 = Scheduler(scope2)
        sess2.insert(ref_scalar("x"), (0, 10.0))
        sched2.commit()
        got = {r[0]: (r[1], r[2]) for r in gb2.current.values()}
        exp_sum = sum(float(i) for i in range(400) if i % 3 == 0) + 10.0
        assert got[0] == (exp_sum, 135)

    def test_bool_int_group_identity_matches_row_path(self):
        for row_wise in (False, True):
            scope, sess, gb, _ = _groupby_scope(
                [(ReducerKind.COUNT, [])], row_wise=row_wise
            )
            sched = Scheduler(scope)
            for i in range(300):
                sess.insert(ref_scalar(("b", i)), (True, 0.0))
            sched.commit()
            for i in range(300):
                sess.insert(ref_scalar(("i", i)), (1, 0.0))
            sched.commit()
            for i in range(300):
                sess.insert(ref_scalar(("f", i)), (1.0, 0.0))
            sched.commit()
            rows = sorted((repr(r[0]), r[1]) for r in gb.current.values())
            assert rows == [("1", 600), ("True", 300)], (row_wise, rows)

    def test_nan_group_values_degrade(self):
        scope, sess, gb, _ = _groupby_scope([(ReducerKind.COUNT, [])])
        sched = Scheduler(scope)
        for i in range(300):
            sess.insert(ref_scalar(i), (float("nan"), 0.0))
        sched.commit()
        assert gb._cg is None  # degraded rather than guessing NaN identity
        assert sum(r[1] for r in gb.current.values()) == 300

    def test_int64_overflow_risk_degrades_exactly(self):
        scope, sess, gb, _ = _groupby_scope([(ReducerKind.SUM, [1])])
        sched = Scheduler(scope)
        big = (1 << 62) - 1
        for i in range(300):
            sess.insert(ref_scalar(i), (1, big))
        sched.commit()
        got = [r for r in gb.current.values()]
        assert got == [(1, 300 * big)], got  # exact Python bigint


class TestUpdateStreamEquivalence:
    def test_subscribe_logs_match_between_paths(self):
        """Per-commit NET update streams (not just final states) must be
        identical between the columnar and row paths: same retract/insert
        multisets at every commit of a randomized groupby stream."""
        rng = random.Random(23)
        live: dict = {}
        ops = []
        for _ in range(15):
            commit = []
            for _ in range(rng.randint(1, 50)):
                if live and rng.random() < 0.35:
                    key = rng.choice(list(live))
                    commit.append(("-", key, live.pop(key)))
                else:
                    key = ref_scalar(("k", rng.randint(0, 10**9)))
                    row = (rng.randint(0, 5), float(rng.randint(-9, 9)))
                    live[key] = row
                    commit.append(("+", key, row))
            ops.append(commit)

        def run(row_wise):
            scope, sess, gb, log = _groupby_scope(
                [(ReducerKind.SUM, [1]), (ReducerKind.COUNT, [])],
                row_wise=row_wise,
            )
            sched = Scheduler(scope)
            per_commit = []
            for commit in ops:
                for op, key, row in commit:
                    (sess.insert if op == "+" else sess.remove)(key, row)
                mark = len(log)
                sched.commit()
                from collections import Counter

                per_commit.append(Counter(map(repr, log[mark:])))
            return per_commit

        assert run(False) == run(True)


class TestGroupbyJoinChain:
    def test_groupby_output_keeps_downstream_join_columnar(self):
        """The groupby's by-column densifies on emission, so a
        groupby -> join chain stays on the columnar paths end to end."""
        scope = Scope()
        sess = scope.input_session(2)
        gb = scope.group_by_table(
            sess,
            by_cols=[0],
            reducers=[(make_reducer(ReducerKind.COUNT), [])],
        )
        dim = scope.input_session(2)
        jn = scope.join_tables(gb, dim, left_on=[0], right_on=[0], kind="inner")
        sched = Scheduler(scope)
        for i in range(600):
            sess.insert(ref_scalar(i), (i % 6, 0.0))
        for g in range(6):
            dim.insert(ref_scalar(("d", g)), (g, f"name{g}"))
        sched.commit()
        assert gb._cg is not None
        assert jn._columnar_ok  # by-column arrived densified (int64)
        got = sorted(r for r in jn.current.values())
        assert got == [(g, 100, g, f"name{g}") for g in range(6)]


class TestColumnarConcat:
    def test_bulk_concat_stays_columnar_and_screens_duplicates(self):
        from pathway_tpu.engine import expression as ex

        def build(scope):
            a = scope.input_session(2)
            b = scope.input_session(2)
            fa = scope.expression_table(
                a, [ex.ColumnRef(0), ex.ColumnRef(1)]
            )
            fb = scope.expression_table(
                b, [ex.ColumnRef(0), ex.ColumnRef(1)]
            )
            return a, b, scope.concat_tables([fa, fb])

        scope = Scope()
        a, b, cat = build(scope)
        sched = Scheduler(scope)
        for i in range(500):
            a.insert(ref_scalar(("a", i)), (i, float(i)))
            b.insert(ref_scalar(("b", i)), (1000 + i, float(i)))
        sched.commit()
        # output stayed columnar (no per-row materialisation)
        assert cat._state_lag and any(
            x.columns is not None for x in cat._state_lag
        )
        assert len(cat.current) == 1000

        # duplicate keys across sides route through the reporting row path
        scope2 = Scope()
        a2, b2, cat2 = build(scope2)
        sched2 = Scheduler(scope2)
        dup = ref_scalar("same")
        for i in range(300):
            a2.insert(ref_scalar(("a", i)), (i, 0.0))
        a2.insert(dup, (1, 0.0))
        b2.insert(dup, (2, 0.0))
        sched2.commit()
        assert len(cat2.current) == 301  # one copy survives, one reported
        assert len(scope2.error_log_default.current) == 1


class TestColumnsPayload:
    def test_concat_keeps_layout_and_rejects_dtype_mixes(self):
        a = Columns(
            2,
            [np.array([1, 2]), np.array([1.5, 2.5])],
            kobjs=[ref_scalar(1), ref_scalar(2)],
        )
        b = Columns(
            1,
            [np.array([3]), np.array([3.5])],
            kobjs=[ref_scalar(3)],
        )
        c = Columns.concat([a, b])
        assert c.n == 3
        assert c.cols[0].tolist() == [1, 2, 3]
        mixed = Columns(1, [np.array([1.0]), np.array([1.0])], kobjs=[ref_scalar(4)])
        assert Columns.concat([a, mixed]) is None  # int64 vs float64 col 0

    def test_key_views_roundtrip(self):
        keys = [ref_scalar(i) for i in range(5)]
        c = Columns(5, [np.arange(5)], kobjs=keys)
        kb = c.kbytes()
        assert kb.shape == (5, 16)
        c2 = Columns(5, [np.arange(5)], kbytes=kb)
        assert c2.kobjs() == keys

    def test_entries_materialisation_types(self):
        keys = [ref_scalar(i) for i in range(3)]
        c = Columns(
            3,
            [np.array([1, 2, 3]), np.array(["a", "b", "c"])],
            kobjs=keys,
            diffs=np.array([1, -1, 2], np.int64),
        )
        batch = DeltaBatch.from_columns(c, consolidated=True)
        entries = batch.entries
        assert entries == [
            (keys[0], (1, "a"), 1),
            (keys[1], (2, "b"), -1),
            (keys[2], (3, "c"), 2),
        ]
        assert all(type(e[1][0]) is int for e in entries)


def _join_scope(columnar=True, kind="inner"):
    scope = Scope()
    left = scope.input_session(2)
    right = scope.input_session(2)
    jn = scope.join_tables(left, right, left_on=[0], right_on=[0], kind=kind)
    if not columnar:
        jn._columnar_ok = False
    return scope, left, right, jn


class TestColumnarJoin:
    def test_randomized_streaming_equivalence(self):
        """Insert-only streaming over several commits: the columnar block
        join must equal the dict-path join state exactly."""
        rng = random.Random(5)

        def ops():
            rng2 = random.Random(5)
            out = []
            for c in range(8):
                commit = []
                for i in range(rng2.randint(5, 80)):
                    side = rng2.random() < 0.6
                    jk = rng2.randint(0, 15)
                    commit.append(
                        (
                            side,
                            ref_scalar((c, i, side)),
                            (jk, float(rng2.randint(0, 99))),
                        )
                    )
                out.append(commit)
            return out

        def run(columnar):
            scope, left, right, jn = _join_scope(columnar)
            sched = Scheduler(scope)
            for commit in ops():
                for is_left, key, row in commit:
                    (left if is_left else right).insert(key, row)
                sched.commit()
            return dict(jn.current)

        a, b = run(True), run(False)
        assert a == b and len(a) > 100

    def test_retraction_hands_over_to_dict_path(self):
        scope, left, right, jn = _join_scope()
        sched = Scheduler(scope)
        for i in range(300):
            left.insert(ref_scalar(("l", i)), (i % 10, float(i)))
        for i in range(10):
            right.insert(ref_scalar(("r", i)), (i, float(i)))
        sched.commit()
        assert jn._columnar_ok and jn._blocks_left
        n0 = len(jn.current)
        assert n0 == 300
        # retraction: blocks materialise into dicts, results stay exact
        left.remove(ref_scalar(("l", 7)), (7, 7.0))
        sched.commit()
        assert not jn._columnar_ok and not jn._blocks_left
        assert len(jn.current) == 299
        # and further streaming still joins correctly
        left.insert(ref_scalar(("l", 999)), (3, 999.0))
        sched.commit()
        assert len(jn.current) == 300

    def test_result_keys_match_row_path(self):
        """Lazy pair-key derivation must equal join_result_key exactly."""
        out_cols, out_rows = [], []

        def run(columnar):
            scope, left, right, jn = _join_scope(columnar)
            sched = Scheduler(scope)
            for i in range(400):
                left.insert(ref_scalar(("l", i)), (i % 7, float(i)))
            for i in range(7):
                right.insert(ref_scalar(("r", i)), (i, float(i) * 10))
            sched.commit()
            return dict(jn.current)

        a, b = run(True), run(False)
        assert a == b  # same Pointers AND same rows
        assert len(a) == 400

    def test_mixed_int_float_join_keys(self):
        for columnar in (True, False):
            scope, left, right, jn = _join_scope(columnar)
            sched = Scheduler(scope)
            left.insert(ref_scalar("a"), (1, 0.0))
            left.insert(ref_scalar("b"), (2, 0.0))
            right.insert(ref_scalar("x"), (1.0, 1.0))  # float 1.0 == int 1
            right.insert(ref_scalar("y"), (2.5, 2.0))
            sched.commit()
            rows = sorted(r[:3] for r in jn.current.values())
            assert rows == [(1, 0.0, 1.0)], (columnar, rows)

    def test_string_join_keys_columnar(self):
        scope, left, right, jn = _join_scope()
        sched = Scheduler(scope)
        for i in range(300):
            left.insert(ref_scalar(("l", i)), (f"k{i % 5}", float(i)))
        for i in range(5):
            right.insert(ref_scalar(("r", i)), (f"k{i}", float(i)))
        sched.commit()
        assert jn._columnar_ok  # strings stayed on the columnar path
        assert len(jn.current) == 300

    def test_snapshot_roundtrip_during_columnar_mode(self):
        scope, left, right, jn = _join_scope()
        sched = Scheduler(scope)
        for i in range(100):
            left.insert(ref_scalar(("l", i)), (i % 4, float(i)))
        for i in range(4):
            right.insert(ref_scalar(("r", i)), (i, 0.5))
        sched.commit()
        state = jn.op_state()
        assert jn._columnar_ok  # snapshot did not degrade
        scope2, l2, r2, jn2 = _join_scope()
        jn2.restore_op_state(state)
        assert not jn2._columnar_ok  # restored dicts take the row path
        sched2 = Scheduler(scope2)
        l2.insert(ref_scalar("new"), (2, -1.0))
        sched2.commit()
        assert len(jn2.current) == 101

    def test_duplicate_key_inserts_fall_back(self):
        """Same (key,row) inserted twice in one commit: the columnar path
        must not take the batch (the dict arrangements collapse duplicate
        multiplicity, so a later retraction would leave a phantom row)."""

        def run(columnar):
            scope, left, right, jn = _join_scope(columnar)
            sched = Scheduler(scope)
            k = ref_scalar("dup")
            left.insert(k, (10, 1.0))
            left.insert(k, (10, 1.0))  # duplicate
            right.insert(ref_scalar("r"), (10, 5.0))
            sched.commit()
            first = len(jn.current)
            left.remove(k, (10, 1.0))
            sched.commit()
            second = len(jn.current)
            left.remove(k, (10, 1.0))
            sched.commit()
            return first, second, len(jn.current)

        assert run(True) == run(False)

    def test_filter_expression_columnar_chain(self):
        """session -> expression -> filter stays columnar end to end and
        matches the row path exactly."""
        from pathway_tpu.engine import expression as ex

        def run(threshold):
            import pathway_tpu.engine.graph as graph_mod

            old = graph_mod.VECTOR_THRESHOLD
            graph_mod.VECTOR_THRESHOLD = threshold
            try:
                scope = Scope()
                sess = scope.input_session(2)
                expr = scope.expression_table(
                    sess,
                    [
                        ex.ColumnRef(0),
                        ex.Binary(
                            "*", ex.ColumnRef(1), ex.Const(2.0)
                        ),
                        ex.Binary(">", ex.ColumnRef(0), ex.Const(100)),
                    ],
                )
                filt = scope.filter_table(expr, 2)
                sched = Scheduler(scope)
                for i in range(1000):
                    sess.insert(ref_scalar(i), (i, float(i)))
                sched.commit()
                return dict(filt.current)
            finally:
                graph_mod.VECTOR_THRESHOLD = old

        fast, slow = run(256), run(1 << 60)
        assert fast == slow
        assert len(fast) == 899
        row = fast[ref_scalar(101)]
        assert row == (101, 202.0, True) and type(row[1]) is float

    def test_nan_join_keys_fall_back(self):
        scope, left, right, jn = _join_scope()
        sched = Scheduler(scope)
        left.insert(ref_scalar("a"), (float("nan"), 0.0))
        right.insert(ref_scalar("x"), (float("nan"), 1.0))
        sched.commit()
        assert not jn._columnar_ok  # NaN identity is the dict path's call


def _join_scope2(columnar=True):
    """Two-equality join: rows are (k1, k2, v); join on [0, 1]."""
    scope = Scope()
    left = scope.input_session(3)
    right = scope.input_session(3)
    jn = scope.join_tables(
        left, right, left_on=[0, 1], right_on=[0, 1], kind="inner"
    )
    if not columnar:
        jn._columnar_ok = False
    return scope, left, right, jn


class TestMultiKeyColumnar:
    """Multi-column columnar joins/groupbys: composite-code matching must
    be unobservable next to the row/dict paths (the round-4 engine only
    took single-key operators columnar; reference joins arbitrary key
    tuples natively, src/engine/dataflow.rs:820)."""

    def test_multikey_join_randomized_equivalence(self):
        rng_ops = []
        rng = random.Random(77)
        for c in range(8):
            commit = []
            for i in range(rng.randint(5, 90)):
                side = rng.random() < 0.6
                commit.append(
                    (
                        side,
                        ref_scalar((c, i, side)),
                        (
                            rng.randint(0, 5),
                            f"s{rng.randint(0, 3)}",
                            float(rng.randint(0, 99)),
                        ),
                    )
                )
            rng_ops.append(commit)

        def run(columnar):
            scope, left, right, jn = _join_scope2(columnar)
            sched = Scheduler(scope)
            for commit in rng_ops:
                for is_left, key, row in commit:
                    (left if is_left else right).insert(key, row)
                sched.commit()
            if columnar:
                # the columnar path actually carried the load
                assert jn._columnar_ok and jn._blocks_left
            return dict(jn.current)

        a, b = run(True), run(False)
        assert a == b and len(a) > 100

    def test_multikey_join_cross_dtype_second_key(self):
        """int vs float equality on key column 2 (1 == 1.0) must match the
        dict path's Python semantics under composite codes."""
        for columnar in (True, False):
            scope, left, right, jn = _join_scope2(columnar)
            sched = Scheduler(scope)
            left.insert(ref_scalar("a"), (7, 1, 0.0))
            left.insert(ref_scalar("b"), (7, 2, 0.0))
            right.insert(ref_scalar("x"), (7, 1.0, 5.0))
            right.insert(ref_scalar("y"), (7, 2.5, 6.0))
            sched.commit()
            rows = sorted(tuple(r) for r in jn.current.values())
            assert rows == [(7, 1, 0.0, 7, 1.0, 5.0)], (columnar, rows)

    def test_multikey_join_nan_in_one_key_falls_back(self):
        scope, left, right, jn = _join_scope2()
        sched = Scheduler(scope)
        left.insert(ref_scalar("a"), (1, float("nan"), 0.0))
        right.insert(ref_scalar("x"), (1, float("nan"), 1.0))
        sched.commit()
        assert not jn._columnar_ok

    def test_multikey_join_retraction_hands_over(self):
        scope, left, right, jn = _join_scope2()
        sched = Scheduler(scope)
        for i in range(200):
            left.insert(
                ref_scalar(("l", i)), (i % 5, i % 3, float(i))
            )
        for i in range(15):
            right.insert(
                ref_scalar(("r", i)), (i % 5, i % 3, float(i) * 10)
            )
        sched.commit()
        assert jn._columnar_ok and jn._blocks_left
        before = dict(jn.current)
        left.remove(ref_scalar(("l", 7)), (2, 1, 7.0))
        sched.commit()
        assert not jn._columnar_ok
        # exactly the pairs of the removed row disappeared
        lost = set(before) - set(jn.current)
        assert len(lost) == 1  # (2,1) matched one right row
        assert len(jn.current) == len(before) - 1

    def _groupby2(self, row_wise=False):
        scope = Scope()
        sess = scope.input_session(3)
        gb = scope.group_by_table(
            sess,
            by_cols=[0, 1],
            reducers=[
                (make_reducer(ReducerKind.SUM), [2]),
                (make_reducer(ReducerKind.COUNT), []),
            ],
        )
        if row_wise:
            gb._cg = None
        return scope, sess, gb

    def test_multikey_groupby_randomized_equivalence(self):
        rng = random.Random(31)
        live: dict = {}
        ops = []
        for _ in range(20):
            commit = []
            for _ in range(rng.randint(1, 70)):
                if live and rng.random() < 0.3:
                    key = rng.choice(list(live))
                    commit.append(("-", key, live.pop(key)))
                else:
                    key = ref_scalar(("k", rng.randint(0, 10**9)))
                    row = (
                        rng.randint(0, 4),
                        f"g{rng.randint(0, 3)}",
                        float(rng.randint(-9, 9)),
                    )
                    live[key] = row
                    commit.append(("+", key, row))
            ops.append(commit)

        def run(row_wise):
            scope, sess, gb = self._groupby2(row_wise)
            sched = Scheduler(scope)
            for commit in ops:
                for op, key, row in commit:
                    (sess.insert if op == "+" else sess.remove)(key, row)
                sched.commit()
            if not row_wise:
                assert gb._cg is not None  # never degraded
            return dict(gb.current)

        assert run(False) == run(True)

    def test_multikey_groupby_bool_int_identity(self):
        """(True, 1.0) and (1, 1) are DIFFERENT groups on the first column
        (bool tag) and the SAME value on the second (1.0 == 1) — exactly
        the row path's hash_values identity."""

        def run(row_wise):
            scope, sess, gb = self._groupby2(row_wise)
            sched = Scheduler(scope)
            rows = [
                (True, 1, 1.0),
                (1, 1.0, 2.0),
                (1, 1, 4.0),
                (True, 1.0, 8.0),
            ]
            for i, row in enumerate(rows):
                sess.insert(ref_scalar(i), row)
            sched.commit()
            return sorted(
                (repr(r[0]), r[1], r[2]) for r in gb.current.values()
            )

        a, b = run(False), run(True)
        assert a == b
        assert [x[2] for x in a] == [6.0, 9.0]  # two groups, not four

    def test_multikey_groupby_nan_by_value_degrades(self):
        scope, sess, gb = self._groupby2()
        sched = Scheduler(scope)
        sess.insert(ref_scalar(1), (1, float("nan"), 2.0))

        # second by column is int here, first carries the NaN
        scope2 = Scope()
        sess2 = scope2.input_session(3)
        gb2 = scope2.group_by_table(
            sess2,
            by_cols=[1, 0],
            reducers=[(make_reducer(ReducerKind.COUNT), [])],
        )
        sched.commit()
        assert gb._cg is None  # degraded, state exact via row path
        assert len(gb.current) == 1
        sched2 = Scheduler(scope2)
        sess2.insert(ref_scalar(1), (1.5, 3, 0.0))
        sess2.insert(ref_scalar(2), (1.5, 3, 0.0))
        sched2.commit()
        assert gb2._cg is not None  # clean floats stay columnar
        (row,) = gb2.current.values()
        assert row == (3, 1.5, 2)


class TestSharedBatchAliasing:
    def test_buffer_end_flush_does_not_mutate_shared_batches(self):
        """BufferNode.take must not extend a taken batch in place: take()
        can return the producer's own batch object (or its consolidate
        cache), still aliased by sibling consumers and by the producer's
        deferred state lag. Regression: a fan-out source -> {buffer with
        flush_on_end, groupby} double-counted the buffer's end-flush rows
        at the sibling."""
        from pathway_tpu.engine.temporal import BufferNode

        scope = Scope()
        sess = scope.input_session(3)  # (threshold, time, group)
        b1 = BufferNode(scope, sess, threshold_col=0, time_col=1)
        b2 = BufferNode(scope, b1, threshold_col=0, time_col=1)
        gb = scope.group_by_table(
            b1,  # sibling consumer of b1's output, next to b2
            by_cols=[2],
            reducers=[(make_reducer(ReducerKind.COUNT), [])],
        )
        sched = Scheduler(scope)
        # H: b1 holds it (threshold 99 > watermark 9) until end-flush
        sess.insert(ref_scalar("H"), (99, 9, "g"))
        sched.commit()
        # R: b1 emits (5 <= 9) but b2 holds (b2's watermark is only 1 —
        # the watermark-driving row H never reached it); gb counts R now
        sess.insert(ref_scalar("R"), (5, 1, "g"))
        sched.commit()
        assert dict(b2.held), "precondition: b2 must hold R at end"
        # at finish, b1's end-flush batch [H] fans out to b2 and gb; b2's
        # own end-flush of R must NOT be spliced into that shared object
        sched.finish()
        counts = {r[0]: r[1] for r in gb.current.values()}
        assert counts == {"g": 2}, counts  # H + R once each, R not doubled


class TestLazyState:
    def test_state_drains_on_read_and_caps(self):
        scope = Scope()
        sess = scope.input_session(1)
        sched = Scheduler(scope)
        for i in range(100):
            sess.insert(ref_scalar(i), (i,))
        sched.commit()
        assert sess._state_lag  # deferred, nothing observed yet
        assert len(sess.current) == 100  # drain on read
        assert not sess._state_lag

    def test_retraction_after_deferred_state(self):
        """An operator reading its own current for retraction handling sees
        all earlier deferred batches."""
        scope = Scope()
        sess = scope.input_session(2)
        ex_node = scope.expression_table(sess, [])
        from pathway_tpu.engine import expression as ex

        filt_in = scope.expression_table(
            sess,
            [
                ex.ColumnRef(0),
                ex.Binary(">", ex.ColumnRef(1), ex.Const(0.0)),
            ],
        )
        filt = scope.filter_table(filt_in, 1)
        sched = Scheduler(scope)
        for i in range(50):
            sess.insert(ref_scalar(i), (i, float(i % 2) - 0.5))
        sched.commit()
        sess.remove(ref_scalar(1), (1, 0.5))
        sched.commit()
        kept = sorted(r[0] for r in filt.current.values())
        assert kept == [i for i in range(50) if i % 2 == 1 and i != 1]


class TestErrorSemanticsAtColumnarScale:
    """ERROR poisoning and None handling must survive batches large
    enough to trigger every columnar fast path — the screens bail to the
    row interpreter, which owns the exact semantics."""

    def test_division_error_rows_poison_not_crash(self):
        import pathway_tpu as pw
        import pathway_tpu.debug as dbg
        from pathway_tpu.internals.parse_graph import G

        G.clear()
        n = 2000
        t = pw.debug.table_from_rows(
            pw.schema_from_types(a=int, b=int),
            [(i, i % 100) for i in range(n)],  # b==0 every 100th row
        )
        r = t.select(q=pw.this.a // pw.this.b)
        pdf = dbg.table_to_pandas(r)
        errs = sum(1 for v in pdf["q"].tolist() if str(v) == "Error")
        assert errs == n // 100
        ok = [v for v in pdf["q"].tolist() if str(v) != "Error"]
        assert len(ok) == n - n // 100

    def test_groupby_error_in_by_column_reports_and_skips(self):
        from pathway_tpu.engine.value import ERROR

        scope = Scope()
        sess = scope.input_session(2)
        gb = scope.group_by_table(
            sess,
            by_cols=[0],
            reducers=[(make_reducer(ReducerKind.COUNT), [])],
        )
        sched = Scheduler(scope)
        for i in range(1000):
            sess.insert(ref_scalar(i), (i % 4, 0.0))
        sess.insert(ref_scalar("bad"), (ERROR, 0.0))
        sched.commit()
        counts = {r[0]: r[1] for r in gb.current.values()}
        assert counts == {0: 250, 1: 250, 2: 250, 3: 250}
        assert len(scope.error_log_default.current) == 1

    def test_join_error_in_key_reports_and_skips(self):
        from pathway_tpu.engine.value import ERROR

        scope = Scope()
        left = scope.input_session(2)
        right = scope.input_session(2)
        jn = scope.join_tables(left, right, left_on=[0], right_on=[0])
        sched = Scheduler(scope)
        for i in range(800):
            left.insert(ref_scalar(("l", i)), (i % 8, float(i)))
        left.insert(ref_scalar("bad"), (ERROR, -1.0))
        for g in range(8):
            right.insert(ref_scalar(("r", g)), (g, float(g)))
        sched.commit()
        assert len(jn.current) == 800  # the poisoned row joined nothing
        assert len(scope.error_log_default.current) == 1

    def test_none_values_in_payload_columns_roundtrip(self):
        """Nones in non-key columns ride object arrays through the
        columnar join and materialise back as None exactly."""
        scope = Scope()
        left = scope.input_session(2)
        right = scope.input_session(2)
        jn = scope.join_tables(left, right, left_on=[0], right_on=[0])
        sched = Scheduler(scope)
        for i in range(600):
            left.insert(
                ref_scalar(("l", i)),
                (i % 3, None if i % 2 else float(i)),
            )
        for g in range(3):
            right.insert(ref_scalar(("r", g)), (g, f"g{g}"))
        sched.commit()
        assert jn._columnar_ok
        rows = list(jn.current.values())
        assert len(rows) == 600
        nones = sum(1 for r in rows if r[1] is None)
        assert nones == 300
        assert all(r[3] == f"g{r[0]}" for r in rows)


# -- adversarial property tests for the exact-semantics degrade screens ------
#
# The columnar paths compute in wrapping int64 / IEEE float64 / dense
# arrays while the row interpreter computes exact Python semantics; a
# family of screens (NaN bail, int64 overflow headroom, duplicate-key
# low-64-bit pass, mixed-dtype bail, group-identity normalization) must
# force degradation BEFORE any divergence. The reference gets this for
# free from Rust's type system; here the screens are load-bearing, so
# they are pinned by randomized generators (VERDICT r4 next-step #10).


def _gen_scalar(rng, kind):
    """One adversarial scalar of the given column kind."""
    if kind == "int":
        return rng.choice(
            [
                rng.randint(-5, 5),
                rng.randint(-(10**6), 10**6),
                # int64 cliff: sums/products near the wrap boundary
                (1 << 62) - rng.randint(0, 3),
                -(1 << 62) + rng.randint(0, 3),
                (1 << 63) - 1,
                -(1 << 63),
                (1 << 53) + rng.randint(-1, 1),  # float-exactness edge
            ]
        )
    if kind == "float":
        return rng.choice(
            [
                float(rng.randint(-9, 9)),  # int-valued floats (== int)
                rng.random() * 1e3,
                float("nan"),
                float("inf"),
                float("-inf"),
                1e18,
                -1e18,
                0.0,
                -0.0,
                5e-324,  # min subnormal
            ]
        )
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "str":
        return rng.choice(["", "a", "b\x00c", "日本", "x" * 50])
    # mixed: bool/int/float sharing one column, where 1 == 1.0 == True
    return _gen_scalar(rng, rng.choice(["int", "float", "bool"]))


def _gen_clean_scalar(rng, kind):
    """Like _gen_scalar but never NaN (for cases pinning NON-degraded
    paths where the oracle needs dict-key equality)."""
    v = _gen_scalar(rng, kind)
    while isinstance(v, float) and v != v:
        v = _gen_scalar(rng, kind)
    return v


def _colliding_pointer_pairs(rng, n):
    """Pointers sharing their LOW 64 bits but differing in the high 64:
    the duplicate-key screen's first pass sorts the low halves only, so
    these force the full 16-byte verification pass."""
    from pathway_tpu.engine.value import unsafe_make_pointer

    out = []
    for _ in range(n):
        lo = rng.getrandbits(64)
        hi1, hi2 = rng.getrandbits(63), rng.getrandbits(63)
        out.append(
            (
                unsafe_make_pointer(lo | (hi1 << 64)),
                unsafe_make_pointer(lo | (hi2 << 64)),
            )
        )
    return out


class TestDegradeScreenProperties:
    def _run_groupby(self, ops, n_vals, row_wise):
        scope = Scope()
        sess = scope.input_session(1 + n_vals)
        gb = scope.group_by_table(
            sess,
            by_cols=[0],
            reducers=[(make_reducer(ReducerKind.SUM), [i + 1]) for i in range(n_vals)]
            + [(make_reducer(ReducerKind.COUNT), [])],
        )
        if row_wise:
            gb._cg = None
        sched = Scheduler(scope)
        for commit in ops:
            for op, key, row in commit:
                (sess.insert if op == "+" else sess.remove)(key, row)
            sched.commit()
        if not row_wise:
            # force any lazy state to materialize the same way
            pass
        return {k: tuple(map(repr, v)) for k, v in gb.current.items()}

    @pytest.mark.parametrize("seed", range(8))
    @pytest.mark.parametrize("by_kind", ["int", "float", "mixed", "str"])
    def test_groupby_columnar_equals_row_path(self, seed, by_kind):
        """Randomized adversarial columns (NaN placement, int64
        near-overflow, bool/int/float identity mixing) through insert/
        retract schedules: columnar state == row state EXACTLY (repr
        equality, so 1 vs 1.0 vs True differences count)."""
        rng = random.Random((seed << 8) ^ hash(by_kind))
        live: dict = {}
        ops = []
        for _ in range(rng.randint(4, 10)):
            commit = []
            for _ in range(rng.randint(1, 50)):
                if live and rng.random() < 0.35:
                    key = rng.choice(list(live))
                    commit.append(("-", key, live.pop(key)))
                else:
                    key = ref_scalar(("pk", rng.randint(0, 10**9)))
                    row = (
                        _gen_scalar(rng, by_kind),
                        _gen_scalar(rng, "int"),
                        _gen_scalar(rng, "float"),
                    )
                    live[key] = row
                    commit.append(("+", key, row))
            ops.append(commit)
        a = self._run_groupby(ops, 2, row_wise=False)
        b = self._run_groupby(ops, 2, row_wise=True)
        assert a == b

    @pytest.mark.parametrize("seed", range(6))
    def test_join_columnar_equals_dict_path(self, seed):
        """Randomized join-key columns across kinds (cross-kind equality,
        NaN keys, huge ints beyond float64 exactness) with interleaved
        retractions: columnar blocks == dict arrangements exactly."""
        rng = random.Random(900 + seed)
        kinds = ["int", "float", "bool", "str", "mixed"]
        lk_kind = rng.choice(kinds)
        rk_kind = rng.choice(kinds)

        def ops():
            rng2 = random.Random(900 + seed)
            live: list = []
            out = []
            for c in range(6):
                commit = []
                for i in range(rng2.randint(3, 40)):
                    if live and rng2.random() < 0.2:
                        entry = live.pop(rng2.randrange(len(live)))
                        commit.append(("-",) + entry)
                    else:
                        is_left = rng2.random() < 0.5
                        kind = lk_kind if is_left else rk_kind
                        entry = (
                            is_left,
                            ref_scalar((c, i, is_left)),
                            (
                                _gen_scalar(rng2, kind),
                                float(rng2.randint(0, 99)),
                            ),
                        )
                        live.append(entry)
                        commit.append(("+",) + entry)
                out.append(commit)
            return out

        def run(columnar):
            scope, left, right, jn = _join_scope(columnar)
            sched = Scheduler(scope)
            for commit in ops():
                for op, is_left, key, row in commit:
                    sess = left if is_left else right
                    (sess.insert if op == "+" else sess.remove)(key, row)
                sched.commit()
            return {
                k: tuple(map(repr, v)) for k, v in jn.current.items()
            }

        assert run(True) == run(False)

    def test_duplicate_key_screen_low64_collisions(self):
        """Row keys engineered to collide in their LOW 64 bits (the
        screen's cheap first pass) but differ in the high bits: the
        uniqueness verdict must come from the full 16-byte pass, keeping
        genuinely distinct keys on the columnar path and catching true
        duplicates."""
        rng = random.Random(4242)
        pairs = _colliding_pointer_pairs(rng, 40)
        scope, left, right, jn = _join_scope()
        sched = Scheduler(scope)
        for i, (p1, p2) in enumerate(pairs):
            left.insert(p1, (i % 5, 1.0))
            left.insert(p2, (i % 5, 2.0))  # collides in low 64 bits
        for i in range(5):
            right.insert(ref_scalar(("r", i)), (i, 10.0))
        sched.commit()
        # distinct (despite colliding halves): columnar path holds
        assert jn._columnar_ok and jn._blocks_left
        assert len(jn.current) == 80
        # a TRUE duplicate (same full key, same row, twice in one batch)
        scope2, left2, right2, jn2 = _join_scope()
        sched2 = Scheduler(scope2)
        dup = pairs[0][0]
        left2.insert(dup, (1, 1.0))
        left2.insert(dup, (1, 1.0))
        right2.insert(ref_scalar("r"), (1, 5.0))
        sched2.commit()
        d1 = dict(jn2.current)
        left2.remove(dup, (1, 1.0))
        sched2.commit()
        scope3, left3, right3, jn3 = _join_scope(columnar=False)
        sched3 = Scheduler(scope3)
        left3.insert(dup, (1, 1.0))
        left3.insert(dup, (1, 1.0))
        right3.insert(ref_scalar("r"), (1, 5.0))
        sched3.commit()
        d2 = dict(jn3.current)
        left3.remove(dup, (1, 1.0))
        sched3.commit()
        assert d1 == d2
        assert dict(jn2.current) == dict(jn3.current)

    @pytest.mark.parametrize("seed", range(6))
    def test_int64_overflow_headroom_rollback(self, seed):
        """Sums pushed near the int64 cliff from random directions: the
        headroom screen must degrade (with group-creation rollback)
        before any wrapped value can differ from Python's exact ints."""
        rng = random.Random(7000 + seed)
        ops = []
        live: dict = {}
        for c in range(6):
            commit = []
            for i in range(rng.randint(1, 12)):
                if live and rng.random() < 0.25:
                    key = rng.choice(list(live))
                    commit.append(("-", key, live.pop(key)))
                else:
                    key = ref_scalar((c, i))
                    row = (
                        rng.randint(0, 2),
                        rng.choice(
                            [
                                (1 << 62) - 1,
                                -(1 << 62),
                                (1 << 61),
                                rng.randint(-100, 100),
                                (1 << 63) - 1,
                            ]
                        ),
                        0.0,
                    )
                    live[key] = row
                    commit.append(("+", key, row))
            ops.append(commit)
        a = self._run_groupby(ops, 2, row_wise=False)
        b = self._run_groupby(ops, 2, row_wise=True)
        assert a == b

    @pytest.mark.parametrize("seed", range(4))
    def test_expression_columnar_equals_row_interpreter(self, seed):
        """Arithmetic over adversarial int/float columns: the vectorized
        evaluator's overflow/division guards must route every batch whose
        NumPy result could differ (int64 wrap, ZeroDivision poisoning)
        back to the row interpreter."""
        from pathway_tpu.engine import expression as ex
        import pathway_tpu.engine.graph as graph_mod

        rng = random.Random(3100 + seed)
        rows = [
            (
                ref_scalar(i),
                (
                    _gen_clean_scalar(rng, "int"),
                    _gen_clean_scalar(rng, "float"),
                ),
            )
            for i in range(400)
        ]
        exprs = [
            ex.Binary("+", ex.ColumnRef(0), ex.Const(1)),
            ex.Binary("*", ex.ColumnRef(0), ex.ColumnRef(0)),
            ex.Binary("-", ex.ColumnRef(1), ex.ColumnRef(1)),
            ex.Binary(">", ex.ColumnRef(0), ex.Const(0)),
        ]

        def run(threshold):
            old = graph_mod.VECTOR_THRESHOLD
            graph_mod.VECTOR_THRESHOLD = threshold
            try:
                scope = Scope()
                sess = scope.input_session(2)
                out = scope.expression_table(sess, exprs)
                sched = Scheduler(scope)
                for key, row in rows:
                    sess.insert(key, row)
                sched.commit()
                return {
                    k: tuple(map(repr, v))
                    for k, v in out.current.items()
                }
            finally:
                graph_mod.VECTOR_THRESHOLD = old

        assert run(16) == run(1 << 60)

    @pytest.mark.parametrize("seed", range(4))
    def test_sharded_columnar_matches_single_adversarial(self, seed):
        """The sharded columnar exchange with adversarial group values
        (NaNs among them) must produce the single-worker result — NaN
        batches fall back to per-row routing, everything else rides the
        vectorized path."""
        import pathway_tpu as pw
        from pathway_tpu.internals.parse_graph import G
        from pathway_tpu.internals.runner import (
            GraphRunner,
            ShardedGraphRunner,
        )

        rng = random.Random(5200 + seed)
        data = [
            (
                rng.choice(
                    [1.0, 2.5, float("nan"), -0.0, 1e17, 3.0]
                ),
                rng.randint(0, 50),
            )
            for _ in range(600)
        ]

        def build():
            t = pw.debug.table_from_rows(
                pw.schema_from_types(g=float, v=int), data
            )
            return t.groupby(t.g).reduce(
                g=t.g, s=pw.reducers.sum(t.v), n=pw.reducers.count()
            )

        G.clear()
        (single,) = GraphRunner().capture(build())
        G.clear()
        (sharded,) = ShardedGraphRunner(4).capture(build())

        def norm(cap):
            # repr-normalize: NaN != NaN would fail equality on
            # IDENTICAL rows
            return {k: tuple(map(repr, v)) for k, v in cap.items()}

        assert norm(single) == norm(sharded)
