"""Row transformers, gradual_broadcast, export/import
(reference: test_transformers.py, complex_columns.rs, export.rs,
gradual_broadcast.rs)."""

import pathway_tpu as pw
from pathway_tpu.internals.runner import GraphRunner


def rows(t):
    return sorted(GraphRunner().capture(t)[0].values())


class TestRowTransformer:
    def test_simple_transformer_reference_doctest(self):
        @pw.transformer
        class foo_transformer:
            class table(pw.ClassArg):
                arg = pw.input_attribute()

                @pw.output_attribute
                def ret(self) -> int:
                    return self.arg + 1

        table = pw.debug.table_from_rows(
            pw.schema_from_types(arg=int), [(1,), (2,), (3,)]
        )
        ret = foo_transformer(table).table
        assert rows(ret) == [(2,), (3,), (4,)]
        # output keyed by the input row ids
        (snap,) = GraphRunner().capture(foo_transformer(table).table)
        (base,) = GraphRunner().capture(table)
        assert set(snap.keys()) == set(base.keys())

    def test_cross_table_pointer_access(self):
        """reference test_transformers.py:677: read another table via
        self.transformer.<table>[pointer].<attr>."""

        @pw.transformer
        class enrich:
            class params(pw.ClassArg):
                a = pw.input_attribute()

            class queries(pw.ClassArg):
                a_ref = pw.input_attribute()

                @pw.output_attribute
                def doubled(self) -> int:
                    return self.transformer.params[self.a_ref].a * 2

        params = pw.debug.table_from_rows(
            pw.schema_from_types(a=int), [(10,), (20,)]
        )
        (psnap,) = GraphRunner().capture(params)
        keys = sorted(psnap.keys(), key=lambda k: psnap[k])
        queries = pw.debug.table_from_rows(
            pw.schema_from_types(a_ref=pw.Pointer), [(keys[0],), (keys[1],)]
        )
        out = enrich(params, queries).queries
        assert rows(out) == [(20,), (40,)]

    def test_recursive_linked_list(self):
        """reference test_transformers.py:127: recursion through output
        attributes of other rows (list length via next pointers)."""

        @pw.transformer
        class list_len:
            class nodes(pw.ClassArg):
                next = pw.input_attribute()

                @pw.output_attribute
                def length(self) -> int:
                    if self.next is None:
                        return 1
                    return self.transformer.nodes[self.next].length + 1

        base = pw.debug.table_from_rows(
            pw.schema_from_types(tag=str), [("n0",), ("n1",), ("n2",)]
        )
        (bsnap,) = GraphRunner().capture(base)
        ordered = sorted(bsnap, key=lambda k: bsnap[k])
        nodes = pw.debug.table_from_rows(
            pw.schema_from_types(next=pw.Pointer),
            [(ordered[1],), (ordered[2],), (None,)],
        )
        out = list_len(nodes).nodes
        assert sorted(rows(out)) == [(1,), (2,), (3,)]

    def test_methods(self):
        @pw.transformer
        class calc:
            class t(pw.ClassArg):
                v = pw.input_attribute()

                @pw.method
                def add(self, x) -> int:
                    return self.v + x

                @pw.output_attribute
                def plus_ten(self) -> int:
                    return self.add(10)

        t = pw.debug.table_from_rows(pw.schema_from_types(v=int), [(5,)])
        assert rows(calc(t).t) == [(15,)]


class TestGradualBroadcast:
    def test_apx_value_splits_key_space(self):
        t = pw.debug.table_from_rows(
            pw.schema_from_types(name=str), [(f"r{i}",) for i in range(30)]
        )
        thr = pw.debug.table_from_rows(
            pw.schema_from_types(lo=float, v=float, hi=float),
            [(0.0, 0.5, 1.0)],
        )
        out = t._gradual_broadcast(thr, thr.lo, thr.v, thr.hi)
        (snap,) = GraphRunner().capture(out)
        vals = [r[-1] for r in snap.values()]
        assert set(vals) <= {0.0, 1.0}
        assert 0 < vals.count(1.0) < 30

    def test_monotone_in_value(self):
        """A higher broadcast value flips strictly more rows to upper."""

        def count_upper(v):
            t = pw.debug.table_from_rows(
                pw.schema_from_types(name=str), [(f"r{i}",) for i in range(40)]
            )
            thr = pw.debug.table_from_rows(
                pw.schema_from_types(lo=float, v=float, hi=float),
                [(0.0, v, 1.0)],
            )
            out = t._gradual_broadcast(thr, thr.lo, thr.v, thr.hi)
            (snap,) = GraphRunner().capture(out)
            return sum(1 for r in snap.values() if r[-1] == 1.0)

        counts = [count_upper(v) for v in (0.1, 0.5, 0.9)]
        assert counts[0] <= counts[1] <= counts[2]
        assert counts[0] < counts[2]

    def test_gradual_update_emits_only_crossers(self):
        from pathway_tpu.engine.graph import Scheduler, Scope
        from pathway_tpu.engine.temporal import GradualBroadcastNode
        from pathway_tpu.engine.value import ref_scalar

        scope = Scope()
        main = scope.input_session(1)
        thr = scope.input_session(3)
        node = GradualBroadcastNode(scope, main, thr)
        sched = Scheduler(scope)
        for i in range(50):
            main.insert(ref_scalar(i), (i,))
        thr.insert(ref_scalar("t"), (0.0, 0.2, 1.0))
        sched.commit()
        before = dict(node.current)
        thr.insert(ref_scalar("t2"), (0.0, 0.4, 1.0))
        sched.commit()
        after = dict(node.current)
        flipped = [k for k in before if before[k] != after[k]]
        unchanged = [k for k in before if before[k] == after[k]]
        assert flipped and unchanged  # only cutoff-crossers changed


class TestExportImport:
    def test_cross_graph_exchange(self):
        # producer graph
        t = pw.debug.table_from_rows(
            pw.schema_from_types(word=str, n=int), [("a", 1), ("b", 2)]
        )
        counts = t.select(word=t.word, n2=t.n * 10)
        exported = pw.export_table(counts)
        pw.run()
        assert len(exported.snapshot()) == 2
        assert exported.finished

        # consumer graph: a separate runner continues from the export
        imported = pw.import_table(exported)
        total = imported.reduce(s=pw.reducers.sum(imported.n2))
        (snap,) = GraphRunner().capture(total)
        assert list(snap.values()) == [(30,)]

    def test_import_preserves_keys_and_columns(self):
        t = pw.debug.table_from_rows(
            pw.schema_from_types(x=int), [(7,), (8,)]
        )
        exported = pw.export_table(t)
        pw.run()
        imported = pw.import_table(exported)
        assert imported.column_names() == ["x"]
        (snap,) = GraphRunner().capture(imported)
        assert set(snap.keys()) == set(exported.snapshot().keys())


class TestReviewRegressions:
    def test_gradual_broadcast_no_double_retract(self):
        """In-place source update + triplet change in one commit must emit
        clean ±1 diffs (review regression)."""
        from pathway_tpu.engine.graph import Scheduler, Scope
        from pathway_tpu.engine.temporal import GradualBroadcastNode
        from pathway_tpu.engine.value import ref_scalar

        scope = Scope()
        main = scope.input_session(1)
        thr = scope.input_session(3)
        node = GradualBroadcastNode(scope, main, thr)
        seen = []
        scope.subscribe_table(
            node, on_change=lambda key, values, time, diff: seen.append(diff)
        )
        sched = Scheduler(scope)
        for i in range(10):
            main.insert(ref_scalar(i), (i,))
        thr.insert(ref_scalar("t"), (0.0, 0.2, 1.0))
        sched.commit()
        seen.clear()
        # same commit: update one row in place AND move the threshold
        main.remove(ref_scalar(3), (3,))
        main.insert(ref_scalar(3), (33,))
        thr.insert(ref_scalar("t2"), (0.0, 0.9, 1.0))
        sched.commit()
        assert all(d in (-1, 1) for d in seen), seen
        # node state stays one row per key
        assert len(node.current) == 10

    def test_import_table_survives_two_builds(self):
        t = pw.debug.table_from_rows(pw.schema_from_types(x=int), [(1,), (2,)])
        exported = pw.export_table(t)
        pw.run()
        imported = pw.import_table(exported)
        (a,) = GraphRunner().capture(imported)
        (b,) = GraphRunner().capture(imported)
        assert len(a) == 2 and len(b) == 2

    def test_internal_attribute_not_in_output(self):
        @pw.transformer
        class calc:
            class t(pw.ClassArg):
                v = pw.input_attribute()

                @pw.attribute
                def helper(self) -> int:
                    return self.v * 10

                @pw.output_attribute
                def final(self) -> int:
                    return self.helper + 1

        t = pw.debug.table_from_rows(pw.schema_from_types(v=int), [(4,)])
        out = calc(t).t
        assert out.column_names() == ["final"]
        assert rows(out) == [(41,)]

    def test_bad_row_poisons_only_itself(self):
        from pathway_tpu.engine.value import is_error

        @pw.transformer
        class follow:
            class t(pw.ClassArg):
                ptr = pw.input_attribute()

                @pw.output_attribute
                def val(self) -> int:
                    if self.ptr is None:
                        return 7
                    return self.transformer.t[self.ptr].val

        from pathway_tpu.engine.value import ref_scalar

        dangling = ref_scalar("nowhere")
        t = pw.debug.table_from_rows(
            pw.schema_from_types(ptr=pw.Pointer), [(None,), (dangling,)]
        )
        (snap,) = GraphRunner().capture(follow(t).t)
        vals = sorted(snap.values(), key=repr)
        ok = [v for (v,) in vals if not is_error(v)]
        bad = [v for (v,) in vals if is_error(v)]
        assert ok == [7] and len(bad) == 1
