"""stdlib completeness: window_join, intervals_over, AsyncTransformer,
LSH KNN (incremental query contract), fuzzy join, HMM, louvain
(reference suites: temporal/test_window_join.py, test_windows_by.py,
test_utils.py AsyncTransformer, ml/test_index.py, test_fuzzy_join.py)."""

import asyncio
from functools import partial

import numpy as np
import pytest

import pathway_tpu as pw
import pathway_tpu.stdlib.temporal as temporal
from pathway_tpu.internals.runner import GraphRunner


def rows(t):
    return sorted(GraphRunner().capture(t)[0].values(), key=repr)


class TestWindowJoin:
    def t1(self):
        return pw.debug.table_from_rows(
            pw.schema_from_types(t=int), [(1,), (2,), (3,), (7,), (13,)]
        )

    def t2(self):
        return pw.debug.table_from_rows(
            pw.schema_from_types(t=int), [(2,), (5,), (6,), (7,)]
        )

    def test_tumbling_matches_reference_doctest(self):
        r = temporal.window_join(
            self.t1(), self.t2(), pw.this.t, pw.this.t, temporal.tumbling(2)
        )
        # args resolve positionally via the original tables
        t1, t2 = self.t1(), self.t2()
        r = temporal.window_join(t1, t2, t1.t, t2.t, temporal.tumbling(2))
        out = sorted(
            GraphRunner().capture(r.select(left_t=t1.t, right_t=t2.t))[0].values()
        )
        assert out == [(2, 2), (3, 2), (7, 6), (7, 7)]

    def test_sliding_matches_reference_doctest(self):
        t1, t2 = self.t1(), self.t2()
        r = temporal.window_join(t1, t2, t1.t, t2.t, temporal.sliding(1, 2))
        out = sorted(
            GraphRunner().capture(r.select(left_t=t1.t, right_t=t2.t))[0].values()
        )
        assert out == [(1, 2), (2, 2), (2, 2), (3, 2), (7, 6), (7, 7), (7, 7)]

    def test_left_join_pads_unmatched(self):
        t1, t2 = self.t1(), self.t2()
        r = temporal.window_join(
            t1, t2, t1.t, t2.t, temporal.tumbling(2), how="left"
        )
        out = sorted(
            GraphRunner().capture(r.select(left_t=t1.t, right_t=t2.t))[0].values()
        )
        assert (13, None) in out and (1, None) in out

    def test_session_window_join(self):
        s1 = pw.debug.table_from_rows(
            pw.schema_from_types(t=int), [(1,), (2,), (10,)]
        )
        s2 = pw.debug.table_from_rows(
            pw.schema_from_types(t=int), [(3,), (11,)]
        )
        r = temporal.window_join(
            s1, s2, s1.t, s2.t, temporal.session(max_gap=2)
        )
        out = sorted(
            GraphRunner().capture(r.select(lt=s1.t, rt=s2.t))[0].values()
        )
        assert out == [(1, 3), (2, 3), (10, 11)]

    def test_on_condition_partitions(self):
        a = pw.debug.table_from_rows(
            pw.schema_from_types(k=str, t=int), [("x", 1), ("y", 1)]
        )
        b = pw.debug.table_from_rows(
            pw.schema_from_types(k=str, t=int), [("x", 1), ("y", 1)]
        )
        r = temporal.window_join(
            a, b, a.t, b.t, temporal.tumbling(10), a.k == b.k
        )
        out = sorted(
            GraphRunner().capture(r.select(lk=a.k, rk=b.k))[0].values()
        )
        assert out == [("x", "x"), ("y", "y")]


class TestIntervalsOver:
    def test_reference_doctest_shape(self):
        t = pw.debug.table_from_rows(
            pw.schema_from_types(t=int, v=int),
            [(1, 10), (2, 1), (4, 3), (8, 2), (9, 4), (10, 8), (1, 9), (2, 16)],
        )
        probes = pw.debug.table_from_rows(
            pw.schema_from_types(t=int), [(2,), (6,)]
        )
        res = t.windowby(
            t.t,
            window=temporal.intervals_over(
                at=probes.t, lower_bound=-2, upper_bound=1
            ),
        ).reduce(
            pw.this["_pw_window_start"],
            pw.this["_pw_window_end"],
            n=pw.reducers.count(),
            vsum=pw.reducers.sum(pw.this.v),
        )
        assert rows(res) == [(0, 3, 4, 36), (4, 7, 1, 3)]

    def test_outer_keeps_empty_windows(self):
        t = pw.debug.table_from_rows(pw.schema_from_types(t=int, v=int), [(1, 5)])
        probes = pw.debug.table_from_rows(
            pw.schema_from_types(t=int), [(1,), (50,)]
        )
        res = t.windowby(
            t.t,
            window=temporal.intervals_over(
                at=probes.t, lower_bound=-1, upper_bound=1, is_outer=True
            ),
        ).reduce(
            pw.this["_pw_window_start"],
            vsum=pw.reducers.sum(pw.this.v),
        )
        out = rows(res)
        assert (0, 5) in out
        assert (49, None) in out  # empty window surfaces with None aggregate


class TestAsyncTransformer:
    def test_reference_doctest(self):
        class OutputSchema(pw.Schema):
            ret: int

        class Inc(pw.AsyncTransformer, output_schema=OutputSchema):
            async def invoke(self, value):
                await asyncio.sleep(0.01)
                return {"ret": value + 1}

        inp = pw.debug.table_from_rows(
            pw.schema_from_types(value=int), [(42,), (44,)]
        )
        assert rows(Inc(input_table=inp).result) == [(43,), (45,)]

    def test_failures_split_out(self):
        class OutputSchema(pw.Schema):
            ret: int

        class Flaky(pw.AsyncTransformer, output_schema=OutputSchema):
            async def invoke(self, value):
                if value == 1:
                    raise RuntimeError("boom")
                return {"ret": value * 10}

        inp = pw.debug.table_from_rows(
            pw.schema_from_types(value=int), [(1,), (2,)]
        )
        t = Flaky(input_table=inp)
        ok, bad = GraphRunner().capture(t.successful, t.failed)
        assert sorted(ok.values()) == [(20,)]
        assert len(bad) == 1

    def test_chained_transformers(self):
        class OutputSchema(pw.Schema):
            ret: int

        class Inc(pw.AsyncTransformer, output_schema=OutputSchema):
            async def invoke(self, value):
                return {"ret": value + 1}

        class Dbl(pw.AsyncTransformer, output_schema=OutputSchema):
            async def invoke(self, ret):
                return {"ret": ret * 2}

        inp = pw.debug.table_from_rows(pw.schema_from_types(value=int), [(5,)])
        b = Dbl(input_table=Inc(input_table=inp).result)
        assert rows(b.result) == [(12,)]

    def test_signature_mismatch_raises(self):
        class OutputSchema(pw.Schema):
            ret: int

        class T(pw.AsyncTransformer, output_schema=OutputSchema):
            async def invoke(self, wrong_name):
                return {}

        inp = pw.debug.table_from_rows(pw.schema_from_types(value=int), [(1,)])
        with pytest.raises(TypeError, match="signature"):
            T(input_table=inp)
        from pathway_tpu.internals import parse_graph

        parse_graph.G.clear()


class TestLshKnn:
    def _data(self):
        pts = [
            (np.array([0.0, 0.1]),),
            (np.array([0.1, 0.0]),),
            (np.array([5.0, 5.1]),),
            (np.array([5.1, 5.0]),),
        ]
        return pw.debug.table_from_rows(
            pw.schema_from_types(data=np.ndarray), pts
        )

    def test_neighbors_found_per_cluster(self):
        from pathway_tpu.stdlib.ml.classifiers import knn_lsh_classifier_train

        model = knn_lsh_classifier_train(
            self._data(), L=4, type="euclidean", d=2, M=3, A=2.0
        )
        queries = pw.debug.table_from_rows(
            pw.schema_from_types(data=np.ndarray, k=int),
            [(np.array([0.05, 0.05]), 2), (np.array([5.05, 5.05]), 2)],
        )
        res = model(queries, with_distances=True)
        (snap,) = GraphRunner().capture(res)
        for _qid, (_q, pairs) in snap.items():
            assert len(pairs) == 2
            assert all(d < 1.0 for _p, d in pairs)

    def test_metadata_filter(self):
        from pathway_tpu.stdlib.ml.classifiers import knn_lsh_classifier_train

        data = pw.debug.table_from_rows(
            pw.schema_from_types(data=np.ndarray, metadata=dict),
            [
                (np.array([0.0, 0.0]), {"owner": "alice"}),
                (np.array([0.1, 0.1]), {"owner": "bob"}),
            ],
        )
        model = knn_lsh_classifier_train(
            data, L=4, type="euclidean", d=2, M=3, A=4.0
        )
        queries = pw.debug.table_from_rows(
            pw.schema_from_types(
                data=np.ndarray, k=int, metadata_filter=str
            ),
            [(np.array([0.0, 0.0]), 5, "owner == 'bob'")],
        )
        res = model(queries, with_distances=True)
        (snap,) = GraphRunner().capture(res)
        ((_qid, pairs),) = list(snap.values())
        assert len(pairs) == 1  # alice's point filtered out

    def test_incremental_query_contract(self):
        """The defining LshKnn property (SURVEY Appendix B): when data
        changes, answers to OLD queries are revised."""
        from pathway_tpu.engine.graph import Scheduler
        from pathway_tpu.stdlib.indexing import DataIndex, LshKnnFactory

        data_src = pw.debug.table_from_rows(
            pw.schema_from_types(vec=np.ndarray),
            [(np.array([0.0, 0.0]),)],
            stream=True,  # streamable session
        ) if False else None
        # build via input session so data can change after the query answers
        import pathway_tpu.io.python as pwio_python

        class DataSubject(pwio_python.ConnectorSubject):
            def run(self):
                self.next(vec=[0.0, 0.0], tag="near")

        class S(pw.Schema):
            vec: list
            tag: str

        data = pwio_python.read(DataSubject(), schema=S)

        def to_vec(v):
            return np.asarray(
                v.value if hasattr(v, "value") else v, dtype=np.float64
            )

        data_v = data.select(vec=pw.apply(to_vec, data.vec), tag=data.tag)
        index = DataIndex(
            data_v, LshKnnFactory(dimensions=2, L=4, M=3, A=4.0), data_v.vec
        )
        queries = pw.debug.table_from_rows(
            pw.schema_from_types(qv=np.ndarray), [(np.array([0.0, 0.1]),)]
        )
        reply = index.query(queries, queries.qv, number_of_matches=1)

        runner = GraphRunner()
        node = runner.build(reply)
        runner.run()
        (first,) = node.current.values()
        assert len(first[0]) == 1  # one hit: the 'near' point

        # new closer point arrives → the old query's answer is REVISED
        # (run a second round through the same scope)
        from pathway_tpu.engine.graph import Scheduler as Sched

        drv = runner.drivers
        # push new data directly into the session feeding the graph
        session_node = [
            d for d in drv if hasattr(d, "session")
        ]
        assert session_node
        driver = session_node[0]
        from pathway_tpu.engine.value import ref_scalar

        driver.session.insert(
            ref_scalar("new"), (np.array([0.0, 0.1]), "exact")
        )
        sched = Sched(runner.scope)
        sched.commit()
        (second,) = node.current.values()
        assert first != second  # answer updated without re-issuing the query


class TestFuzzyJoin:
    def test_mutual_best_pairs(self):
        from pathway_tpu.stdlib.ml import fuzzy_match_tables

        left = pw.debug.table_from_rows(
            pw.schema_from_types(name=str),
            [("John Smith",), ("Alice Cooper",), ("Bob Dylan",)],
        )
        right = pw.debug.table_from_rows(
            pw.schema_from_types(name=str),
            [("smith john",), ("alice m cooper",), ("ziggy stardust",)],
        )
        out = rows(fuzzy_match_tables(left, right))
        assert len(out) == 2
        assert all(w > 0 for _l, _r, w in out)

    def test_incremental_revision(self):
        """New rows can steal a match — old pairs retract (dataflow)."""
        from pathway_tpu.stdlib.ml import fuzzy_match_tables

        left = pw.debug.table_from_rows(
            pw.schema_from_types(name=str), [("alpha beta",)]
        )
        right = pw.debug.table_from_rows(
            pw.schema_from_types(name=str), [("alpha beta gamma",)]
        )
        out = rows(fuzzy_match_tables(left, right))
        assert len(out) == 1


class TestHmm:
    def test_reference_manul_doctest(self):
        import networkx as nx

        from pathway_tpu.stdlib.ml.hmm import create_hmm_reducer

        table = {
            ("HUNGRY", "GRUMPY"): 0.9,
            ("HUNGRY", "HAPPY"): 0.1,
            ("FULL", "GRUMPY"): 0.7,
            ("FULL", "HAPPY"): 0.3,
        }

        def emis(obs, state):
            return float(np.log(table[(state, obs)]))

        g = nx.DiGraph()
        g.add_node("HUNGRY", calc_emission_log_ppb=partial(emis, state="HUNGRY"))
        g.add_node("FULL", calc_emission_log_ppb=partial(emis, state="FULL"))
        g.add_edge("HUNGRY", "HUNGRY", log_transition_ppb=float(np.log(0.4)))
        g.add_edge("HUNGRY", "FULL", log_transition_ppb=float(np.log(0.6)))
        g.add_edge("FULL", "HUNGRY", log_transition_ppb=float(np.log(0.6)))
        g.add_edge("FULL", "FULL", log_transition_ppb=float(np.log(0.4)))
        g.graph["start_nodes"] = ["HUNGRY", "FULL"]

        decode = create_hmm_reducer(g, num_results_kept=3)
        obs = pw.debug.table_from_rows(
            pw.schema_from_types(observation=str),
            [("HAPPY",), ("HAPPY",), ("GRUMPY",), ("GRUMPY",), ("HAPPY",), ("GRUMPY",)],
        )
        decoded = obs.groupby().reduce(
            decoded_state=pw.reducers.stateful_single(
                decode, pw.this.observation
            )
        )
        assert rows(decoded) == [(("HUNGRY", "FULL", "HUNGRY"),)]


class TestLouvain:
    def test_two_triangles(self):
        from pathway_tpu.stdlib.graphs import louvain_communities

        e = pw.debug.table_from_rows(
            pw.schema_from_types(u=str, v=str),
            [
                ("a", "b"), ("b", "c"), ("a", "c"),
                ("x", "y"), ("y", "z"), ("x", "z"),
                ("c", "x"),
            ],
        )
        comm = dict(rows(louvain_communities(e)))
        assert comm["a"] == comm["b"] == comm["c"]
        assert comm["x"] == comm["y"] == comm["z"]
        assert comm["a"] != comm["x"]


class TestJmespathLite:
    def test_subset_semantics(self):
        from pathway_tpu.internals.jmespath_lite import search

        doc = {"path": "docs/a/report.pdf", "owner": "alice", "size": 4}
        assert search("globmatch('**/*.pdf', path)", doc) is True
        assert search("owner == 'bob' || size > 3", doc) is True
        assert search("contains(path, 'report') && size <= 4", doc) is True
        assert search("missing == null", doc) is True
