"""bench.py must land a JSON verdict line BEFORE its wall budget expires.

Round 5 lost an entire bench round to this: the device probe waited out an
1800s window against an unreachable TPU tunnel, the outer harness killed
the process at its own deadline, and rc=124 with ZERO bytes of JSON was
all that survived. The fix is a hard ``BENCH_WALL_BUDGET_S`` deadline that
clamps every internal wait and guarantees the outage JSON (carrying any
partial numbers) is printed with headroom to spare. This smoke test fakes
the unreachable backend and holds bench.py to that guarantee.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(
    not (REPO / "bench.py").exists(), reason="bench.py not present"
)


def test_outage_json_lands_within_wall_budget():
    budget = 30.0
    env = dict(os.environ)
    # strip any harness-level knobs that would widen the probe window
    for knob in (
        "BENCH_PROBE_WINDOW_S",
        "BENCH_DEVICE_PROBE_S",
        "BENCH_WALL_BUDGET_S",
        "BENCH_REPROBE_GAP_S",
    ):
        env.pop(knob, None)
    env.update(
        # an accelerator platform this CPU-only container cannot reach:
        # jax init either raises or hangs — both are outage modes the
        # budget must bound
        JAX_PLATFORMS="tpu",
        BENCH_WALL_BUDGET_S=str(int(budget)),
        # the probe window deliberately EXCEEDS the budget: only the
        # budget clamp can stop it in time
        BENCH_PROBE_WINDOW_S="600",
        BENCH_REPROBE_GAP_S="1",
        # host workloads are exercised by their own tests; here they
        # would only add noise to the timing assertion
        BENCH_SKIP_DATAFLOW="1",
        PYTHONPATH=str(REPO),
    )
    start = time.time()
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        env=env,
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=budget * 4,  # generous outer net — must NOT be what stops it
    )
    elapsed = time.time() - start

    # rc 3: a watchdog/probe path ran to completion. rc -9/137: libtpu's
    # init held the GIL through its whole C-level retry loop, starving
    # every Python thread, and the sentinel PROCESS printed the outage
    # JSON then SIGKILLed the wedged bench — the designed last resort.
    assert proc.returncode in (3, -9, 137), (
        proc.returncode,
        proc.stdout,
        proc.stderr,
    )
    # the run respected its own deadline (grace for the sentinel's 10s
    # hold-off + interpreter startup/teardown)
    assert elapsed < budget + 25.0, (elapsed, proc.stderr)

    verdicts = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]
    assert verdicts, proc.stdout
    outage = verdicts[-1]
    # the verdict line reports the outage, not a fabricated number
    assert outage.get("value") is None
    err = outage.get("error") or ""
    assert "accelerator" in err or "wall budget" in err, outage


def test_probe_fraction_caps_first_contact_without_wall_budget():
    """BENCH_r05 regression: with NO wall budget set, a never-initializing
    backend must still be bounded by ``BENCH_PROBE_FRACTION`` — the cap
    applies to attempt 1 itself, not only to budget-clamped reprobes — so
    the run self-terminates with a valid outage JSON line instead of
    looping until an external harness kill (rc=124, zero parsed legs)."""
    env = dict(os.environ)
    for knob in (
        "BENCH_PROBE_WINDOW_S",
        "BENCH_DEVICE_PROBE_S",
        "BENCH_WALL_BUDGET_S",
        "BENCH_REPROBE_GAP_S",
        "BENCH_PROBE_FRACTION",
    ):
        env.pop(knob, None)
    env.update(
        # unreachable accelerator platform: init raises (or hangs) on
        # this CPU-only container — the never-initializing backend
        JAX_PLATFORMS="tpu",
        # deliberately NO BENCH_WALL_BUDGET_S: only the fraction cap can
        # bound the window
        BENCH_PROBE_WINDOW_S="600",
        BENCH_PROBE_FRACTION="0.02",  # 600s * 0.02 = 12s hard cap
        BENCH_REPROBE_GAP_S="1",
        BENCH_SKIP_DATAFLOW="1",
        BENCH_SKIP_HOST_FALLBACK="1",
        PYTHONPATH=str(REPO),
    )
    start = time.time()
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        env=env,
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=240,  # outer net only — the fraction cap must do the work
    )
    elapsed = time.time() - start
    assert proc.returncode in (3, -9, 137), (
        proc.returncode,
        proc.stdout,
        proc.stderr,
    )
    # 12s capped window + interpreter startup/teardown + JSON flush; far
    # below the uncapped 600s window that would have required a harness
    # kill to stop
    assert elapsed < 90.0, (elapsed, proc.stderr[-2000:])
    verdicts = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]
    assert verdicts, proc.stdout
    outage = verdicts[-1]
    assert outage.get("value") is None, outage
    assert outage.get("device_unreachable") is True, outage
    # the emitted window proves the fraction cap (not the raw 600s
    # window) bounded the probe
    window = (outage.get("extra") or {}).get("probe_window_s")
    assert window is not None and window <= 600 * 0.02 + 1.0, outage


def test_sigterm_mid_leg_flushes_completed_partials():
    """Killing bench.py mid-leg (SIGTERM, the harness-timeout signal)
    must still land one final VALID JSON line carrying ``truncated:
    true`` plus every leg that already completed — a killed bench
    parses, it never leaves half a line or nothing."""
    import signal

    env = dict(os.environ)
    env.pop("BENCH_WALL_BUDGET_S", None)
    env.update(
        JAX_PLATFORMS="cpu",
        # a small serving leg completes quickly (emitting its partial),
        # then the dataflow suite — pinned to an absurd row count —
        # holds the bench mid-leg for minutes: a deterministic window
        # to land the SIGTERM in
        BENCH_SKIP_PIPELINE="1",
        BENCH_SKIP_QUERY_LOAD="1",
        BENCH_SKIP_FLASH_PARITY="1",
        BENCH_SKIP_DECODE="1",
        BENCH_SKIP_MULTIMODAL="1",
        BENCH_SKIP_VECTOR_STORE="1",
        BENCH_SKIP_RERANKER="1",
        BENCH_SKIP_DEVICE_ONLY="1",
        BENCH_SKIP_HOST_FALLBACK="1",
        BENCH_SERVING_DOCS="200",
        BENCH_SERVING_QUERIES="10",
        BENCH_SERVING_CLIENTS="2",
        BENCH_DATAFLOW_ROWS="200000000",
        PYTHONPATH=str(REPO),
        PYTHONUNBUFFERED="1",
    )
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py")],
        env=env,
        cwd=str(REPO),
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    lines = []
    deadline = time.time() + 600.0
    saw_partial = False
    try:
        # wait for the serving leg's incremental partial line, then
        # kill the bench while the dataflow suite is still mid-leg
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line)
            if '"partial"' in line and "serving_plane" in line:
                saw_partial = True
                break
        assert saw_partial, (proc.poll(), lines)
        # give the dataflow suite a moment to be well inside its leg
        time.sleep(2.0)
        proc.send_signal(signal.SIGTERM)
        rest, _ = proc.communicate(timeout=60.0)
        lines.extend(rest.splitlines(keepends=True))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30.0)
    assert proc.returncode == 3, (proc.returncode, lines)
    # every emitted line is individually valid JSON (nothing half-written)
    parsed = [json.loads(ln) for ln in lines if ln.strip()]
    final = parsed[-1]
    assert final.get("truncated") is True, final
    assert "SIGTERM" in (final.get("error") or ""), final
    # the completed leg's numbers survived into the truncated flush
    assert "serving_plane" in (final.get("extra") or {}), final


def test_slow_serving_leg_is_marked_not_killed():
    """A serving leg that cannot finish inside its per-leg budget must be
    abandoned and MARKED in ``leg_errors`` — the run still exits 0 with a
    parseable JSON verdict, never an rc=124 harness kill."""
    env = dict(os.environ)
    env.pop("BENCH_WALL_BUDGET_S", None)
    env.update(
        JAX_PLATFORMS="cpu",
        # every other leg off: this test times ONLY the serving leg path
        BENCH_SKIP_PIPELINE="1",
        BENCH_SKIP_QUERY_LOAD="1",
        BENCH_SKIP_FLASH_PARITY="1",
        BENCH_SKIP_DECODE="1",
        BENCH_SKIP_MULTIMODAL="1",
        BENCH_SKIP_VECTOR_STORE="1",
        BENCH_SKIP_RERANKER="1",
        BENCH_SKIP_DEVICE_ONLY="1",
        BENCH_SKIP_DATAFLOW="1",
        BENCH_SKIP_HOST_FALLBACK="1",
        # a deliberately unfinishable leg: far more paced-ingest work
        # than the leg budget allows
        BENCH_SERVING_DOCS="2000000",
        BENCH_SERVING_INGEST_RATE="500",
        BENCH_LEG_TIMEOUT_SERVING_PLANE_S="10",
        PYTHONPATH=str(REPO),
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        env=env,
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=240,  # outer net only — the leg budget must do the work
    )
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-2000:])
    verdicts = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{") and "leg_errors" in line
    ]
    assert verdicts, proc.stdout
    leg_errors = verdicts[-1]["extra"]["leg_errors"]
    assert "serving_plane" in leg_errors, leg_errors
    assert "did not complete" in leg_errors["serving_plane"], leg_errors
