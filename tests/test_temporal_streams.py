"""Temporal behaviors under streaming commits with artificial time —
update-stream assertions (the reference's DiffEntry-style stream tests,
tests/utils.py:120-241 + temporal/ suite patterns)."""

import pathway_tpu as pw
import pathway_tpu.stdlib.temporal as temporal
from pathway_tpu.internals.runner import GraphRunner


def run_stream(table):
    """Capture the full update stream [(commit, row, diff)] of ``table``."""
    updates = []
    pw.io.subscribe(
        table,
        on_change=lambda key, row, time, is_addition: updates.append(
            (time, tuple(sorted(row.items())), 1 if is_addition else -1)
        ),
    )
    pw.run()
    return updates


class TestWindowStreamBehavior:
    def _stream(self, batches):
        sg = pw.debug.StreamGenerator()

        class S(pw.Schema):
            t: int
            v: int

        return sg.table_from_list_of_batches(
            [[{"t": t, "v": v} for t, v in batch] for batch in batches], S
        )

    def test_tumbling_updates_then_settles(self):
        """A window's aggregate is revised as rows stream in (diff -1/+1
        pairs), then settles — the incremental contract."""
        t = self._stream([[(1, 10)], [(2, 20)], [(15, 5)]])
        res = t.windowby(t.t, window=temporal.tumbling(10)).reduce(
            start=pw.this["_pw_window_start"],
            total=pw.reducers.sum(pw.this.v),
        )
        updates = run_stream(res)
        # first commit: window [0,10) total 10
        # second commit: retract 10, insert 30
        # third commit: new window [10,20) total 5
        inserts = [(r, c) for c, r, d in updates if d > 0]
        retracts = [(r, c) for c, r, d in updates if d < 0]
        assert (
            (("start", 0), ("total", 10)),
        ) == tuple(r for r, _c in inserts[:1])
        assert any(r == (("start", 0), ("total", 30)) for r, _c in inserts)
        assert any(r == (("start", 0), ("total", 10)) for r, _c in retracts)
        assert any(r == (("start", 10), ("total", 5)) for r, _c in inserts)

    def test_delay_holds_window_until_watermark(self):
        """common_behavior(delay=d): no output until the watermark passes
        window start + d (start-anchored, ADVICE r1)."""
        t = self._stream([[(1, 10)], [(3, 20)], [(8, 1)], [(40, 0)]])
        res = t.windowby(
            t.t,
            window=temporal.tumbling(10),
            behavior=temporal.common_behavior(delay=5),
        ).reduce(
            start=pw.this["_pw_window_start"],
            total=pw.reducers.sum(pw.this.v),
        )
        updates = []
        arrivals = []
        pw.io.subscribe(
            res,
            on_change=lambda key, row, time, is_addition: updates.append(
                (time, tuple(sorted(row.items())), 1 if is_addition else -1)
            ),
        )
        pw.io.subscribe(
            t,
            on_change=lambda key, row, time, is_addition: arrivals.append(
                (time, row["t"])
            ),
        )
        pw.run()
        first_commit_with_w0 = min(
            c for c, r, d in updates if d > 0 and ("start", 0) in r
        )
        watermark_commit = min(c for c, tv in arrivals if tv == 8)
        # rows at t=1,3 arrive earlier, but no [0,10) output may appear
        # before the watermark passes window start + delay (t=8 commit)
        assert first_commit_with_w0 >= watermark_commit
        final = {}
        for c, r, d in updates:
            final[r] = final.get(r, 0) + d
        live = {r for r, n in final.items() if n > 0}
        assert (("start", 0), ("total", 31)) in live
        assert (("start", 40), ("total", 0)) in live

    def test_cutoff_drops_late_rows(self):
        """forget/cutoff: a row arriving after its window's cutoff is
        ignored (reference TimeColumnForget semantics)."""
        t = self._stream([[(1, 10)], [(30, 1)], [(2, 99)]])  # t=2 is LATE
        res = t.windowby(
            t.t,
            window=temporal.tumbling(10),
            behavior=temporal.common_behavior(cutoff=0, keep_results=False),
        ).reduce(
            start=pw.this["_pw_window_start"],
            total=pw.reducers.sum(pw.this.v),
        )
        updates = run_stream(res)
        final = {}
        for c, r, d in updates:
            final[r] = final.get(r, 0) + d
        live = {r for r, n in final.items() if n > 0}
        # the late t=2 row (v=99) must NOT appear in any live window
        assert not any(
            ("total", 109) in r or ("total", 99) in r for r in live
        )
        assert (("start", 30), ("total", 1)) in live

    def test_exactly_once_emits_each_window_once(self):
        """exactly_once_behavior: every window's aggregate appears exactly
        once in the stream — no retractions, no revisions."""
        t = self._stream([[(1, 1)], [(2, 2)], [(11, 3)], [(25, 4)], [(40, 0)]])
        res = t.windowby(
            t.t,
            window=temporal.tumbling(10),
            behavior=temporal.exactly_once_behavior(),
        ).reduce(
            start=pw.this["_pw_window_start"],
            total=pw.reducers.sum(pw.this.v),
        )
        updates = run_stream(res)
        retractions = [u for u in updates if u[2] < 0]
        assert retractions == []  # exactly-once: nothing revised
        emitted = [r for _c, r, d in updates if d > 0]
        assert len(emitted) == len(set(emitted))  # each window once
        assert (("start", 0), ("total", 3)) in emitted
        assert (("start", 10), ("total", 3)) in emitted

    def test_replay_csv_with_time_drives_windows(self, tmp_path):
        """Artificial-time replay (reference demo/__init__.py:258) feeding
        a windowed aggregation."""
        src = tmp_path / "timed.csv"
        src.write_text("t,v\n1,5\n2,6\n11,7\n")

        class S(pw.Schema):
            t: int
            v: int

        t = pw.demo.replay_csv_with_time(str(src), schema=S, time_column="t")
        res = t.windowby(t.t, window=temporal.tumbling(10)).reduce(
            start=pw.this["_pw_window_start"],
            total=pw.reducers.sum(pw.this.v),
        )
        updates = run_stream(res)
        final = {}
        for _c, r, d in updates:
            final[r] = final.get(r, 0) + d
        live = {r for r, n in final.items() if n > 0}
        assert (("start", 0), ("total", 11)) in live
        assert (("start", 10), ("total", 7)) in live


class TestIntervalJoinStream:
    def test_matches_appear_as_sides_arrive(self):
        sg = pw.debug.StreamGenerator()

        class L(pw.Schema):
            t: int
            tag: str

        left = sg.table_from_list_of_batches(
            [[{"t": 10, "tag": "l1"}], [{"t": 30, "tag": "l2"}]], L
        )
        right = pw.debug.table_from_rows(
            pw.schema_from_types(t=int, tag=str),
            [(12, "r1"), (29, "r2")],
        )
        res = temporal.interval_join(
            left, right, left.t, right.t, temporal.interval(-3, 3)
        ).select(lt=left.tag, rt=right.tag)
        updates = run_stream(res)
        live = {}
        for _c, r, d in updates:
            live[r] = live.get(r, 0) + d
        assert {r for r, n in live.items() if n > 0} == {
            (("lt", "l1"), ("rt", "r1")),
            (("lt", "l2"), ("rt", "r2")),
        }


class TestOrderSensitiveReducers:
    def test_earliest_latest_over_stream(self):
        """earliest keeps the first-arrived value, latest the last — across
        commits (reference Earliest/Latest reducers reduce.rs:22)."""
        sg = pw.debug.StreamGenerator()

        class S(pw.Schema):
            k: str
            v: int

        t = sg.table_from_list_of_batches(
            [
                [{"k": "a", "v": 1}],
                [{"k": "a", "v": 2}, {"k": "b", "v": 10}],
                [{"k": "a", "v": 3}],
            ],
            S,
        )
        res = t.groupby(t.k).reduce(
            k=t.k,
            first=pw.reducers.earliest(t.v),
            last=pw.reducers.latest(t.v),
        )
        updates = run_stream(res)
        final = {}
        for _c, r, d in updates:
            final[r] = final.get(r, 0) + d
        live = sorted(r for r, n in final.items() if n > 0)
        assert live == [
            (("first", 1), ("k", "a"), ("last", 3)),
            (("first", 10), ("k", "b"), ("last", 10)),
        ]

    def test_ndarray_reducer(self):
        import numpy as np

        t = pw.debug.table_from_rows(
            pw.schema_from_types(k=str, v=float),
            [("a", 1.0), ("a", 2.0), ("b", 5.0)],
        )
        res = t.groupby(t.k).reduce(
            k=t.k, arr=pw.reducers.ndarray(t.v)
        )
        (snap,) = GraphRunner().capture(res)
        by_k = {r[0]: np.sort(np.asarray(r[1])) for r in snap.values()}
        assert np.allclose(by_k["a"], [1.0, 2.0])
        assert np.allclose(by_k["b"], [5.0])


class TestAsofDirections:
    def _tables(self):
        trades = pw.debug.table_from_rows(
            pw.schema_from_types(t=int, sym=str), [(10, "A"), (20, "A")]
        )
        quotes = pw.debug.table_from_rows(
            pw.schema_from_types(t=int, sym=str, px=float),
            [(7, "A", 1.0), (12, "A", 2.0), (19, "A", 3.0), (30, "A", 4.0)],
        )
        return trades, quotes

    def _run(self, direction):
        trades, quotes = self._tables()
        res = trades.asof_join(
            quotes,
            trades.t,
            quotes.t,
            trades.sym == quotes.sym,
            direction=direction,
        ).select(t=trades.t, px=quotes.px)
        (snap,) = GraphRunner().capture(res)
        return sorted(snap.values())

    def test_backward(self):
        # latest quote at or before each trade
        assert self._run("backward") == [(10, 1.0), (20, 3.0)]

    def test_forward(self):
        # earliest quote at or after each trade
        assert self._run("forward") == [(10, 2.0), (20, 4.0)]

    def test_nearest(self):
        # closest quote either side (|12-10| < |7-10|; |19-20| < |30-20|)
        assert self._run("nearest") == [(10, 2.0), (20, 3.0)]


class TestSessionWindowStream:
    def test_sessions_merge_as_gap_closes(self):
        """Two separate sessions MERGE when a bridging row arrives — the
        retract/re-emit shape of incremental session windows."""
        sg = pw.debug.StreamGenerator()

        class S(pw.Schema):
            t: int

        t = sg.table_from_list_of_batches(
            [[{"t": 1}], [{"t": 10}], [{"t": 5}]], S  # 5 bridges 1 and 10
        )
        res = t.windowby(t.t, window=temporal.session(max_gap=5)).reduce(
            start=pw.this["_pw_window_start"],
            end=pw.this["_pw_window_end"],
            n=pw.reducers.count(),
        )
        updates = run_stream(res)
        final = {}
        for _c, r, d in updates:
            final[r] = final.get(r, 0) + d
        live = {r for r, n in final.items() if n > 0}
        # one merged session [1, 10] with all three rows
        assert live == {(("end", 10), ("n", 3), ("start", 1))}
        # and the separate pre-merge sessions were retracted
        assert any(d < 0 for _c, _r, d in updates)
