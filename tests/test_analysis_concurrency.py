"""Seeded-bug corpus for the source-level concurrency (PWC4xx) and
protocol (PWC5xx) passes.

Each test writes a small module with ONE deliberately planted bug from
the classes the analyzer polices — unguarded write, lock-order cycle,
blocking call under a lock, unbounded daemon wait, annotation typo,
commit-hook-before-drain, rollback that never truncates, frame-arity
drift, missing epoch fence — and asserts the pass finds exactly that
bug (and nothing else).  Negative twins prove the exemptions
(``__init__``, ``*_locked``, cv aliasing, waivers, timeouts) hold, and
the final test pins the real tree to zero errors/warnings so the gate
in tools/check.py can never rot silently.
"""

import os
import textwrap

from pathway_tpu.analysis.findings import Severity
from pathway_tpu.analysis.source import analyze_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _analyze(tmp_path, source: str, name: str = "mod.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    report = analyze_paths([str(f)], root=str(tmp_path))
    assert not report.internal_errors, report.internal_errors
    return report


def _codes(report) -> list[str]:
    return [f.code for f in report.findings]


class TestLockDiscipline:
    def test_unguarded_assign_pwc401(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: self._lock

                def put_ok(self, x):
                    with self._lock:
                        self._items = self._items + [x]

                def put_bad(self, x):
                    self._items = self._items + [x]
            """,
        )
        assert _codes(report) == ["PWC401"]
        (f,) = report.findings
        assert f.severity is Severity.ERROR
        assert "put_bad" not in f.message  # message names the attr, not fn
        assert "_items" in f.message and "self._lock" in f.message

    def test_unguarded_mutator_call_pwc401(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: self._lock

                def put(self, x):
                    self._items.append(x)
            """,
        )
        assert _codes(report) == ["PWC401"]
        assert "append" in report.findings[0].message

    def test_locked_suffix_methods_are_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: self._lock

                def _put_locked(self, x):
                    self._items.append(x)

                def put(self, x):
                    with self._lock:
                        self._put_locked(x)
            """,
        )
        assert report.findings == []

    def test_condition_aliases_with_wrapped_lock(self, tmp_path):
        # holding the Condition satisfies a guard on the inner lock
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self._items = []  # guarded-by: self._lock

                def put(self, x):
                    with self._cv:
                        self._items.append(x)
                        self._cv.notify()
            """,
        )
        assert report.findings == []

    def test_lock_order_cycle_pwc402(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Mesh:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def forward(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def reverse(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """,
        )
        assert _codes(report) == ["PWC402"]
        assert "deadlock" in report.findings[0].message

    def test_lock_order_cycle_through_call_pwc402(self, tmp_path):
        # the B-side acquisition hides one call level down
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Mesh:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def _bump(self):
                    with self._b_lock:
                        pass

                def forward(self):
                    with self._a_lock:
                        self._bump()

                def reverse(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """,
        )
        assert "PWC402" in _codes(report)

    def test_consistent_order_is_clean(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Mesh:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def one(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def two(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
            """,
        )
        assert report.findings == []

    def test_sleep_under_lock_pwc403(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading
            import time

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def poke(self):
                    with self._lock:
                        time.sleep(0.1)
            """,
        )
        assert _codes(report) == ["PWC403"]
        assert report.findings[0].severity is Severity.WARNING

    def test_unbounded_queue_get_under_lock_pwc403(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import queue
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def take_bad(self):
                    with self._lock:
                        return self._q.get()

                def take_ok(self):
                    with self._lock:
                        return self._q.get(timeout=0.5)
            """,
        )
        assert _codes(report) == ["PWC403"]

    def test_wait_on_held_cv_is_exempt_foreign_wait_is_not(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self._done = threading.Event()

                def wait_ok(self):
                    with self._cv:
                        self._cv.wait()

                def wait_bad(self):
                    with self._cv:
                        self._done.wait()
            """,
        )
        assert _codes(report) == ["PWC403"]
        assert "_done" in report.findings[0].message

    def test_pwc_ok_waiver_suppresses(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading
            import time

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def poke(self):
                    with self._lock:
                        time.sleep(0.1)  # pwc-ok: PWC403 settle before probe
            """,
        )
        assert report.findings == []

    def test_unbounded_daemon_loop_pwc404(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import queue
            import threading

            class Worker:
                def __init__(self):
                    self._q = queue.Queue()
                    self._t = threading.Thread(target=self._run, daemon=True)

                def _run(self):
                    while True:
                        item = self._q.get()
                        del item
            """,
        )
        assert _codes(report) == ["PWC404"]
        assert "shutdown" in report.findings[0].message

    def test_bounded_daemon_loop_is_clean(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import queue
            import threading

            class Worker:
                def __init__(self):
                    self._q = queue.Queue()
                    self._t = threading.Thread(target=self._run, daemon=True)

                def _run(self):
                    while True:
                        try:
                            item = self._q.get(timeout=0.25)
                        except queue.Empty:
                            continue
                        del item
            """,
        )
        assert report.findings == []

    def test_unknown_lock_annotation_pwc405(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0  # guarded-by: self._mu
            """,
        )
        assert _codes(report) == ["PWC405"]
        assert "_mu" in report.findings[0].message


class TestProtocolInvariants:
    def test_commit_hook_with_no_drain_pwc501(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            class Sched:
                def commit(self, n):
                    self.snapshots.on_commit(n)
            """,
        )
        assert _codes(report) == ["PWC501"]
        assert "no preceding" in report.findings[0].message

    def test_commit_hook_before_drain_pwc501(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            class Sched:
                def commit(self, n):
                    publish_on_commit(self, n)
                    self.pipeline.drain_until(n)
            """,
        )
        assert _codes(report) == ["PWC501"]
        assert "before the drain" in report.findings[0].message

    def test_drain_then_hook_is_clean(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            class Sched:
                def commit(self, n):
                    self.pipeline.drain_until(n)
                    self.snapshots.on_commit(n)
                    publish_on_commit(self, n)
            """,
        )
        assert report.findings == []

    def test_rollback_without_truncate_pwc502(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            class Store:
                def rollback_to(self, commit):
                    self.current = commit
            """,
        )
        assert _codes(report) == ["PWC502"]

    def test_rollback_reaching_truncate_via_call_is_clean(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            class Store:
                def _rewind(self, commit):
                    self.snapshots.truncate(commit)

                def rollback_to(self, commit):
                    self.current = commit
                    self._rewind(commit)
            """,
        )
        assert report.findings == []

    def test_frame_arity_drift_pwc503(self, tmp_path):
        # encoder ships 4 fields, decoder destructures 3
        report = _analyze(
            tmp_path,
            """\
            def announce(conn, epoch, commit, digest):
                conn.send(("round", epoch, commit, digest))

            def handle(conn):
                frame = conn.recv_frame()
                kind, epoch, commit = frame
                if kind == "round":
                    return epoch, commit
            """,
        )
        assert _codes(report) == ["PWC503"]
        assert "drift" in report.findings[0].message

    def test_decoder_reads_past_encoded_arity_pwc503(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            def announce(conn, epoch):
                conn.send(("cmd", epoch))

            def handle(conn):
                frame = conn.recv_frame()
                if frame[0] == "cmd":
                    return frame[5]
            """,
        )
        assert _codes(report) == ["PWC503"]
        assert "[5]" in report.findings[0].message

    def test_agreeing_arity_is_clean(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            def announce(conn, epoch, commit):
                conn.send(("round", epoch, commit))

            def handle(conn):
                frame = conn.recv_frame()
                kind, epoch, commit = frame
                if kind == "round":
                    return epoch, commit
            """,
        )
        assert report.findings == []

    def test_missing_epoch_fence_pwc504(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            def handle(frame, fence):
                if frame[0] == "elect":
                    return frame[1]
            """,
        )
        assert _codes(report) == ["PWC504"]
        assert "zombie" in report.findings[0].message

    def test_fenced_dispatch_is_clean(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            def handle(frame, fence):
                if frame[0] == "elect":
                    if not fence.admit("elect", frame[1]):
                        return None
                    return frame[1]
            """,
        )
        assert report.findings == []


class TestRealTree:
    def test_runtime_source_analyzes_clean(self):
        """The whole-tree gate tools/check.py enforces (`analyze --source
        --strict pathway_tpu/`): zero findings of ANY severity — info
        included, matching --strict — across every pass."""
        report = analyze_paths(
            [os.path.join(REPO, "pathway_tpu")], root=REPO
        )
        assert not report.internal_errors, report.internal_errors
        assert report.node_count > 20
        bad = [f.render() for f in report.findings]
        assert bad == [], "\n".join(bad)
