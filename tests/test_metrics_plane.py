"""Mesh-wide metrics plane: registry, exposition conformance, latency
histograms, leader-aggregated /metrics, flight recorder, shutdown hygiene
(reference: src/engine/http_server.rs:22-194, telemetry.rs:195-407)."""

from __future__ import annotations

import ast
import json
import os
import re
import socket
import sys
import textwrap
import threading
import time
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals.monitoring import (
    MonitoringHttpServer,
    MonitoringLevel,
    StatsMonitor,
)
from pathway_tpu.internals.parse_graph import G

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port_base(n: int) -> int:
    """A base port such that base..base+n-1 are currently bindable."""
    for _ in range(64):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
        probe.close()
        if base + n >= 65535:
            continue
        ok = True
        for i in range(n):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", base + i))
            except OSError:
                ok = False
                break
            finally:
                s.close()
        if ok:
            return base
    raise RuntimeError("no free port range found")


def _scrape(port: int) -> str:
    return (
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        )
        .read()
        .decode()
    )


class TestRegistry:
    def test_counter_gauge_histogram_series(self):
        r = _metrics.Registry()
        c = r.counter("reqs_total", "requests", route="/a")
        c.inc()
        c.inc(4)
        assert r.counter("reqs_total", route="/a") is c
        assert r.counter("reqs_total", route="/b") is not c
        g = r.gauge("depth")
        g.set(3.0)
        h = r.histogram("lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe_n(0.5, 3)
        h.observe(5.0)
        assert h.count == 5
        assert h.counts == [1, 3, 1]
        assert h.sum == pytest.approx(0.05 + 1.5 + 5.0)
        snap = r.snapshot()
        assert snap["reqs_total"]["kind"] == "counter"
        assert len(snap["reqs_total"]["series"]) == 2
        (hs,) = snap["lat"]["series"]
        assert hs["count"] == 5 and hs["counts"] == [1, 3, 1]

    def test_kind_conflict_raises(self):
        r = _metrics.Registry()
        r.counter("x_total")
        with pytest.raises(ValueError):
            r.gauge("x_total")

    def test_histogram_bounds_must_increase(self):
        with pytest.raises(ValueError):
            _metrics.Histogram((1.0, 1.0, 2.0))
        with pytest.raises(ValueError):
            _metrics.Histogram((2.0, 1.0))

    def test_quantile_interpolates(self):
        h = _metrics.Histogram((1.0, 2.0, 4.0))
        h.observe_n(0.5, 10)  # all in the first bucket
        assert 0.0 < h.quantile(0.5) <= 1.0
        h2 = _metrics.Histogram((1.0,))
        assert h2.quantile(0.99) == 0.0  # empty

    def test_observe_n_ignores_nonpositive(self):
        h = _metrics.Histogram((1.0,))
        h.observe_n(0.5, 0)
        h.observe_n(0.5, -3)
        assert h.count == 0

    def test_broken_collector_does_not_break_snapshot(self):
        r = _metrics.Registry()
        r.counter("ok_total").inc()

        def broken():
            raise RuntimeError("collector exploded")

        r.register_collector(broken)
        snap = r.snapshot()
        assert "ok_total" in snap


class TestExpositionConformance:
    def test_render_parse_roundtrip_with_hostile_labels(self):
        r = _metrics.Registry()
        hostile = 'we"ird\\name\nwith newline'
        r.counter("evil_total", "hostile labels", connector=hostile).inc(7)
        h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe_n(0.05, 3)
        text = _metrics.render_snapshots({"": r.snapshot()})
        families = _metrics.validate_exposition(text)
        (name, labels, value) = families["evil_total"]["samples"][0]
        assert labels["connector"] == hostile
        assert value == 7
        counts = {
            la["le"]: v
            for n, la, v in families["lat_seconds"]["samples"]
            if n.endswith("_bucket")
        }
        assert counts == {"0.1": 3, "1": 3, "+Inf": 3}

    def test_one_help_type_block_per_family_across_workers(self):
        r = _metrics.Registry()
        r.counter("shared_total", "shared").inc(1)
        snap = r.snapshot()
        text = _metrics.render_snapshots({"0": snap, "1": snap, "2": snap})
        assert text.count("# TYPE shared_total counter") == 1
        assert text.count("# HELP shared_total") == 1
        families = _metrics.validate_exposition(text)
        workers = {
            la["worker"] for _n, la, _v in families["shared_total"]["samples"]
        }
        assert workers == {"0", "1", "2"}

    def test_validate_rejects_malformed(self):
        with pytest.raises(ValueError):
            _metrics.validate_exposition("orphan_metric 1\n")
        with pytest.raises(ValueError):
            _metrics.validate_exposition(
                "# HELP h x\n# TYPE h histogram\n"
                'h_bucket{le="1"} 1\nh_sum 1\n'  # no +Inf, no _count
            )
        with pytest.raises(ValueError):
            _metrics.parse_prometheus_text("# COMMENT nope\n")
        with pytest.raises(ValueError):
            _metrics.parse_prometheus_text("# TYPE x frobnicator\n")

    def test_monitor_exposition_is_conformant(self):
        # the exchange counter family registers when the routing layer
        # loads — make sure it's present regardless of test ordering
        from pathway_tpu.engine import routing  # noqa: F401

        monitor = StatsMonitor(MonitoringLevel.IN_OUT)
        monitor.connector('fs:"quo\\ted"').entries = 3
        monitor.on_commit(1, time.monotonic())
        families = _metrics.validate_exposition(monitor.prometheus_text())
        assert "pathway_commits_total" in families
        assert "pathway_uptime_seconds" in families
        # registry families ride along under this process's worker label
        assert "pathway_exchange_events_total" in families
        assert "pathway_optimizer_chains_fused" in families
        names = {
            la.get("connector")
            for _n, la, _v in families["pathway_input_entries_total"][
                "samples"
            ]
        }
        assert 'fs:"quo\\ted"' in names


class TestRendererEdgeCases:
    """Renderer corner cases a live mesh produces: an empty registry
    snapshot, a counter only followers report, and a histogram family
    where one worker has observed nothing yet."""

    def test_empty_registry_snapshot_renders_empty(self):
        text = _metrics.render_snapshots({"": {}})
        assert text.strip() == ""
        assert _metrics.validate_exposition(text) == {}

    def test_follower_only_counter_keeps_one_help_type_block(self):
        def counter(value: float) -> dict:
            return {
                "kind": "counter",
                "help": "rows seen",
                "buckets": None,
                "series": [{"labels": {}, "value": value}],
            }

        # the leader ("") has never bumped this family — only followers
        text = _metrics.render_snapshots(
            {
                "": {},
                "1": {"rows_seen_total": counter(3.0)},
                "2": {"rows_seen_total": counter(4.0)},
            }
        )
        assert text.count("# HELP rows_seen_total") == 1
        assert text.count("# TYPE rows_seen_total counter") == 1
        families = _metrics.validate_exposition(text)
        samples = families["rows_seen_total"]["samples"]
        assert {la["worker"]: v for _n, la, v in samples} == {
            "1": 3.0,
            "2": 4.0,
        }

    def test_histogram_with_zero_observation_worker(self):
        def hist(counts: list, count: int, total: float) -> dict:
            return {
                "kind": "histogram",
                "help": "latency",
                "buckets": [0.1, 1.0],
                "series": [
                    {
                        "labels": {},
                        "counts": counts,
                        "sum": total,
                        "count": count,
                    }
                ],
            }

        text = _metrics.render_snapshots(
            {
                "0": {"lat_seconds": hist([1, 2], 5, 1.5)},
                "1": {"lat_seconds": hist([0, 0], 0, 0.0)},
            }
        )
        assert text.count("# HELP lat_seconds") == 1
        assert text.count("# TYPE lat_seconds histogram") == 1
        families = _metrics.validate_exposition(text)
        by_worker: dict = {}
        for n, la, v in families["lat_seconds"]["samples"]:
            by_worker.setdefault(la["worker"], {})[
                (n, la.get("le", ""))
            ] = v
        # the idle worker still renders a complete, all-zero series
        assert by_worker["1"][("lat_seconds_count", "")] == 0
        assert by_worker["1"][("lat_seconds_bucket", "+Inf")] == 0
        assert by_worker["0"][("lat_seconds_count", "")] == 5
        assert by_worker["0"][("lat_seconds_bucket", "0.1")] == 1
        assert by_worker["0"][("lat_seconds_bucket", "1")] == 3


class TestObservabilityFamilyConformance:
    """OpenMetrics conformance for the PR-15 observability families
    (profiler tick cost, timeseries tick cost): zero-observation and
    single-bucket renderings a live mesh produces, plus the ``cli
    stats`` profiler section fed by them."""

    PROFILE_BUCKETS = [1e-5, 1e-4, 1e-3, 1e-2, 0.1]
    TS_BUCKETS = [1e-4, 1e-3, 1e-2, 0.1, 1.0]

    @staticmethod
    def _hist(bounds, counts, count, total):
        return {
            "kind": "histogram",
            "help": "tick cost",
            "buckets": list(bounds),
            "series": [
                {
                    "labels": {},
                    "counts": list(counts),
                    "sum": total,
                    "count": count,
                }
            ],
        }

    def test_zero_observation_worker_renders_conformant(self):
        # worker 1 enabled the profiler but its sampler has not ticked
        # yet; worker 0's recorder loop is mid-run — one exposition
        text = _metrics.render_snapshots(
            {
                "0": {
                    "pathway_profile_sample_seconds": self._hist(
                        self.PROFILE_BUCKETS, [3, 2, 1, 0, 0], 6, 0.004
                    ),
                    "pathway_timeseries_tick_seconds": self._hist(
                        self.TS_BUCKETS, [5, 1, 0, 0, 0], 6, 0.001
                    ),
                },
                "1": {
                    "pathway_profile_sample_seconds": self._hist(
                        self.PROFILE_BUCKETS, [0] * 5, 0, 0.0
                    ),
                    "pathway_timeseries_tick_seconds": self._hist(
                        self.TS_BUCKETS, [0] * 5, 0, 0.0
                    ),
                },
            }
        )
        families = _metrics.validate_exposition(text)
        for fam_name in (
            "pathway_profile_sample_seconds",
            "pathway_timeseries_tick_seconds",
        ):
            assert text.count(f"# TYPE {fam_name} histogram") == 1
            by_worker: dict = {}
            for n, la, v in families[fam_name]["samples"]:
                by_worker.setdefault(la["worker"], {})[
                    (n, la.get("le", ""))
                ] = v
            # the idle worker's series is complete and all-zero
            assert by_worker["1"][(f"{fam_name}_count", "")] == 0
            assert by_worker["1"][(f"{fam_name}_sum", "")] == 0
            assert by_worker["1"][(f"{fam_name}_bucket", "+Inf")] == 0
            assert by_worker["0"][(f"{fam_name}_count", "")] == 6

    def test_single_bucket_histogram_conformant_and_quantiles(self):
        # a family whose whole distribution lands in one finite bucket
        text = _metrics.render_snapshots(
            {
                "0": {
                    "pathway_timeseries_tick_seconds": self._hist(
                        [0.01], [4], 4, 0.012
                    )
                }
            }
        )
        families = _metrics.validate_exposition(text)
        samples = families["pathway_timeseries_tick_seconds"]["samples"]
        les = [
            la["le"] for n, la, _v in samples if n.endswith("_bucket")
        ]
        assert les == ["0.01", "+Inf"]
        from pathway_tpu.cli import _hist_quantile

        # interpolated inside the lone finite bucket
        q = _hist_quantile([(0.01, 4.0), (float("inf"), 4.0)], 0.5)
        assert q == pytest.approx(0.005)
        # zero observations / +Inf-only: no fabricated number
        assert _hist_quantile([(0.01, 0.0), (float("inf"), 0.0)], 0.5) is None
        assert _hist_quantile([(float("inf"), 4.0)], 0.5) is None

    def test_cli_stats_renders_profiler_section(self, capsys):
        from pathway_tpu import cli

        _metrics.REGISTRY.counter(
            "pathway_profile_samples_total",
            "stack samples aggregated by the profiler",
        ).inc(12)
        _metrics.REGISTRY.gauge(
            "pathway_profile_rate_hz",
            "current (adaptive) profiler sampling rate",
        ).set(50.0)
        _metrics.REGISTRY.histogram(
            "pathway_profile_sample_seconds",
            "wall cost of one profiler sampling tick",
            buckets=tuple(self.PROFILE_BUCKETS),
        ).observe(5e-4)
        _metrics.REGISTRY.histogram(
            "pathway_timeseries_tick_seconds",
            "wall cost of one timeseries recording pass",
            buckets=tuple(self.TS_BUCKETS),
        ).observe(2e-3)
        monitor = StatsMonitor(MonitoringLevel.IN_OUT)
        server = MonitoringHttpServer(monitor, port=0)
        try:
            assert cli.main(["stats", str(server.port)]) == 0
        finally:
            server.stop()
        out = capsys.readouterr().out
        assert "profiler:" in out
        profiler = next(
            line for line in out.splitlines()
            if "samples=" in line and "tick_us" in line
        )
        assert "rate_hz=50.0" in profiler
        assert "tick_us: p50=" in profiler
        assert "p50=-" not in profiler  # a real per-tick cost estimate
        # both new families appear in the per-family percentile table
        # with their histogram percentile columns populated
        for fam_name in (
            "pathway_profile_sample_seconds",
            "pathway_timeseries_tick_seconds",
        ):
            row = next(
                line for line in out.splitlines()
                if line.startswith(fam_name)
            )
            assert "histogram" in row
            assert "-" not in row.split()[-3:]


class TestExchangeStatsAbsorption:
    def test_single_dict_alias_across_modules(self):
        from pathway_tpu.engine import distributed, routing, sharded

        assert routing.EXCHANGE_STATS is sharded.EXCHANGE_STATS
        assert routing.EXCHANGE_STATS is distributed.EXCHANGE_STATS

    def test_writes_mirror_into_registry_counter(self):
        from pathway_tpu.engine.routing import EXCHANGE_STATS

        # the mirrored series carry the delivery-path label alongside
        # the kind (elided / host / device / total)
        c = _metrics.REGISTRY.counter(
            "pathway_exchange_events_total", kind="elided", path="elided"
        )
        EXCHANGE_STATS["elided"] += 1
        assert c.value == float(EXCHANGE_STATS["elided"])
        EXCHANGE_STATS["elided"] += 2
        assert c.value == float(EXCHANGE_STATS["elided"])


class TestFlightRecorder:
    def test_ring_is_bounded_and_ordered(self):
        fr = _metrics.FlightRecorder(maxlen=4)
        for i in range(10):
            fr.record("commit", time=i)
        events = fr.snapshot()
        assert len(events) == 4
        assert [e["time"] for e in events] == [6, 7, 8, 9]
        assert [e["seq"] for e in events] == [7, 8, 9, 10]

    def test_dump_format(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PATHWAY_TPU_FLIGHT_DIR", str(tmp_path))
        fr = _metrics.FlightRecorder(maxlen=8)
        fr.record("error", message="boom")
        path = fr.dump("test reason")
        assert path is not None and os.path.exists(path)
        assert os.path.basename(path).startswith("pathway_flight_p")
        with open(path) as fh:
            payload = json.load(fh)
        assert payload["reason"] == "test reason"
        assert payload["pid"] == os.getpid()
        assert payload["events"][0]["kind"] == "error"
        assert payload["events"][0]["message"] == "boom"


class TestLiveScrapeSharded:
    def test_scrape_during_sharded_run(self):
        """The endpoint must serve conformant text WHILE a 2-worker
        sharded run is pumping commits, and the final scrape must carry
        the latency histogram with _count == output rows."""
        from pathway_tpu.internals.runner import ShardedGraphRunner

        G.clear()
        rows_out = []

        class Feed(pw.io.python.ConnectorSubject):
            def run(self):
                for i in range(40):
                    self.next(k=i % 4, v=i)
                    if i % 10 == 9:
                        self.commit()
                        time.sleep(0.05)

        t = pw.io.python.read(
            Feed(),
            schema=pw.schema_from_types(k=int, v=int),
            autocommit_duration_ms=None,
        )
        agg = t.groupby(pw.this.k).reduce(
            k=pw.this.k, s=pw.reducers.sum(pw.this.v)
        )
        # single sink: every row pathway_output_rows_total counts lands in
        # rows_out too, so the two tallies must match exactly
        pw.io.subscribe(
            agg,
            on_change=lambda key, row, time, is_addition: rows_out.append(
                row
            ),
        )

        out_before = _metrics.REGISTRY.counter(
            "pathway_output_rows_total"
        ).value
        hist = _metrics.REGISTRY.histogram(
            "pathway_ingest_to_sink_latency_seconds"
        )
        count_before = hist.count

        runner = ShardedGraphRunner(2)
        monitor = StatsMonitor(MonitoringLevel.ALL)
        runner.monitor = monitor
        runner.attach_sinks()
        server = MonitoringHttpServer(monitor, port=0)
        mid_run: list[str] = []
        done = threading.Event()

        def poll():
            while not done.is_set():
                try:
                    mid_run.append(_scrape(server.port))
                except Exception:
                    pass
                time.sleep(0.02)

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        try:
            runner.run()
            done.set()
            poller.join(timeout=5)
            final = _scrape(server.port)
        finally:
            done.set()
            server.stop()
            G.clear()
        assert mid_run, "no successful scrape during the run"
        _metrics.validate_exposition(mid_run[-1])
        families = _metrics.validate_exposition(final)
        out_rows = _metrics.REGISTRY.counter(
            "pathway_output_rows_total"
        ).value
        assert out_rows - out_before == len(rows_out) > 0
        assert hist.count - count_before == out_rows - out_before
        hist_counts = [
            v
            for n, _la, v in families[
                "pathway_ingest_to_sink_latency_seconds"
            ]["samples"]
            if n.endswith("_count")
        ]
        assert sum(hist_counts) == hist.count
        assert "pathway_operator_rows" in families
        assert "pathway_queue_depth" in families


class TestMeshAggregation:
    def test_leader_metrics_cover_all_workers(self, tmp_path):
        """3-process TCP mesh: one scrape of the LEADER endpoint reports
        per-worker-labelled operator counters for every process, and the
        ingest->sink latency histogram _count equals rows produced."""
        from pathway_tpu.cli import spawn

        indir = tmp_path / "in"
        indir.mkdir()
        words = [f"w{i % 17}" for i in range(300)]
        with open(indir / "words.csv", "w") as fh:
            fh.write("word\n")
            fh.writelines(f"{w}\n" for w in words)
        out = tmp_path / "out.csv"
        scrape_path = tmp_path / "scrape.txt"
        prog = tmp_path / "prog.py"
        prog.write_text(
            textwrap.dedent(
                """
                import os, urllib.request
                import pathway_tpu as pw

                pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
                port = int(os.environ["TEST_METRICS_PORT_BASE"]) + pid
                words = pw.io.csv.read(
                    {indir!r},
                    schema=pw.schema_from_types(word=str),
                    mode="static",
                )
                counts = words.groupby(pw.this.word).reduce(
                    word=pw.this.word, count=pw.reducers.count()
                )
                pw.io.csv.write(counts, {out!r})
                pw.run(
                    with_http_server=True,
                    monitoring_server_port=port,
                    _keep_http_server=True,
                )
                if pid == 0:
                    body = urllib.request.urlopen(
                        f"http://127.0.0.1:{{port}}/metrics", timeout=10
                    ).read().decode()
                    with open({scrape!r}, "w") as fh:
                        fh.write(body)
                """.format(
                    indir=str(indir),
                    out=str(out),
                    scrape=str(scrape_path),
                )
            )
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["TEST_METRICS_PORT_BASE"] = str(_free_port_base(3))
        env.pop("PATHWAY_PERSISTENT_STORAGE", None)
        rc = spawn(
            sys.executable,
            [str(prog)],
            threads=1,
            processes=3,
            first_port=_free_port_base(3),
            env=env,
        )
        assert rc == 0
        families = _metrics.validate_exposition(scrape_path.read_text())

        workers = {
            la.get("worker")
            for _n, la, _v in families["pathway_operator_rows"]["samples"]
            if "worker" in la
        }
        assert {"0", "1", "2"} <= workers, workers

        def worker0(family: str, suffix: str = "") -> float:
            return sum(
                v
                for n, la, v in families[family]["samples"]
                if la.get("worker") == "0"
                and (not suffix or n.endswith(suffix))
                and (suffix or n == family)
            )

        out_rows = worker0("pathway_output_rows_total")
        hist_count = worker0(
            "pathway_ingest_to_sink_latency_seconds", "_count"
        )
        with open(out) as fh:
            produced = sum(1 for _ in fh) - 1  # minus header
        assert out_rows == produced > 0
        assert hist_count == out_rows


class TestShutdownHygiene:
    def test_failing_run_leaks_nothing_and_dumps_flight(
        self, tmp_path, monkeypatch
    ):
        """A raising pw.run must stop the metrics sampler thread, release
        the HTTP port, and leave a flight-recorder JSON dump behind."""
        monkeypatch.setenv("PATHWAY_PROCESS_METRICS", "1")
        monkeypatch.setenv("PATHWAY_TELEMETRY_INTERVAL_S", "0.05")
        monkeypatch.setenv("PATHWAY_TPU_FLIGHT_DIR", str(tmp_path))
        G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(x=int), [(1,), (2,)]
        )

        def boom(key, row, time, is_addition):
            raise RuntimeError("sink exploded")

        pw.io.subscribe(t, on_change=boom)
        port = _free_port_base(1)
        with pytest.raises(RuntimeError, match="sink exploded"):
            pw.run(with_http_server=True, monitoring_server_port=port)
        # no leaked sampler thread
        leaked = [
            th
            for th in threading.enumerate()
            if th.name == "pw-telemetry" and th.is_alive()
        ]
        assert not leaked, leaked
        # port released: plain re-bind (no SO_REUSEADDR) must succeed
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", port))
        finally:
            s.close()
        # flight dump exists and records the failure
        dumps = [
            f
            for f in os.listdir(tmp_path)
            if f.startswith("pathway_flight_p") and f.endswith(".json")
        ]
        assert dumps, os.listdir(tmp_path)
        with open(tmp_path / dumps[0]) as fh:
            payload = json.load(fh)
        assert "sink exploded" in payload["reason"]
        kinds = [e["kind"] for e in payload["events"]]
        assert "run_start" in kinds
        assert "run_error" in kinds


class TestCliStats:
    def test_stats_pretty_prints_table(self, capsys):
        from pathway_tpu import cli

        _metrics.REGISTRY.counter("pathway_output_rows_total").inc(0)
        monitor = StatsMonitor(MonitoringLevel.IN_OUT)
        monitor.on_commit(1, time.monotonic())
        server = MonitoringHttpServer(monitor, port=0)
        try:
            assert cli.main(["stats", str(server.port)]) == 0
        finally:
            server.stop()
        out = capsys.readouterr().out
        assert "worker" in out
        assert "pathway_commits_total" in out

    def test_stats_raw_dumps_exposition(self, capsys):
        from pathway_tpu import cli

        monitor = StatsMonitor(MonitoringLevel.IN_OUT)
        server = MonitoringHttpServer(monitor, port=0)
        try:
            assert cli.main(["stats", "--raw", str(server.port)]) == 0
        finally:
            server.stop()
        out = capsys.readouterr().out
        _metrics.validate_exposition(out)

    def test_stats_unreachable_endpoint_exits_2(self):
        from pathway_tpu import cli

        port = _free_port_base(1)
        assert cli.main(["stats", "--timeout", "1", str(port)]) == 2


class TestNativeKernelTimers:
    def test_kernel_ns_mirrors_hit_counts(self):
        from pathway_tpu import native

        if not native.available():
            assert native.kernel_ns() == {}
            pytest.skip("native kernels unavailable")
        ns = native.kernel_ns()
        hits = native.hit_counts()
        assert set(ns) == set(hits)
        assert all(
            isinstance(v, int) and v >= 0 for v in ns.values()
        )

    def test_reset_zeroes_both(self):
        from pathway_tpu import native

        if not native.available():
            pytest.skip("native kernels unavailable")
        G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(a=int), [(i,) for i in range(200)]
        )
        r = t.select(b=pw.this.a + 1)
        pw.debug.compute_and_print(r, include_id=False)
        native.reset_hit_counts()
        assert sum(native.hit_counts().values()) == 0
        assert sum(native.kernel_ns().values()) == 0


# -- registry conformance over the whole tree ---------------------------------


class TestRegistryConformance:
    """Property test over the SOURCE tree: every `pathway_*` family any
    module registers must have exactly one kind, an OpenMetrics-safe
    name, and a help string at its first registration site."""

    _KINDS = ("counter", "gauge", "histogram")
    _NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

    def _instrument_calls(self):
        root = os.path.join(REPO, "pathway_tpu")
        out = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path, encoding="utf-8") as fh:
                    tree = ast.parse(fh.read(), filename=path)
                for node in ast.walk(tree):
                    if not (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in self._KINDS
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and node.args[0].value.startswith("pathway_")
                    ):
                        continue
                    has_help = (
                        len(node.args) > 1
                        and isinstance(node.args[1], ast.Constant)
                        and bool(node.args[1].value)
                    ) or any(
                        kw.arg == "help"
                        and isinstance(kw.value, ast.Constant)
                        and bool(kw.value.value)
                        for kw in node.keywords
                    )
                    out.append(
                        (
                            node.args[0].value,
                            node.func.attr,
                            os.path.relpath(path, REPO),
                            node.lineno,
                            has_help,
                        )
                    )
        return out

    def test_every_family_has_exactly_one_kind(self):
        calls = self._instrument_calls()
        assert len(calls) >= 10, "AST scan found too few instrument sites"
        kinds: dict = {}
        for name, kind, path, lineno, _help in calls:
            kinds.setdefault(name, {}).setdefault(kind, []).append(
                f"{path}:{lineno}"
            )
        conflicts = {
            name: sites for name, sites in kinds.items() if len(sites) > 1
        }
        assert not conflicts, (
            f"metric families registered under multiple kinds: {conflicts}"
        )

    def test_every_family_name_is_openmetrics_safe(self):
        for name, _kind, path, lineno, _help in self._instrument_calls():
            assert self._NAME_RE.match(name), f"{path}:{lineno}: {name!r}"
            assert not name.endswith(("_bucket", "_sum", "_count")), (
                f"{path}:{lineno}: {name!r} collides with histogram "
                "sample suffixes"
            )

    def test_every_family_renders_valid_exposition(self):
        calls = self._instrument_calls()
        reg = _metrics.Registry()
        made: set = set()
        for name, kind, _path, _lineno, _help in calls:
            if name in made:
                continue
            made.add(name)
            handle = getattr(reg, kind)(name, "conformance probe")
            if kind == "counter":
                handle.inc()
            elif kind == "gauge":
                handle.set(1.0)
            else:
                handle.observe(0.5)
        text = _metrics.render_snapshots({"": reg.snapshot()})
        families = _metrics.validate_exposition(text)
        assert set(families) == made

    def test_at_least_one_site_passes_help(self):
        by_name: dict = {}
        for name, _kind, _path, _lineno, has_help in self._instrument_calls():
            by_name[name] = by_name.get(name, False) or has_help
        missing = sorted(n for n, ok in by_name.items() if not ok)
        assert not missing, (
            f"families never registered with a help string: {missing}"
        )
