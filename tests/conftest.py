import os

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
# exercised without TPU hardware (bench.py runs on the real chip).
os.environ["JAX_PLATFORMS"] = "cpu"
# the parsers' default vision seam compiles a ViT; the tiny preset keeps
# CPU test runs fast while exercising the identical code path
os.environ.setdefault("PATHWAY_VISION_PRESET", "vit-tiny")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize pins jax to the accelerator plugin regardless of
# the env var; override at the config level too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running chaos soak / scale tests excluded from tier-1",
    )
