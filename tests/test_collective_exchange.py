"""Collective exchange (engine/collective_exchange.py): parity corpus.

``PATHWAY_TPU_COLLECTIVE_EXCHANGE=1`` forces every codeable repartition
through the shard_map + all_to_all kernel and ``=0`` pins routing.py's
host path; the two runs must be bit-identical — sink values, diffs,
error logs and checkpoint round trips — on the in-process sharded
scheduler and the single-process distributed scheduler (the same
discipline tests/test_device_ops.py applies to the operator kernels).
The corpus deliberately includes retractions, NaN float keys and
values, empty commits, cancelling delta batches, skewed
all-rows-to-one-shard batches, non-codeable (object dtype) columns
declining to host, and a chaos leg that kills the device kernel
mid-collective and recovers through the decline-to-host (PR-6
rollback) seam.  A cross-check test asserts the EXCHANGE_STATS
delivery-plane invariant: elided + host + collective == repartitions.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

pytest.importorskip("jax")

import pathway_tpu as pw
from pathway_tpu.engine import collective_exchange as cx
from pathway_tpu.engine import routing
from pathway_tpu.engine.graph import Scope
from pathway_tpu.engine.persistence import (
    MemoryBackend,
    OperatorSnapshotManager,
)
from pathway_tpu.engine.reducers import CountReducer, SumReducer
from pathway_tpu.engine.sharded import ShardedScheduler
from pathway_tpu.engine.value import ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner, ShardedGraphRunner
from pathway_tpu.stdlib.indexing import DataIndex, TpuKnnFactory

N_WORKERS = 4  # conftest forces 8 host-platform sim devices — mesh_ready


def _set(monkeypatch, on: bool) -> None:
    monkeypatch.setenv(
        "PATHWAY_TPU_COLLECTIVE_EXCHANGE", "1" if on else "0"
    )


def _canon(obj):
    """NaN-safe, ndarray-safe canonical form for equality asserts."""
    if isinstance(obj, np.ndarray):
        obj = obj.tolist()
    if isinstance(obj, (list, tuple)):
        return tuple(_canon(x) for x in obj)
    if isinstance(obj, float) and obj != obj:
        return "NaN"
    return obj


# -- env contract + mesh detection -------------------------------------------


def test_enabled_env_contract(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_COLLECTIVE_EXCHANGE", "0")
    assert not cx.enabled() and not cx.forced()
    monkeypatch.setenv("PATHWAY_TPU_COLLECTIVE_EXCHANGE", "off")
    assert not cx.enabled()
    monkeypatch.setenv("PATHWAY_TPU_COLLECTIVE_EXCHANGE", "1")
    assert cx.enabled() and cx.forced()
    monkeypatch.setenv("PATHWAY_TPU_COLLECTIVE_EXCHANGE", "force")
    assert cx.enabled() and cx.forced()
    # auto on the CPU sim backend: never silently re-route through
    # jax-on-CPU (the host path is cheaper than a fake collective)
    monkeypatch.delenv("PATHWAY_TPU_COLLECTIVE_EXCHANGE", raising=False)
    assert not cx.enabled()


def test_mesh_ready_needs_one_device_per_shard():
    assert not cx.mesh_ready(0)
    assert not cx.mesh_ready(1)  # nothing to exchange
    assert cx.mesh_ready(N_WORKERS)  # 8 sim devices cover 4 shards
    assert not cx.mesh_ready(4096)


def test_min_rows_env(monkeypatch):
    monkeypatch.delenv("PATHWAY_TPU_COLLECTIVE_MIN_ROWS", raising=False)
    assert cx.min_rows() == 512
    monkeypatch.setenv("PATHWAY_TPU_COLLECTIVE_MIN_ROWS", "7")
    assert cx.min_rows() == 7
    monkeypatch.setenv("PATHWAY_TPU_COLLECTIVE_MIN_ROWS", "bogus")
    assert cx.min_rows() == 512


# -- framework parity corpus --------------------------------------------------


def _corpus():
    def groupby_int():
        # int keys: digests + int64/float64 columns — fully codeable,
        # the collective carries every repartition in forced mode
        t = pw.debug.table_from_rows(
            pw.schema_from_types(k=int, v=int, w=float),
            [(i % 7, i, i * 0.25) for i in range(400)],
        )
        sel = t.select(k=t.k, v=t.v * 2 + 1, w=t.w)
        flt = sel.filter(sel.v > 7)
        return flt.groupby(flt.k).reduce(
            k=flt.k,
            total=pw.reducers.sum(flt.v),
            wsum=pw.reducers.sum(flt.w),
            cnt=pw.reducers.count(),
        )

    def join_int():
        orders = pw.debug.table_from_rows(
            pw.schema_from_types(oid=int, cust=int, amount=float),
            [(i, i % 9, float(i) * 1.5) for i in range(280)],
        )
        custs = pw.debug.table_from_rows(
            pw.schema_from_types(cid=int, region=int),
            [(i, i % 2) for i in range(9)],
        )
        j = orders.join(custs, orders.cust == custs.cid)
        return j.select(
            cust=orders.cust, region=custs.region, amount=orders.amount
        )

    def join_groupby_skew():
        # every order lands on ONE customer key: the all-to-all sees one
        # full bucket and n-1 empty ones on the skewed edge
        orders = pw.debug.table_from_rows(
            pw.schema_from_types(oid=int, cust=int, amount=float),
            [(i, 3, float(i)) for i in range(300)],
        )
        custs = pw.debug.table_from_rows(
            pw.schema_from_types(cid=int, region=int),
            [(i, i % 2) for i in range(4)],
        )
        j = orders.join(custs, orders.cust == custs.cid).select(
            region=custs.region, amount=orders.amount
        )
        return j.groupby(j.region).reduce(
            region=j.region,
            total=pw.reducers.sum(j.amount),
            cnt=pw.reducers.count(),
        )

    def groupby_str():
        # str keys columnarize as fixed-width numpy unicode — raw-byte
        # codeable, so the collective carries them like numerics
        t = pw.debug.table_from_rows(
            pw.schema_from_types(k=str, v=int),
            [(f"k{i % 5}", i) for i in range(300)],
        )
        return t.groupby(t.k).reduce(
            k=t.k, total=pw.reducers.sum(t.v), cnt=pw.reducers.count()
        )

    def knn():
        docs = pw.debug.table_from_rows(
            pw.schema_from_types(doc=int, emb=tuple),
            [
                (i, tuple(float((i * 7 + j * 3) % 13 - 6) for j in range(4)))
                for i in range(40)
            ],
        )
        queries = pw.debug.table_from_rows(
            pw.schema_from_types(q=int, qemb=tuple),
            [
                (i, tuple(float((i * 5 + j) % 13 - 6) for j in range(4)))
                for i in range(9)
            ],
        )
        index = DataIndex(
            docs, TpuKnnFactory(dimensions=4, capacity=8), docs.emb
        )
        return index.query_as_of_now(
            queries, queries.qemb, number_of_matches=3
        )

    return {
        "groupby_int": groupby_int,
        "join_int": join_int,
        "join_groupby_skew": join_groupby_skew,
        "groupby_str": groupby_str,
        "knn": knn,
    }


def _capture(build, runner_factory, monkeypatch, on):
    _set(monkeypatch, on)
    G.clear()
    try:
        (state,) = runner_factory().capture(build())
    finally:
        G.clear()
    return {k: _canon(v) for k, v in state.items()}


@pytest.mark.parametrize("name", sorted(_corpus()))
def test_sharded_parity(name, monkeypatch):
    build = _corpus()[name]
    cx.reset_counters()
    off = _capture(
        build, lambda: ShardedGraphRunner(N_WORKERS), monkeypatch, False
    )
    assert cx.COLLECTIVE_STATS["exchanges"] == 0  # off run stayed host
    on = _capture(
        build, lambda: ShardedGraphRunner(N_WORKERS), monkeypatch, True
    )
    assert off == on
    if name != "knn":  # knn edges route via pin/entry, not columnar
        assert cx.COLLECTIVE_STATS["exchanges"] > 0  # non-vacuous


@pytest.mark.parametrize("name", ["groupby_int", "join_int"])
def test_sharded_matches_single_worker(name, monkeypatch):
    build = _corpus()[name]
    base = _capture(build, GraphRunner, monkeypatch, False)
    on = _capture(
        build, lambda: ShardedGraphRunner(N_WORKERS), monkeypatch, True
    )
    assert base == on


# -- raw-scope corpus: retractions, NaN, cancelling batches -------------------


def _build_scopes(n_workers):
    scopes, sessions, aggs = [], [], []
    for _w in range(n_workers):
        sc = Scope()
        sess = sc.input_session(3)
        agg = sc.group_by_table(
            sess,
            by_cols=[0],
            reducers=[
                (SumReducer(), [1]),
                (SumReducer(), [2]),
                (CountReducer(), []),
            ],
        )
        scopes.append(sc)
        sessions.append(sess)
        aggs.append(agg)
    return scopes, sessions, aggs


def _feed(sess, sched, nan_keys=False, nan_vals=False):
    live = {}

    def key(i):
        if nan_keys and i % 97 == 0:
            return float("nan")
        return float(i % 7) if nan_keys else i % 7

    def ins(i, row):
        live[i] = row
        sess.insert(ref_scalar(i), row)

    def rm(i):
        sess.remove(ref_scalar(i), live.pop(i))

    for i in range(600):
        v = float("nan") if nan_vals and i % 89 == 0 else i * 0.5
        ins(i, (key(i), i, v))
    sched.commit()
    for i in range(100, 150):  # retract + reinsert modified
        rm(i)
        ins(i, (key(i), i + 1000, i * 0.25))
    sched.commit()
    sched.commit()  # empty commit
    ins(10_000, (key(3), 1, 1.0))  # cancelling batch: net-zero delta
    rm(10_000)
    sched.commit()
    for i in [k for k in list(live) if _canon(live[k][0]) == _canon(key(6))]:
        rm(i)  # retract an entire group to extinction
    sched.commit()


def _run_sharded(on, monkeypatch, nan_keys=False, nan_vals=False):
    _set(monkeypatch, on)
    scopes, sessions, aggs = _build_scopes(N_WORKERS)
    sched = ShardedScheduler(scopes)
    _feed(sessions[0], sched, nan_keys=nan_keys, nan_vals=nan_vals)
    merged = {}
    for agg in aggs:
        merged.update(agg.current)
    return {k: _canon(v) for k, v in merged.items()}


def test_raw_scope_retraction_parity(monkeypatch):
    cx.reset_counters()
    off = _run_sharded(False, monkeypatch)
    assert cx.COLLECTIVE_STATS["exchanges"] == 0
    on = _run_sharded(True, monkeypatch)
    assert off == on
    assert cx.COLLECTIVE_STATS["exchanges"] > 0


def test_raw_scope_nan_key_parity(monkeypatch):
    # NaN float keys stay vectorized in routing (fixed bit pattern), so
    # the payload is codeable and the collective still engages
    cx.reset_counters()
    off = _run_sharded(False, monkeypatch, nan_keys=True)
    on = _run_sharded(True, monkeypatch, nan_keys=True)
    assert off == on
    assert cx.COLLECTIVE_STATS["exchanges"] > 0
    assert any("NaN" in repr(k) for k in (repr(off),))  # corpus non-vacuous


def test_raw_scope_nan_value_parity(monkeypatch):
    off = _run_sharded(False, monkeypatch, nan_vals=True)
    on = _run_sharded(True, monkeypatch, nan_vals=True)
    assert off == on
    assert any("NaN" in repr(v) for v in off.values())


# -- error-log parity ---------------------------------------------------------


def test_error_log_parity(monkeypatch):
    from pathway_tpu.engine import expression as ex
    from pathway_tpu.engine.graph import Scheduler

    def run(on):
        _set(monkeypatch, on)
        scopes, logs, aggs = [], [], []
        for _w in range(N_WORKERS):
            sc = Scope()
            sess = sc.input_session(2)
            e1 = sc.expression_table(
                sess,
                [
                    ex.Binary("%", ex.ColumnRef(0), ex.Const(5)),
                    # 1/x poisons x == 0 rows with ERROR
                    ex.Binary("/", ex.Const(1.0), ex.ColumnRef(1)),
                ],
            )
            gb = sc.group_by_table(
                e1,
                by_cols=[0],
                reducers=[(SumReducer(), [1]), (CountReducer(), [])],
            )
            scopes.append(sc)
            logs.append(sc.error_log_default)
            aggs.append(gb)
            if _w == 0:
                feed = sess
        sched = ShardedScheduler(scopes)
        for i in range(400):
            feed.insert(ref_scalar(i), (i, float(i % 5)))
        sched.commit()
        log = sorted(
            entry for lg in logs for entry in lg.current.values()
        )
        merged = {}
        for agg in aggs:
            merged.update(agg.current)
        return {k: _canon(v) for k, v in merged.items()}, log

    cur_off, log_off = run(False)
    cur_on, log_on = run(True)
    assert cur_off == cur_on
    assert log_off == log_on
    assert log_on  # the corpus actually exercised the error path


def test_object_column_declines_to_host(monkeypatch):
    """A mixed-type value column columnarizes as object dtype — not
    raw-byte codeable — so the payload packer declines and the host path
    must deliver bit-identically (declined_non_codeable ticks)."""

    def run(on):
        _set(monkeypatch, on)
        scopes, sessions, aggs = [], [], []
        for _w in range(N_WORKERS):
            sc = Scope()
            sess = sc.input_session(2)
            agg = sc.group_by_table(
                sess, by_cols=[0], reducers=[(CountReducer(), [])]
            )
            scopes.append(sc)
            sessions.append(sess)
            aggs.append(agg)
        sched = ShardedScheduler(scopes)
        for i in range(300):
            v = i if i % 2 else f"s{i}"  # mixed types -> object column
            sessions[0].insert(ref_scalar(i), (i % 7, v))
        sched.commit()
        merged = {}
        for agg in aggs:
            merged.update(agg.current)
        return {k: _canon(v) for k, v in merged.items()}

    cx.reset_counters()
    off = run(False)
    assert cx.COLLECTIVE_STATS["declined_non_codeable"] == 0  # off: no consult
    on = run(True)
    assert off == on
    assert cx.COLLECTIVE_STATS["declined_non_codeable"] > 0


# -- chaos: kernel dies mid-collective ----------------------------------------


def test_kernel_failure_declines_to_host(monkeypatch):
    """A device error mid-collective performs NO pushes; the caller's
    host path delivers the whole batch (the PR-6 rollback seam), so the
    run completes bit-identically with the errors counter ticking."""
    cx.reset_counters()
    off = _run_sharded(False, monkeypatch)

    def boom(n):
        def dead_kernel(payload, gidx):
            raise RuntimeError("simulated worker loss mid-collective")

        return dead_kernel

    monkeypatch.setattr(cx, "_kernel", boom)
    chaos = _run_sharded(True, monkeypatch)
    assert chaos == off
    assert cx.COLLECTIVE_STATS["errors"] > 0
    assert cx.COLLECTIVE_STATS["exchanges"] == 0  # nothing half-delivered


# -- EXCHANGE_STATS delivery-plane invariant ----------------------------------


def test_exchange_stats_path_invariant(monkeypatch):
    """Every repartition decision lands on exactly one delivery plane:
    elided + host_deliveries + collective_deliveries == repartitions."""
    stats = routing.EXCHANGE_STATS
    for on in (False, True):
        before = {
            k: stats[k]
            for k in (
                "elided",
                "host_deliveries",
                "collective_deliveries",
                "repartitions",
            )
        }
        _run_sharded(on, monkeypatch)
        delta = {k: stats[k] - before[k] for k in before}
        assert delta["repartitions"] > 0
        assert (
            delta["elided"]
            + delta["host_deliveries"]
            + delta["collective_deliveries"]
            == delta["repartitions"]
        )
        if on:
            assert delta["collective_deliveries"] > 0
        else:
            assert delta["collective_deliveries"] == 0


def test_exchange_stats_invariant_with_elision(monkeypatch):
    """The invariant holds when the optimizer elides edges too — the
    framework runner's elision plane increments `elided`, never `host`
    or `collective`."""
    stats = routing.EXCHANGE_STATS
    before = {
        k: stats[k]
        for k in (
            "elided",
            "host_deliveries",
            "collective_deliveries",
            "repartitions",
        )
    }
    _capture(
        _corpus()["groupby_int"],
        lambda: ShardedGraphRunner(N_WORKERS),
        monkeypatch,
        True,
    )
    delta = {k: stats[k] - before[k] for k in before}
    assert delta["repartitions"] > 0
    assert (
        delta["elided"]
        + delta["host_deliveries"]
        + delta["collective_deliveries"]
        == delta["repartitions"]
    )


# -- checkpoint round trips across modes --------------------------------------


class TestCheckpointCompat:
    """The exchange plane is a runtime decision, not graph structure: a
    snapshot taken with the collective forced must restore under a
    host-only run (and vice versa) with identical state."""

    def _snap(self, on, backend, monkeypatch, restore_only=False):
        _set(monkeypatch, on)
        scopes, sessions, aggs = _build_scopes(N_WORKERS)
        mgr = OperatorSnapshotManager(backend)
        if restore_only:
            restored = mgr.restore(scopes, [])
            assert restored is not None
            merged = {}
            for agg in aggs:
                merged.update(agg.current)
            return merged
        sched = ShardedScheduler(scopes)
        for i in range(600):
            sessions[0].insert(ref_scalar(i), (i % 7, i, i * 0.5))
        sched.commit()
        for i in range(100, 150):
            sessions[0].remove(ref_scalar(i), (i % 7, i, i * 0.5))
        sched.commit()
        mgr.snapshot(scopes, [], sched.time)
        merged = {}
        for agg in aggs:
            merged.update(agg.current)
        return merged

    @pytest.mark.parametrize(
        "snap_on,restore_on", [(True, False), (False, True)]
    )
    def test_cross_restore(self, snap_on, restore_on, monkeypatch):
        backend = MemoryBackend()
        live = self._snap(snap_on, backend, monkeypatch)
        restored = self._snap(
            restore_on, backend, monkeypatch, restore_only=True
        )
        assert {k: _canon(v) for k, v in restored.items()} == {
            k: _canon(v) for k, v in live.items()
        }


# -- single-process distributed scheduler -------------------------------------


def test_distributed_single_process_collective(monkeypatch):
    """A single-process DistributedScheduler (all destination workers
    process-local) routes columnar repartitions through the collective;
    parity vs the host path and the engagement counter both hold."""
    from pathway_tpu.engine import distributed as dist

    def run(on):
        _set(monkeypatch, on)
        scopes, sessions, aggs = [], [], []
        for _w in range(2):
            sc = Scope()
            sess = sc.input_session(2)
            agg = sc.group_by_table(
                sess,
                by_cols=[0],
                reducers=[(SumReducer(), [1]), (CountReducer(), [])],
            )
            scopes.append(sc)
            sessions.append(sess)
            aggs.append(agg)
        transport = dist.MeshTransport(0, 1, addresses=[("127.0.0.1", 0)])
        try:
            sched = dist.DistributedScheduler(
                scopes, 0, 1, transport, n_shared=len(scopes[0].nodes)
            )
            sched.announce_topology()
            for i in range(500):
                sessions[0].insert(ref_scalar(i), (i % 13, float(i)))
            sched.commit_local()
            for i in range(50, 80):
                sessions[0].remove(ref_scalar(i), (i % 13, float(i)))
            sched.commit_local()
        finally:
            transport.close()
        merged = {}
        for agg in aggs:
            merged.update(agg.current)
        return {k: _canon(v) for k, v in merged.items()}

    cx.reset_counters()
    off = run(False)
    assert cx.COLLECTIVE_STATS["exchanges"] == 0
    on = run(True)
    assert off == on
    assert cx.COLLECTIVE_STATS["exchanges"] > 0


# -- counters + stats shape ---------------------------------------------------


def test_stats_shape(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_COLLECTIVE_EXCHANGE", "1")
    cx.reset_counters()
    s = cx.stats()
    assert s["enabled"] is True and s["forced"] is True
    assert s["events"] == {
        "exchanges": 0,
        "declined_non_codeable": 0,
        "errors": 0,
    }
    assert s["ns_total"] == 0 and s["bytes_total"] == 0
    assert "placement" in s


def test_metric_families_registered(monkeypatch):
    from pathway_tpu.internals import metrics as m

    cx.reset_counters()
    _run_sharded(True, monkeypatch)
    snap = m.REGISTRY.snapshot()
    assert "pathway_collective_exchange_events_total" in snap
    assert "pathway_collective_exchange_ns_total" in snap
    assert "pathway_collective_exchange_bytes_total" in snap
    # the path label distinguishes delivery planes on the exchange family
    paths = {
        s["labels"].get("path")
        for s in snap["pathway_exchange_events_total"]["series"]
    }
    assert {"device", "host", "elided", "total"} <= paths
