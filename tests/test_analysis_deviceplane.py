"""Seeded-bug corpus for the device-plane discipline pass (PWD6xx).

Each test writes a small module with ONE deliberately planted violation
from the classes the analyzer polices — implicit sync in a hot path,
branch-on-traced-shape, uncounted transfer, partial push on a
decline/except path, unregistered resident state, import-cached live
flag, metric-family drift — and asserts the pass reports exactly that
code at the right line (and nothing else).  Negative twins prove the
exemptions (materialize/fetch helpers, counted functions, static config
branches, registered classes, startup flags, consistent re-registration)
and the ``# pwd-ok`` waivers hold, and the final tests pin the real tree
to strict zero so the tools/check.py gates can never rot silently.
"""

import json
import os
import textwrap

from pathway_tpu.analysis.findings import Severity
from pathway_tpu.analysis.source import analyze_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _analyze(tmp_path, source: str, name: str = "mod.py"):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    report = analyze_paths([str(f)], root=str(tmp_path))
    assert not report.internal_errors, report.internal_errors
    return report


def _codes(report) -> list[str]:
    return [f.code for f in report.findings]


def _line_of(source: str, needle: str) -> int:
    for i, line in enumerate(textwrap.dedent(source).splitlines(), start=1):
        if needle in line:
            return i
    raise AssertionError(f"needle {needle!r} not in source")


class TestHotPathSync:
    SRC_FLOAT = """\
        import jax.numpy as jnp

        def process(self, batch):
            acc = jnp.sum(batch)
            return float(acc)
        """

    def test_float_on_jnp_value_pwd601(self, tmp_path):
        report = _analyze(tmp_path, self.SRC_FLOAT)
        assert _codes(report) == ["PWD601"]
        (f,) = report.findings
        assert f.severity is Severity.WARNING
        assert f.node_index == _line_of(self.SRC_FLOAT, "float(acc)")
        assert "acc" in f.message and "process" in f.message

    def test_item_in_exchange_path_pwd601(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import jax.numpy as jnp

            def exchange_totals(rows):
                total = jnp.max(rows)
                return total.item()
            """,
        )
        assert _codes(report) == ["PWD601"]
        assert ".item()" in report.findings[0].message

    def test_materialize_helper_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import jax.numpy as jnp

            def materialize_totals(rows):
                total = jnp.max(rows)
                return total.item()
            """,
        )
        assert _codes(report) == []

    def test_counted_fetch_exempt(self, tmp_path):
        # a hot-path function that touches the transfer ledger is an
        # explicit counted fetch — PWD603's jurisdiction, not PWD601's
        report = _analyze(
            tmp_path,
            """\
            import numpy as np
            import jax.numpy as jnp

            def exchange(rows):
                out = jnp.cumsum(rows)
                fetched = np.asarray(out)
                record_d2h(fetched.nbytes)
                return fetched
            """,
        )
        assert _codes(report) == []

    def test_pwd_ok_waiver(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import jax.numpy as jnp

            def process(self, batch):
                acc = jnp.sum(batch)
                return float(acc)  # pwd-ok: PWD601 per-commit readback
            """,
        )
        assert _codes(report) == []
        assert [f.code for f in report.waived] == ["PWD601"]
        assert report.waived[0].waived is True


class TestRecompileHazard:
    SRC_SHAPE = """\
        import jax

        def _kernel(x):
            if x.shape[0] > 8:
                return x * 2
            return x

        compiled = jax.jit(_kernel)
        """

    def test_shape_branch_in_jitted_fn_pwd602(self, tmp_path):
        report = _analyze(tmp_path, self.SRC_SHAPE)
        assert _codes(report) == ["PWD602"]
        (f,) = report.findings
        assert f.severity is Severity.ERROR
        assert f.node_index == _line_of(self.SRC_SHAPE, "if x.shape[0]")
        assert "shape" in f.message

    def test_value_branch_under_decorator_pwd602(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import jax

            @jax.jit
            def clip(v):
                if v > 0:
                    return v
                return -v
            """,
        )
        assert _codes(report) == ["PWD602"]
        assert "value" in report.findings[0].message

    def test_python_loop_over_param_bound_pwd602(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            from functools import partial
            import jax

            @partial(jax.jit, static_argnums=())
            def fold(xs, n):
                acc = 0
                for i in range(n):
                    acc = acc + xs[i]
                return acc
            """,
        )
        assert _codes(report) == ["PWD602"]
        assert "fori_loop" in report.findings[0].message

    def test_shard_map_wrapped_fn_pwd602(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            def bucket(payload):
                if len(payload) > 4:
                    return payload
                return payload

            def build(shard_map):
                return shard_map(bucket)
            """,
        )
        assert _codes(report) == ["PWD602"]

    def test_static_config_branch_exempt(self, tmp_path):
        # comparisons against string constants / None are static config,
        # and untraced functions may branch on anything
        report = _analyze(
            tmp_path,
            """\
            import jax

            @jax.jit
            def reduce_op(x, op):
                if op == "sum":
                    return x.sum()
                if x is None:
                    return x
                return x.max()

            def host_side(x):
                if x.shape[0] > 8:
                    return x * 2
                return x
            """,
        )
        assert _codes(report) == []


class TestUncountedTransfer:
    SRC_PUT = """\
        import jax

        def upload(batch):
            return jax.device_put(batch)
        """

    def test_device_put_without_ledger_pwd603(self, tmp_path):
        report = _analyze(tmp_path, self.SRC_PUT, name="engine/mod.py")
        assert _codes(report) == ["PWD603"]
        (f,) = report.findings
        assert f.severity is Severity.ERROR
        assert f.node_index == _line_of(self.SRC_PUT, "device_put")
        assert "record_h2d" in f.message

    def test_materialization_without_ledger_pwd603(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import numpy as np
            import jax.numpy as jnp

            def download(out):
                dev = jnp.dot(out, out)
                return np.asarray(dev)
            """,
            name="engine/mod.py",
        )
        assert _codes(report) == ["PWD603"]

    def test_outside_engine_exempt(self, tmp_path):
        report = _analyze(tmp_path, self.SRC_PUT, name="tools/mod.py")
        assert _codes(report) == []

    def test_counted_in_same_function_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import jax

            def upload(batch, _dres):
                _dres.record_h2d(batch.nbytes)
                return jax.device_put(batch)
            """,
            name="engine/mod.py",
        )
        assert _codes(report) == []

    def test_counted_via_local_helper_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import jax

            def _count(nbytes):
                record_h2d(nbytes)

            def upload(batch):
                _count(batch.nbytes)
                return jax.device_put(batch)
            """,
            name="engine/mod.py",
        )
        assert _codes(report) == []

    def test_jitted_body_exempt(self, tmp_path):
        # jnp calls inside a traced function are staged ops, not transfers
        report = _analyze(
            tmp_path,
            """\
            import jax
            import jax.numpy as jnp

            @jax.jit
            def kernel(x):
                return jnp.asarray(x) * 2
            """,
            name="engine/mod.py",
        )
        assert _codes(report) == []

    def test_pwd_ok_waiver(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import jax

            def upload(batch):
                return jax.device_put(batch)  # pwd-ok: PWD603 test rig
            """,
            name="engine/mod.py",
        )
        assert _codes(report) == []
        assert [f.code for f in report.waived] == ["PWD603"]


class TestPartialPush:
    SRC_EXCEPT = """\
        def deliver_parts(consumer, parts, pack):
            try:
                payload = pack(parts)
            except ValueError:
                consumer.push(parts)
                return None
            return payload
        """

    def test_push_on_except_path_pwd604(self, tmp_path):
        report = _analyze(tmp_path, self.SRC_EXCEPT)
        assert _codes(report) == ["PWD604"]
        (f,) = report.findings
        assert f.severity is Severity.ERROR
        assert f.node_index == _line_of(self.SRC_EXCEPT, "consumer.push(parts)")
        assert "except path" in f.message

    def test_push_after_decline_counter_pwd604(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            STATS = {}

            def run(consumer, stats, parts):
                stats["declined_non_codeable"] += 1
                consumer.push(parts)
            """,
            name="exchange.py",
        )
        assert _codes(report) == ["PWD604"]
        assert "decline path" in report.findings[0].message

    def test_materialize_before_push_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import numpy as np

            def deliver_parts(consumer, parts, pack):
                try:
                    payload = pack(parts)
                except ValueError:
                    whole = np.asarray(parts)
                    consumer.push(whole)
                    return None
                return payload
            """,
        )
        assert _codes(report) == []

    def test_normal_path_push_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            def deliver_parts(consumer, payload):
                consumer.push(payload)
            """,
        )
        assert _codes(report) == []


class TestResidencyLeak:
    SRC_LEAK = """\
        class DeviceResidentColumns:
            def __init__(self, cols):
                self.cols = cols

        def build(cols):
            return DeviceResidentColumns(cols)
        """

    def test_unregistered_class_pwd605(self, tmp_path):
        report = _analyze(tmp_path, self.SRC_LEAK)
        assert _codes(report) == ["PWD605"]
        (f,) = report.findings
        assert f.severity is Severity.ERROR
        assert f.node_index == _line_of(
            self.SRC_LEAK, "return DeviceResidentColumns"
        )
        assert "decay_resident_batches" in f.message

    def test_self_registering_class_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import weakref

            _LIVE_RESIDENT = weakref.WeakSet()

            class DeviceResidentColumns:
                def __init__(self, cols):
                    self.cols = cols
                    _LIVE_RESIDENT.add(self)

            def build(cols):
                return DeviceResidentColumns(cols)
            """,
        )
        assert _codes(report) == []

    def test_site_registration_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import weakref

            _staged_handles = weakref.WeakSet()

            class DeviceResidentColumns:
                def __init__(self, cols):
                    self.cols = cols

            def build(cols):
                out = DeviceResidentColumns(cols)
                _staged_handles.add(out)
                return out
            """,
        )
        assert _codes(report) == []

    def test_pwd_ok_bare_waiver(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            class DeviceResidentColumns:
                def __init__(self, cols):
                    self.cols = cols

            def build(cols):
                return DeviceResidentColumns(cols)  # pwd-ok: host-only twin
            """,
        )
        assert _codes(report) == []
        assert [f.code for f in report.waived] == ["PWD605"]


class TestFlagLiveness:
    SRC_CACHED = """\
        import os

        _ENABLED = os.environ.get("PATHWAY_TPU_DEVICE_RESIDENCY") == "1"

        def enabled():
            return _ENABLED
        """

    def test_live_flag_cached_at_module_scope_pwd606(self, tmp_path):
        report = _analyze(tmp_path, self.SRC_CACHED)
        assert _codes(report) == ["PWD606"]
        (f,) = report.findings
        assert f.severity is Severity.ERROR
        assert f.node_index == _line_of(self.SRC_CACHED, "_ENABLED = ")
        assert "PATHWAY_TPU_DEVICE_RESIDENCY" in f.message
        assert "flags.py" in f.message

    def test_live_flag_cached_at_class_scope_pwd606(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import os

            class Plane:
                enabled = os.getenv("PATHWAY_TPU_COLLECTIVE_EXCHANGE", "auto")
            """,
        )
        assert _codes(report) == ["PWD606"]
        assert "class Plane" in report.findings[0].message

    def test_startup_flag_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import os

            _BATCH = int(os.environ.get("PATHWAY_TPU_DEVICE_BATCH", "256"))
            """,
        )
        assert _codes(report) == []

    def test_per_call_read_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            import os

            def enabled():
                return os.environ.get("PATHWAY_TPU_DEVICE_RESIDENCY", "auto")
            """,
        )
        assert _codes(report) == []


class TestMetricFamilies:
    SRC_DRIFT = """\
        from pathway_tpu.internals.metrics import REGISTRY

        A = REGISTRY.counter("pathway_widget_total", "widgets", kind="a")
        B = REGISTRY.counter("pathway_widget_total", "widgets", worker="0")
        """

    def test_label_drift_pwd607(self, tmp_path):
        report = _analyze(tmp_path, self.SRC_DRIFT)
        assert _codes(report) == ["PWD607"]
        (f,) = report.findings
        assert f.severity is Severity.WARNING
        assert f.node_index == _line_of(self.SRC_DRIFT, 'worker="0"')
        assert "label sets must agree" in f.message

    def test_unregistered_family_use_pwd607(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            def bump(store):
                store.inc("pathway_ghost_total", 1)
            """,
        )
        assert _codes(report) == ["PWD607"]
        assert "never registered" in report.findings[0].message

    def test_consistent_reregistration_exempt(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            from pathway_tpu.internals.metrics import REGISTRY

            def fam():
                return REGISTRY.counter("pathway_w_total", "w", kind="a")

            def fam2():
                return REGISTRY.counter("pathway_w_total", "w", kind="b")
            """,
        )
        assert _codes(report) == []

    def test_mirrored_counter_registration_counts(self, tmp_path):
        report = _analyze(
            tmp_path,
            """\
            from pathway_tpu.internals.metrics import MirroredCounterDict

            STATS = MirroredCounterDict(
                "pathway_plane_events_total", "kind", {"hits": 0}
            )

            def bump(store):
                store.inc("pathway_plane_events_total", 1)
            """,
        )
        assert _codes(report) == []


class TestJsonOutput:
    def test_source_json_schema_includes_waived(self, tmp_path, capsys):
        from pathway_tpu import cli

        f = tmp_path / "engine" / "mod.py"
        f.parent.mkdir()
        f.write_text(
            textwrap.dedent(
                """\
                import jax

                def upload(batch):
                    return jax.device_put(batch)

                def upload_waived(batch):
                    return jax.device_put(batch)  # pwd-ok: PWD603 rig
                """
            )
        )
        old = os.getcwd()
        os.chdir(tmp_path)
        try:
            rc = cli.analyze_source([str(f)], as_json=True, strict=True)
        finally:
            os.chdir(old)
        out = json.loads(capsys.readouterr().out)
        assert rc == 1  # the unwaived finding fails strict mode
        assert out["mode"] == "source"
        assert out["files"] == 1
        recs = out["findings"]
        assert {r["code"] for r in recs} == {"PWD603"}
        by_waived = {r["waived"]: r for r in recs}
        assert set(by_waived) == {True, False}
        for r in recs:
            assert set(r) == {
                "code", "path", "line", "column", "severity",
                "message", "waived",
            }
        assert out["summary"]["errors"] == 1
        assert out["summary"]["waived"] == 1

    def test_waived_only_tree_exits_zero(self, tmp_path, capsys):
        from pathway_tpu import cli

        f = tmp_path / "engine" / "mod.py"
        f.parent.mkdir()
        f.write_text(
            "import jax\n\n"
            "def upload(batch):\n"
            "    return jax.device_put(batch)  # pwd-ok: PWD603 rig\n"
        )
        rc = cli.analyze_source([str(f)], as_json=True, strict=True)
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["summary"]["waived"] == 1


class TestRealTree:
    def test_runtime_tree_is_strict_clean(self):
        """The shipped tree must analyze strict-clean: zero findings of
        ANY severity across concurrency, protocol, and device-plane
        passes — the pin behind tools/check.py's whole-tree source-lint
        and deviceplane-lint gates."""
        target = os.path.join(REPO, "pathway_tpu")
        report = analyze_paths([target], root=REPO)
        assert report.node_count > 100
        assert not report.internal_errors, report.internal_errors
        assert not report.findings, "\n".join(
            f.render() for f in report.sorted_findings()
        )

    def test_every_pwd_code_is_registered(self):
        from pathway_tpu.analysis.findings import FINDING_CODES

        for code in (
            "PWD601", "PWD602", "PWD603", "PWD604",
            "PWD605", "PWD606", "PWD607",
        ):
            assert code in FINDING_CODES

    def test_flag_registry_covers_live_planes(self):
        from pathway_tpu.analysis.flags import LIVE_FLAGS, REGISTRY

        for name in (
            "PATHWAY_TPU_COLLECTIVE_EXCHANGE",
            "PATHWAY_TPU_DEVICE_RESIDENCY",
            "PATHWAY_TPU_DEVICE_OPS",
            "PATHWAY_TPU_ASYNC_DEVICE",
        ):
            assert name in LIVE_FLAGS
        # startup flags must never be classified live by accident
        assert "PATHWAY_TPU_DEVICE_BATCH" in REGISTRY
        assert "PATHWAY_TPU_DEVICE_BATCH" not in LIVE_FLAGS
