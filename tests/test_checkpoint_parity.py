"""Committed real-checkpoint parity fixture (VERDICT r2 #5).

tests/fixtures/tiny_bert holds a frozen HF BertModel checkpoint (.npz, a
real torch-generated state dict) plus golden sentence embeddings computed
ONCE via torch (tools/make_tiny_bert_fixture.py). These tests reproduce
the goldens from the committed bytes through the full product path —
WordPiece tokenizer -> hf_import -> JAX encoder -> mean pooling — with no
torch at test time. Reference: python/pathway/xpacks/llm/embedders.py:270
(SentenceTransformerEmbedder semantics)."""

import os

import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "tiny_bert")


@pytest.fixture(scope="module")
def golden():
    data = np.load(os.path.join(FIXTURE, "golden_embeddings.npz"))
    return (
        [str(t) for t in data["texts"]],
        np.asarray(data["embeddings"], np.float32),
        np.asarray(data["input_ids"], np.int64),
    )


def test_tokenizer_reproduces_golden_input_ids(golden):
    from pathway_tpu.xpacks.llm._tokenizer import WordPieceTokenizer

    texts, _emb, input_ids = golden
    tok = WordPieceTokenizer(os.path.join(FIXTURE, "vocab.txt"))
    for row, text in zip(input_ids, texts):
        real = [int(t) for t in row if t != tok.pad_id]
        assert tok.encode(text) == real, text


def test_jax_encoder_reproduces_torch_goldens_to_1e4(golden):
    import jax.numpy as jnp

    from pathway_tpu.models.hf_import import load_sentence_transformer
    from pathway_tpu.models.transformer import EncoderConfig, embed

    texts, expected, _ids = golden
    params, cfg, tok = load_sentence_transformer(FIXTURE)
    assert tok is not None
    # head count comes from the checkpoint's config.json (invisible in
    # tensor shapes)
    assert (cfg.hidden, cfg.layers, cfg.heads) == (64, 2, 4)
    cfg = EncoderConfig(
        **{
            **{f: getattr(cfg, f) for f in cfg.__dataclass_fields__},
            "dtype": jnp.float32,
        }
    )
    ids, mask = tok.encode_batch(texts, 32)
    ours = np.asarray(
        embed(params, jnp.asarray(ids, jnp.int32), jnp.asarray(mask), cfg),
        np.float32,
    )
    diff = np.abs(ours - expected).max()
    assert diff < 1e-4, f"max |jax - torch| = {diff}"
    # and the embeddings are semantically sane: self-similarity 1.0
    sims = ours @ expected.T
    assert np.allclose(np.diag(sims), 1.0, atol=1e-4)


def test_embedder_udf_serves_fixture_checkpoint(golden):
    """The user-facing path: TpuEncoderEmbedder(model=<dir>) loads the
    committed checkpoint and reproduces the torch goldens."""
    import jax.numpy as jnp

    from pathway_tpu.models.transformer import EncoderConfig
    from pathway_tpu.xpacks.llm.embedders import TpuEncoderEmbedder

    texts, expected, _ids = golden
    emb = TpuEncoderEmbedder(model=FIXTURE, max_len=32, device_resident=False)
    assert emb.config.heads == 4  # from the checkpoint's config.json
    out = np.stack([np.asarray(v, np.float32) for v in emb._fn(list(texts))])
    # bf16 activations on the default config cost precision; the parity
    # axis is the f32 test above — here assert the product path ranks
    # identically and stays close
    assert np.abs(out - expected).max() < 2e-2
    sims = out @ expected.T
    assert (np.argmax(sims, axis=1) == np.arange(len(texts))).all()
