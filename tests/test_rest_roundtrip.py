"""REST connector round trip: live HTTP requests through a streaming
run — the serving surface behind VectorStoreServer/QA servers
(reference python/pathway/io/http + tests/test_rest_connector shape)."""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.request

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url: str, payload: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _post_with_retry(url: str, payload: dict, deadline_s: float = 20.0):
    deadline = time.monotonic() + deadline_s
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            return _post(url, payload)
        except Exception as exc:  # noqa: BLE001 — server still starting
            last = exc
            time.sleep(0.1)
    raise last  # type: ignore[misc]


class TestRestConnectorRoundTrip:
    def test_concurrent_requests_get_their_own_answers(self):
        G.clear()
        port = _free_port()
        queries, attach = pw.io.http.rest_connector(
            "127.0.0.1",
            port,
            schema=pw.schema_from_types(x=int),
            route="/double",
        )
        result = queries.select(result=pw.this.x * 2)
        runner = GraphRunner()
        attach(result, runner)
        threading.Thread(
            target=runner.run, name="rest-test-run", daemon=True
        ).start()

        answers: dict[int, dict] = {}
        errors: list[Exception] = []

        def client(i: int) -> None:
            try:
                answers[i] = _post_with_retry(
                    f"http://127.0.0.1:{port}/double", {"x": i}
                )
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert len(answers) == 4
        for i, body in answers.items():
            value = body["result"] if isinstance(body, dict) else body
            assert value == i * 2, (i, body)

    def test_openapi_schema_endpoint_and_cors(self):
        """/_schema serves an OpenAPI 3.0.3 description generated from the
        route schemas (reference _server.py:329 with_schema_endpoint), and
        with_cors stamps Access-Control-* on responses + answers
        preflight OPTIONS."""
        G.clear()
        port = _free_port()
        server = pw.io.http.PathwayWebserver(
            "127.0.0.1", port, with_cors=True
        )
        queries, attach = pw.io.http.rest_connector(
            schema=pw.schema_from_types(q=str, k=int),
            route="/v1/retrieve",
            webserver=server,
        )
        result = queries.select(result=pw.this.q)
        runner = GraphRunner()
        attach(result, runner)
        threading.Thread(target=runner.run, daemon=True).start()
        # wait until the server answers
        _post_with_retry(
            f"http://127.0.0.1:{port}/v1/retrieve", {"q": "x", "k": 1}
        )

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/_schema?format=json", timeout=10
        ) as resp:
            desc = json.loads(resp.read().decode())
            cors_origin = resp.headers.get("Access-Control-Allow-Origin")
        assert desc["openapi"] == "3.0.3"
        path = desc["paths"]["/v1/retrieve"]
        props = path["post"]["requestBody"]["content"][
            "application/json"
        ]["schema"]["properties"]
        assert props == {
            "q": {"type": "string"},
            "k": {"type": "integer"},
        }
        get_params = {p["name"]: p for p in path["get"]["parameters"]}
        assert get_params["k"]["schema"] == {"type": "integer"}
        assert cors_origin == "*"

        # yaml default format
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/_schema", timeout=10
        ) as resp:
            body = resp.read().decode()
            assert resp.headers.get_content_type() == "text/x-yaml"
        import yaml

        assert yaml.safe_load(body)["paths"]["/v1/retrieve"]

        # CORS preflight
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/retrieve", method="OPTIONS"
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert (
                resp.headers.get("Access-Control-Allow-Methods")
                == "GET, POST, OPTIONS"
            )

    def test_qa_style_server_class(self):
        """The xpack server wrapper: BaseRestServer.serve + threaded run,
        the exact shape DocumentStoreServer/QARestServer use."""
        G.clear()
        from pathway_tpu.xpacks.llm.servers import BaseRestServer

        port = _free_port()
        server = BaseRestServer("127.0.0.1", port)
        server.serve(
            "/echo",
            pw.schema_from_types(text=str),
            lambda q: q.select(result=pw.this.text + "!"),
        )
        server.run(threaded=True)
        body = _post_with_retry(
            f"http://127.0.0.1:{port}/echo", {"text": "hello"}
        )
        value = body["result"] if isinstance(body, dict) else body
        assert value == "hello!"
