"""REST connector round trip: live HTTP requests through a streaming
run — the serving surface behind VectorStoreServer/QA servers
(reference python/pathway/io/http + tests/test_rest_connector shape)."""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.request

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url: str, payload: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _post_with_retry(url: str, payload: dict, deadline_s: float = 20.0):
    deadline = time.monotonic() + deadline_s
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            return _post(url, payload)
        except Exception as exc:  # noqa: BLE001 — server still starting
            last = exc
            time.sleep(0.1)
    raise last  # type: ignore[misc]


class TestRestConnectorRoundTrip:
    def test_concurrent_requests_get_their_own_answers(self):
        G.clear()
        port = _free_port()
        queries, attach = pw.io.http.rest_connector(
            "127.0.0.1",
            port,
            schema=pw.schema_from_types(x=int),
            route="/double",
        )
        result = queries.select(result=pw.this.x * 2)
        runner = GraphRunner()
        attach(result, runner)
        threading.Thread(
            target=runner.run, name="rest-test-run", daemon=True
        ).start()

        answers: dict[int, dict] = {}
        errors: list[Exception] = []

        def client(i: int) -> None:
            try:
                answers[i] = _post_with_retry(
                    f"http://127.0.0.1:{port}/double", {"x": i}
                )
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert len(answers) == 4
        for i, body in answers.items():
            value = body["result"] if isinstance(body, dict) else body
            assert value == i * 2, (i, body)

    def test_qa_style_server_class(self):
        """The xpack server wrapper: BaseRestServer.serve + threaded run,
        the exact shape DocumentStoreServer/QARestServer use."""
        G.clear()
        from pathway_tpu.xpacks.llm.servers import BaseRestServer

        port = _free_port()
        server = BaseRestServer("127.0.0.1", port)
        server.serve(
            "/echo",
            pw.schema_from_types(text=str),
            lambda q: q.select(result=pw.this.text + "!"),
        )
        server.run(threaded=True)
        body = _post_with_retry(
            f"http://127.0.0.1:{port}/echo", {"text": "hello"}
        )
        value = body["result"] if isinstance(body, dict) else body
        assert value == "hello!"
