"""Config env flags, YAML app templates, CLI spawn
(reference: internals/config.py:58, yaml_loader.py:214, cli.py)."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

import pathway_tpu as pw


class TestPathwayConfig:
    def test_env_flags_read(self, monkeypatch):
        from pathway_tpu.internals.config import PathwayConfig

        monkeypatch.setenv("PATHWAY_IGNORE_ASSERTS", "true")
        monkeypatch.setenv("PATHWAY_THREADS", "4")
        monkeypatch.setenv("PATHWAY_PROCESS_ID", "2")
        cfg = PathwayConfig()
        assert cfg.ignore_asserts is True
        assert cfg.threads == 4
        assert cfg.process_id == "2"

    def test_replay_config_from_env(self, monkeypatch, tmp_path):
        from pathway_tpu.internals.config import PathwayConfig
        from pathway_tpu.persistence import PersistenceMode

        monkeypatch.setenv("PATHWAY_PERSISTENT_STORAGE", str(tmp_path / "s"))
        monkeypatch.setenv("PATHWAY_PERSISTENCE_MODE", "operator_persisting")
        cfg = PathwayConfig().replay_config
        assert cfg is not None
        assert cfg.persistence_mode == PersistenceMode.OPERATOR_PERSISTING

    def test_env_persistence_drives_pw_run(self, monkeypatch, tmp_path):
        """pw.run() with no explicit config persists via the env (reference
        PathwayConfig.replay_config)."""
        data = tmp_path / "data"
        data.mkdir()
        (data / "a.txt").write_text("x\ny\n")
        store = tmp_path / "store"
        monkeypatch.setenv("PATHWAY_PERSISTENT_STORAGE", str(store))
        t = pw.io.plaintext.read(data, mode="static", persistent_id="w")
        out = tmp_path / "o.jsonl"
        pw.io.jsonlines.write(t, out)
        pw.run()
        assert store.exists() and any(store.iterdir())  # journal written


class TestYamlLoader:
    def test_construct_objects_with_variables(self):
        text = """
$splitter: !pw.xpacks.llm.splitters.NullSplitter {}
chain:
  splitter: $splitter
  again: $splitter
  name: plain
"""
        out = pw.load_yaml(text)
        from pathway_tpu.xpacks.llm.splitters import NullSplitter

        assert isinstance(out["chain"]["splitter"], NullSplitter)
        # constructed exactly once: both references share the instance
        assert out["chain"]["splitter"] is out["chain"]["again"]
        assert out["chain"]["name"] == "plain"

    def test_nested_kwargs(self):
        text = """
tok: !pw.xpacks.llm._tokenizer.HashTokenizer
  vocab_size: 128
"""
        out = pw.load_yaml(text)
        assert out["tok"].vocab_size == 128

    def test_non_pw_dotted_path(self):
        text = "d: !collections.OrderedDict {}\n"
        import collections

        out = pw.load_yaml(text)
        assert isinstance(out["d"], collections.OrderedDict)

    def test_undefined_variable_raises(self):
        with pytest.raises(ValueError, match="undefined variable"):
            pw.load_yaml("a: $missing\n")


class TestCli:
    def test_spawn_sets_worker_env(self, tmp_path):
        worker = tmp_path / "w.py"
        worker.write_text(
            "import json, os, sys\n"
            "out = {k: os.environ.get(k) for k in ("
            "'PATHWAY_THREADS','PATHWAY_PROCESSES','PATHWAY_PROCESS_ID',"
            "'PATHWAY_RUN_ID')}\n"
            "open(sys.argv[1] + os.environ['PATHWAY_PROCESS_ID'], 'w')"
            ".write(json.dumps(out))\n"
        )
        from pathway_tpu.cli import spawn

        rc = spawn(
            sys.executable,
            [str(worker), str(tmp_path / "out")],
            threads=3,
            processes=2,
        )
        assert rc == 0
        envs = [
            json.loads((tmp_path / f"out{i}").read_text()) for i in range(2)
        ]
        assert all(e["PATHWAY_THREADS"] == "3" for e in envs)
        assert all(e["PATHWAY_PROCESSES"] == "2" for e in envs)
        assert {e["PATHWAY_PROCESS_ID"] for e in envs} == {"0", "1"}
        assert len({e["PATHWAY_RUN_ID"] for e in envs}) == 1

    def test_spawn_from_env(self, tmp_path, monkeypatch):
        worker = tmp_path / "w.py"
        worker.write_text(
            "import os, sys\n"
            "open(sys.argv[1], 'w').write(os.environ['PATHWAY_THREADS'])\n"
        )
        out = tmp_path / "flag"
        monkeypatch.setenv(
            "PATHWAY_SPAWN_ARGS",
            f"--threads 2 {sys.executable} {worker} {out}",
        )
        from pathway_tpu.cli import main

        assert main(["spawn-from-env"]) == 0
        assert out.read_text() == "2"

    def test_module_entrypoint(self, tmp_path):
        worker = tmp_path / "w.py"
        worker.write_text("print('hi')\n")
        res = subprocess.run(
            [
                sys.executable,
                "-m",
                "pathway_tpu.cli",
                "spawn",
                "--processes",
                "1",
                sys.executable,
                str(worker),
            ],
            capture_output=True,
            text=True,
            timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=str(pathlib.Path(__file__).resolve().parent.parent),
        )
        assert res.returncode == 0
        assert "hi" in res.stdout


class TestParsers:
    def _tiny_pdf(self, text):
        import zlib

        content = f"BT /F1 12 Tf 72 700 Td ({text}) Tj ET".encode()
        compressed = zlib.compress(content)
        return (
            b"%PDF-1.4\n"
            b"1 0 obj\n<< /Length " + str(len(compressed)).encode()
            + b" /Filter /FlateDecode >>\nstream\n"
            + compressed
            + b"\nendstream\nendobj\n%%EOF"
        )

    def test_pypdf_parser_extracts_text(self):
        from pathway_tpu.xpacks.llm.parsers import PypdfParser

        parser = PypdfParser()
        ((text, meta),) = parser._fn(self._tiny_pdf("Hello pathway PDF"))
        assert text == "Hello pathway PDF"
        assert meta["format"] == "pdf"

    def test_pdf_tj_array_and_escapes(self):
        from pathway_tpu.xpacks.llm._pdf import extract_pdf_text

        content = rb"BT [(Hel) -30 (lo)] TJ T* (wor\(ld\)) Tj ET"
        pdf = (
            b"%PDF-1.4\n1 0 obj\n<< /Length "
            + str(len(content)).encode()
            + b" >>\nstream\n"
            + content
            + b"\nendstream\nendobj"
        )
        assert extract_pdf_text(pdf) == "Hello\nwor(ld)"

    def test_image_parser_with_vision_seam(self):
        import io

        from PIL import Image

        from pathway_tpu.xpacks.llm.parsers import ImageParser

        buf = io.BytesIO()
        Image.new("RGB", (64, 32), "red").save(buf, format="PNG")
        parser = ImageParser(llm=lambda img, prompt: f"a {img.width}px thing")
        ((text, meta),) = parser._fn(buf.getvalue())
        assert text == "a 64px thing"
        assert meta["width"] == 64 and meta["format"] == "png"

    def test_slide_parser_multiframe(self):
        import io

        from PIL import Image

        from pathway_tpu.xpacks.llm.parsers import SlideParser

        frames = [
            Image.new("RGB", (20, 20), c) for c in ("red", "green", "blue")
        ]
        buf = io.BytesIO()
        frames[0].save(
            buf,
            format="GIF",
            save_all=True,
            append_images=frames[1:],
            optimize=False,
        )
        parser = SlideParser()
        parts = parser._fn(buf.getvalue())
        assert len(parts) == 3
        assert [m["page"] for _t, m in parts] == [0, 1, 2]


class TestLicense:
    def test_free_tier_caps_workers(self):
        from pathway_tpu.internals.license import LicenseError
        from pathway_tpu.internals.runner import ShardedGraphRunner

        with pytest.raises(LicenseError, match="free tier"):
            ShardedGraphRunner(9)
        ShardedGraphRunner(8)  # at the cap: fine

    def test_entitlement_unlocks(self, monkeypatch):
        monkeypatch.setenv(
            "PATHWAY_LICENSE_KEY", "pathway-tpu:unlimited-workers"
        )
        from pathway_tpu.internals.runner import ShardedGraphRunner

        ShardedGraphRunner(9)

    def test_check_entitlements(self, monkeypatch):
        from pathway_tpu.internals import license as lic

        with pytest.raises(lic.LicenseError, match="does not grant"):
            lic.check_entitlements("xpack-sharepoint")
        monkeypatch.setenv(
            "PATHWAY_LICENSE_KEY", "pathway-tpu:xpack-sharepoint"
        )
        lic.check_entitlements("xpack-sharepoint")


class TestSharePoint:
    def test_entitlement_gated(self):
        from pathway_tpu.internals.license import LicenseError
        from pathway_tpu.xpacks.connectors import sharepoint

        with pytest.raises(LicenseError, match="does not grant"):
            sharepoint.read("https://site", client=object())

    def test_reads_with_entitlement_and_client(self, monkeypatch):
        monkeypatch.setenv(
            "PATHWAY_LICENSE_KEY", "pathway-tpu:xpack-sharepoint"
        )
        from pathway_tpu.engine.storage import DictObjectStore
        from pathway_tpu.internals.runner import GraphRunner
        from pathway_tpu.xpacks.connectors import sharepoint

        store = DictObjectStore()
        store.put_object("docs/a.txt", b"hello sharepoint")
        t = sharepoint.read("https://site", mode="static", client=store)
        (snap,) = GraphRunner().capture(t)
        assert list(snap.values()) == [(b"hello sharepoint",)]
