"""Metrics history ring + SLO sentinel: tiered down-sampling rings,
bounded store over registry snapshots, worker pruning, declarative SLO
burn evaluation with flight-recorder events, the telemetry recorder
loop, and the live ``/timeseries`` endpoint during a sharded run
(reference: PR "observability")."""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from types import SimpleNamespace

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import timeseries
from pathway_tpu.internals.monitoring import (
    MonitoringHttpServer,
    MonitoringLevel,
    StatsMonitor,
)
from pathway_tpu.internals.parse_graph import G


def _gauge_family(value: float, labels: dict | None = None) -> dict:
    return {
        "kind": "gauge",
        "help": "",
        "buckets": None,
        "series": [{"labels": dict(labels or {}), "value": value}],
    }


def _hist_family(
    bounds: list, counts: list, total: float, labels: dict | None = None
) -> dict:
    return {
        "kind": "histogram",
        "help": "",
        "buckets": list(bounds),
        "series": [
            {
                "labels": dict(labels or {}),
                "counts": list(counts),
                "sum": total,
                "count": sum(counts),
            }
        ],
    }


# -- tiered series ring -------------------------------------------------------


class TestSeriesRing:
    def test_points_merge_tiers_finest_wins(self):
        ring = timeseries.SeriesRing()
        for t in (0.0, 0.4, 1.2, 10.5):
            ring.append(t, t)
        # first append promoted to both coarser tiers; 1.2 to mid; 10.5
        # to both again — all still raw, so points() is raw-only
        assert ring.points(0.0) == [
            [0.0, 0.0],
            [0.4, 0.4],
            [1.2, 1.2],
            [10.5, 10.5],
        ]
        assert [t for t, _ in ring.mid] == [0.0, 1.2, 10.5]
        assert [t for t, _ in ring.coarse] == [0.0, 10.5]

    def test_evicted_raw_span_is_covered_by_coarser_tiers(self):
        ring = timeseries.SeriesRing(raw_points=4, mid_points=64,
                                     coarse_points=64)
        ts = [i * 0.5 for i in range(61)]  # 0..30s
        for t in ts:
            ring.append(t, t)
        pts = ring.points(0.0)
        times = [t for t, _ in pts]
        # ascending, deduplicated
        assert times == sorted(times)
        assert len(times) == len(set(times))
        # the raw ring only holds the last 4 points; the mid tier still
        # covers the evicted span at 1s resolution
        assert times[-4:] == [28.5, 29.0, 29.5, 30.0]
        assert 0.0 in times and 15.0 in times
        covered = [t for t in times if t < 28.5]
        assert len(covered) >= 25  # ~1s resolution over the old span

    def test_window_filter_and_last(self):
        ring = timeseries.SeriesRing()
        for t in (10.0, 20.0, 30.0):
            ring.append(t, t * 2)
        assert ring.points(15.0) == [[20.0, 40.0], [30.0, 60.0]]
        assert ring.last() == (30.0, 60.0)
        assert ring.n_points() == 3 + 3 + 3  # 10s gaps promote everywhere


# -- bounded store ------------------------------------------------------------


class TestTimeSeriesStore:
    def test_observe_and_windowed_query(self):
        store = timeseries.TimeSeriesStore(max_series=16)
        now = 1000.0
        for dt, v in ((-100, 1.0), (-30, 2.0), (-5, 3.0)):
            store.observe("fam", {"worker": "0"}, v, t=now + dt)
        res = store.query("fam", window_s=60, now=now)
        assert res["family"] == "fam" and res["window_s"] == 60.0
        assert [p[1] for p in res["series"][0]["points"]] == [2.0, 3.0]

    def test_label_superset_filter(self):
        store = timeseries.TimeSeriesStore(max_series=16)
        store.observe("fam", {"worker": "0", "op": "a"}, 1.0, t=1.0)
        store.observe("fam", {"worker": "1", "op": "a"}, 2.0, t=1.0)
        res = store.query("fam", window_s=1e9, labels={"worker": "1"}, now=2.0)
        assert len(res["series"]) == 1
        assert res["series"][0]["labels"]["worker"] == "1"
        # a label the series lacks matches nothing
        res = store.query("fam", window_s=1e9, labels={"zone": "x"}, now=2.0)
        assert res["series"] == []

    def test_series_cap_drops_new_series_not_old_points(self):
        store = timeseries.TimeSeriesStore(max_series=2)
        store.observe("fam", {"worker": "0"}, 1.0, t=1.0)
        store.observe("fam", {"worker": "1"}, 1.0, t=1.0)
        store.observe("fam", {"worker": "2"}, 1.0, t=1.0)  # over cap
        store.observe("fam", {"worker": "0"}, 2.0, t=2.0)  # existing: fine
        stats = store.stats()
        assert stats["series"] == 2
        assert stats["dropped_series"] == 1
        assert stats["max_points"] == 2 * (
            timeseries.RAW_POINTS
            + timeseries.MID_POINTS
            + timeseries.COARSE_POINTS
        )

    def test_ingest_snapshot_scalars_histograms_and_reserved_keys(self):
        store = timeseries.TimeSeriesStore(max_series=64)
        snap = {
            "pathway_queue_depth": _gauge_family(7.0, {"op": "reader"}),
            "pathway_ingest_to_sink_latency_seconds": _hist_family(
                [0.1, 1.0], [2, 3, 1], total=2.5
            ),
            "__profile__": {"v": 1},  # reserved piggyback key: skipped
            "__trace__": [1, 2, 3],
        }
        store.ingest_snapshot(snap, worker="0", t=100.0)
        fams = {f["family"] for f in store.families()}
        assert fams == {
            "pathway_queue_depth",
            "pathway_ingest_to_sink_latency_seconds",
        }
        gauge = store.query("pathway_queue_depth", 1e9, now=101.0)
        assert gauge["series"][0]["labels"] == {
            "op": "reader", "worker": "0"
        }
        assert gauge["series"][0]["points"] == [[100.0, 7.0]]
        # histograms become derived stat tracks, never bucket series
        hist = store.query(
            "pathway_ingest_to_sink_latency_seconds", 1e9, now=101.0
        )
        stats = {s["labels"]["stat"] for s in hist["series"]}
        assert stats == {"count", "sum", "p50", "p95", "p99"}
        by_stat = {
            s["labels"]["stat"]: s["points"][0][1] for s in hist["series"]
        }
        assert by_stat["count"] == 6.0
        assert by_stat["sum"] == 2.5
        # p50: target 3 of 6 -> 1/3 into the (0.1, 1.0] bucket
        assert by_stat["p50"] == pytest.approx(0.4, rel=1e-6)

    def test_prune_workers_dead_and_width(self):
        store = timeseries.TimeSeriesStore(max_series=16)
        for w in ("0", "1", "5"):
            store.observe("fam", {"worker": w}, 1.0, t=1.0)
        store.prune_workers(dead=("1",))
        left = {
            s["labels"]["worker"]
            for s in store.query("fam", 1e9, now=2.0)["series"]
        }
        assert left == {"0", "5"}
        store.prune_workers(width=2)  # rescale narrowed the mesh
        left = {
            s["labels"]["worker"]
            for s in store.query("fam", 1e9, now=2.0)["series"]
        }
        assert left == {"0"}

    def test_clear(self):
        store = timeseries.TimeSeriesStore(max_series=4)
        store.observe("fam", {"worker": "0"}, 1.0, t=1.0)
        store.clear()
        assert store.stats()["series"] == 0
        assert store.families() == []


# -- SLO specs + sentinel -----------------------------------------------------


class TestSloSpec:
    def test_rejects_unknown_kind_bound_quantile(self):
        with pytest.raises(ValueError, match="unknown kind"):
            timeseries.SloSpec("s", "jitter", "fam", 1.0)
        with pytest.raises(ValueError, match="bound"):
            timeseries.SloSpec("s", "latency", "fam", 0.0)
        with pytest.raises(ValueError, match="quantile"):
            timeseries.SloSpec("s", "latency", "fam", 1.0, quantile="p42")

    def test_budget_clamps(self):
        assert timeseries.SloSpec(
            "s", "latency", "fam", 1.0, budget=0.0
        ).budget == 1e-6
        assert timeseries.SloSpec(
            "s", "latency", "fam", 1.0, budget=7.0
        ).budget == 1.0

    def test_dict_roundtrip(self):
        spec = timeseries.SloSpec(
            "lat", "latency", "fam", 0.25,
            labels={"worker": "0"}, window_s=30.0, budget=0.05,
            quantile="p95",
        )
        again = timeseries.SloSpec.from_dict(spec.to_dict())
        assert again.to_dict() == spec.to_dict()


class TestSloSentinel:
    def _store_with(self, family, labels, points):
        store = timeseries.TimeSeriesStore(max_series=16)
        for t, v in points:
            store.observe(family, labels, v, t=t)
        return store

    def test_latency_burn_records_one_edge_triggered_event(self):
        name = "lat-edge-test"
        store = self._store_with(
            "lat_fam",
            {"worker": "0", "stat": "p99"},
            [(1.0, 0.5), (2.0, 0.5), (3.0, 0.5), (4.0, 0.01)],
        )
        spec = timeseries.SloSpec(
            "lat-edge-test", "latency", "lat_fam", bound=0.1, budget=0.5
        )
        sentinel = timeseries.SloSentinel([spec])
        events_before = len(
            [e for e in _metrics.FLIGHT.snapshot()
             if e.get("kind") == "slo_burn" and e.get("slo") == name]
        )
        reports = sentinel.evaluate(store, now=5.0)
        # 3 of 4 points over the bound: burn = 0.75 / 0.5
        assert reports[0]["burn"] == pytest.approx(1.5)
        assert reports[0]["measured"] == pytest.approx(0.5)
        burns = [
            e for e in _metrics.FLIGHT.snapshot()
            if e.get("kind") == "slo_burn" and e.get("slo") == name
        ]
        assert len(burns) == events_before + 1
        event = burns[-1]
        assert event["slo_kind"] == "latency"
        assert event["family"] == "lat_fam"
        assert event["burn"] == pytest.approx(1.5)
        gauge = _metrics.REGISTRY.gauge(
            "pathway_slo_burn_ratio",
            "SLO burn ratio (> 1.0 = violating)",
            slo=name,
        )
        assert gauge.value == pytest.approx(1.5)
        # still burning: edge-triggered, no second event
        sentinel.evaluate(store, now=5.0)
        burns = [
            e for e in _metrics.FLIGHT.snapshot()
            if e.get("kind") == "slo_burn" and e.get("slo") == name
        ]
        assert len(burns) == events_before + 1
        # recover (all points healthy) -> re-armed -> violate again
        healthy = self._store_with(
            "lat_fam", {"worker": "0", "stat": "p99"}, [(1.0, 0.01)]
        )
        assert sentinel.evaluate(healthy, now=5.0)[0]["burn"] < 1.0
        sentinel.evaluate(store, now=5.0)
        burns = [
            e for e in _metrics.FLIGHT.snapshot()
            if e.get("kind") == "slo_burn" and e.get("slo") == name
        ]
        assert len(burns) == events_before + 2

    def test_queue_depth_ceiling(self):
        store = self._store_with(
            "depth_fam", {"worker": "0"}, [(1.0, 4.0), (2.0, 12.0)]
        )
        spec = timeseries.SloSpec("q", "queue_depth", "depth_fam", bound=10)
        reports = timeseries.SloSentinel([spec]).evaluate(store, now=3.0)
        assert reports[0]["burn"] == pytest.approx(1.2)
        assert reports[0]["measured"] == pytest.approx(12.0)

    def test_staleness_bound_reads_last_point(self):
        store = self._store_with(
            "stale_fam", {"worker": "0"}, [(1.0, 50.0), (2.0, 30.0)]
        )
        spec = timeseries.SloSpec("st", "staleness", "stale_fam", bound=10)
        reports = timeseries.SloSentinel([spec]).evaluate(store, now=3.0)
        assert reports[0]["burn"] == pytest.approx(3.0)

    def test_throughput_floor_uses_counter_rate(self):
        store = self._store_with(
            "rows_fam", {"worker": "0"}, [(0.0, 0.0), (10.0, 50.0)]
        )
        spec = timeseries.SloSpec("tp", "throughput", "rows_fam", bound=10)
        reports = timeseries.SloSentinel([spec]).evaluate(store, now=11.0)
        assert reports[0]["burn"] == pytest.approx(2.0)  # 10 / (5 rows/s)
        assert reports[0]["measured"] == pytest.approx(5.0)

    def test_no_data_is_not_a_violation(self):
        store = timeseries.TimeSeriesStore(max_series=4)
        spec = timeseries.SloSpec("empty", "latency", "nope", bound=1.0)
        reports = timeseries.SloSentinel([spec]).evaluate(store, now=1.0)
        assert reports[0]["burn"] is None
        # a single throughput point has no rate either
        store.observe("rows_fam", {"worker": "0"}, 5.0, t=1.0)
        spec = timeseries.SloSpec("tp1", "throughput", "rows_fam", bound=1.0)
        reports = timeseries.SloSentinel([spec]).evaluate(store, now=2.0)
        assert reports[0]["burn"] is None

    def test_configure_from_env_inline_and_file(self, monkeypatch, tmp_path):
        specs = [
            {
                "name": "lat", "kind": "latency",
                "family": "lat_fam", "bound": 0.5,
            }
        ]
        monkeypatch.setenv("PATHWAY_TPU_SLO", json.dumps(specs))
        sentinel = timeseries.SloSentinel()
        assert sentinel.configure() == 1
        assert sentinel.specs()[0].name == "lat"
        path = tmp_path / "slo.json"
        path.write_text(json.dumps(specs * 2))
        monkeypatch.setenv("PATHWAY_TPU_SLO", str(path))
        assert sentinel.configure() == 2

    def test_configure_bad_env_records_config_error(self, monkeypatch):
        monkeypatch.setenv("PATHWAY_TPU_SLO", '[{"name": "broken"}]')
        before = len(
            [e for e in _metrics.FLIGHT.snapshot()
             if e.get("kind") == "slo_config_error"]
        )
        sentinel = timeseries.SloSentinel()
        assert sentinel.configure() == 0
        after = len(
            [e for e in _metrics.FLIGHT.snapshot()
             if e.get("kind") == "slo_config_error"]
        )
        assert after == before + 1


# -- telemetry recorder loop --------------------------------------------------


class TestTelemetryLoop:
    def test_tick_records_local_registry_under_worker_label(self):
        _metrics.REGISTRY.gauge(
            "test_ts_loop_gauge", "fixture", worker_kind="local"
        ).set(42.0)
        store = timeseries.TimeSeriesStore(max_series=4096)
        loop = timeseries.TelemetryLoop(
            store, timeseries.SloSentinel(), monitor=None, period_s=60.0
        )
        loop.tick(now=100.0)
        res = store.query("test_ts_loop_gauge", 1e9, now=101.0)
        assert res["series"][0]["labels"]["worker"] == "0"
        assert res["series"][0]["points"][0][1] == 42.0

    def test_tick_ingests_mesh_snapshots_with_width_filter(self):
        # room for the full local registry snapshot plus the peers
        store = timeseries.TimeSeriesStore(max_series=8192)
        peer_snap = {"peer_fam": _gauge_family(1.0)}
        monitor = SimpleNamespace(
            scheduler=SimpleNamespace(n_processes=2, stats=None),
            mesh_snapshots={1: peer_snap, 3: peer_snap},
        )
        loop = timeseries.TelemetryLoop(
            store, timeseries.SloSentinel(), monitor=monitor, period_s=60.0
        )
        loop.tick(now=100.0)
        workers = {
            s["labels"]["worker"]
            for s in store.query("peer_fam", 1e9, now=101.0)["series"]
        }
        # peer 3 is beyond the mesh width: a dead incarnation, filtered
        assert workers == {"1"}

    def test_stop_lands_a_final_tick(self):
        _metrics.REGISTRY.gauge(
            "test_ts_final_tick", "fixture"
        ).set(7.0)
        store = timeseries.TimeSeriesStore(max_series=4096)
        loop = timeseries.TelemetryLoop(
            store, timeseries.SloSentinel(), monitor=None, period_s=300.0
        )
        loop.start()
        assert loop.running
        loop.stop()  # period never elapsed: only the final tick records
        assert not loop.running
        assert store.query("test_ts_final_tick", 1e9)["series"]

    def test_loop_enabled_env(self, monkeypatch):
        monkeypatch.delenv("PATHWAY_TPU_TIMESERIES", raising=False)
        monkeypatch.delenv("PATHWAY_TPU_SLO", raising=False)
        assert timeseries.loop_enabled() is False
        monkeypatch.setenv("PATHWAY_TPU_TIMESERIES", "1")
        assert timeseries.loop_enabled() is True
        monkeypatch.delenv("PATHWAY_TPU_TIMESERIES")
        monkeypatch.setenv("PATHWAY_TPU_SLO", '[{"name": "x"}]')
        assert timeseries.loop_enabled() is True

    def test_start_loop_is_idempotent(self, monkeypatch):
        monkeypatch.delenv("PATHWAY_TPU_SLO", raising=False)
        try:
            a = timeseries.start_loop()
            b = timeseries.start_loop()
            assert a is b and a.running
        finally:
            timeseries.stop_loop()
            timeseries.STORE.clear()
        timeseries.stop_loop()  # second stop is a no-op


# -- live acceptance ----------------------------------------------------------


class TestLiveTimeseries:
    def test_timeseries_endpoint_during_sharded_run(self):
        """``/timeseries`` must answer windowed queries WHILE a
        2-worker sharded run is pumping commits, under the fixed ring
        memory budget."""
        from pathway_tpu.internals.runner import ShardedGraphRunner

        G.clear()
        timeseries.STORE.clear()
        rows_out = []

        class Feed(pw.io.python.ConnectorSubject):
            def run(self):
                for i in range(40):
                    self.next(k=i % 4, v=i)
                    if i % 10 == 9:
                        self.commit()
                        time.sleep(0.05)

        t = pw.io.python.read(
            Feed(),
            schema=pw.schema_from_types(k=int, v=int),
            autocommit_duration_ms=None,
        )
        agg = t.groupby(pw.this.k).reduce(
            k=pw.this.k, s=pw.reducers.sum(pw.this.v)
        )
        pw.io.subscribe(
            agg,
            on_change=lambda key, row, time, is_addition: rows_out.append(
                row
            ),
        )

        runner = ShardedGraphRunner(2)
        monitor = StatsMonitor(MonitoringLevel.ALL)
        runner.monitor = monitor
        runner.attach_sinks()
        server = MonitoringHttpServer(monitor, port=0)
        loop = timeseries.TelemetryLoop(
            timeseries.STORE,
            timeseries.SloSentinel(),
            monitor=monitor,
            period_s=0.05,
        )
        loop.start()
        mid_run: list[dict] = []
        done = threading.Event()
        family = "pathway_ingest_to_sink_latency_seconds"

        def poll():
            url = (
                f"http://127.0.0.1:{server.port}/timeseries"
                f"?family={family}&window=60"
            )
            while not done.is_set():
                try:
                    mid_run.append(
                        json.loads(
                            urllib.request.urlopen(url, timeout=10)
                            .read().decode()
                        )
                    )
                except Exception:
                    pass
                time.sleep(0.02)

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        try:
            runner.run()
            done.set()
            poller.join(timeout=5)
            index = json.loads(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/timeseries",
                    timeout=10,
                ).read().decode()
            )
        finally:
            done.set()
            loop.stop()
            server.stop()
            G.clear()
            timeseries.STORE.clear()
        assert mid_run, "no successful /timeseries query during the run"
        live = [r for r in mid_run if r["series"]]
        assert live, "no mid-run window carried recorded series"
        last = live[-1]
        assert last["family"] == family
        stats = {s["labels"].get("stat") for s in last["series"]}
        assert {"count", "p99"} <= stats
        for s in last["series"]:
            assert s["points"] == sorted(s["points"])
        # the index view reports families + bound accounting
        fams = {f["family"] for f in index["families"]}
        assert family in fams
        assert index["stats"]["series"] <= index["stats"]["max_series"]
        assert index["stats"]["points"] <= index["stats"]["max_points"]

    def test_latency_slo_burn_during_live_run(self, monkeypatch):
        """A live run whose ingest->sink latency violates a declared
        latency SLO must record a structured ``slo_burn`` event in the
        flight recorder (the machine-checkable chaos-leg verdict)."""
        G.clear()
        timeseries.STORE.clear()
        name = "live-ingest-latency"
        monkeypatch.setenv(
            "PATHWAY_TPU_SLO",
            json.dumps(
                [
                    {
                        "name": name,
                        "kind": "latency",
                        "family": (
                            "pathway_ingest_to_sink_latency_seconds"
                        ),
                        # any real commit takes longer than 1us: the
                        # budget burns immediately
                        "bound": 1e-6,
                        "budget": 0.01,
                        "window_s": 60.0,
                    }
                ]
            ),
        )
        monkeypatch.setenv("PATHWAY_TPU_TS_INTERVAL", "0.05")
        before = len(
            [e for e in _metrics.FLIGHT.snapshot()
             if e.get("kind") == "slo_burn" and e.get("slo") == name]
        )

        class Feed(pw.io.python.ConnectorSubject):
            def run(self):
                for i in range(30):
                    self.next(k=i % 3, v=i)
                    if i % 10 == 9:
                        self.commit()
                        time.sleep(0.1)

        t = pw.io.python.read(
            Feed(),
            schema=pw.schema_from_types(k=int, v=int),
            autocommit_duration_ms=None,
        )
        agg = t.groupby(pw.this.k).reduce(
            k=pw.this.k, s=pw.reducers.sum(pw.this.v)
        )
        pw.io.subscribe(agg, on_change=lambda *a, **k: None)
        try:
            pw.run(monitoring_level=MonitoringLevel.NONE)
        finally:
            G.clear()
            timeseries.SENTINEL.configure([])
            timeseries.STORE.clear()
        burns = [
            e for e in _metrics.FLIGHT.snapshot()
            if e.get("kind") == "slo_burn" and e.get("slo") == name
        ]
        assert len(burns) == before + 1, (
            "the live latency violation recorded no slo_burn event"
        )
        event = burns[-1]
        assert event["slo_kind"] == "latency"
        assert event["burn"] > 1.0
        assert event["bound"] == pytest.approx(1e-6)
        breaches = _metrics.REGISTRY.counter(
            "pathway_slo_breaches_total",
            "SLO burn events recorded by the sentinel",
            slo=name,
        )
        assert breaches.value >= 1
