"""Native kernel layer vs pure-Python reference: bit-identical or bust.

Every gen-2 kernel in pathway_tpu/native/enginecore.cpp keeps its Python
implementation alive as THE reference behavior; these tests drive both
paths over adversarial inputs (bigints crossing 2**127, NaN payload bits,
-0.0, tz-aware datetimes, Json/PyObjectWrapper/ERROR sentinels, low-64-bit
key collisions) and assert exact equality — digests byte for byte, index
arrays element for element, entries object for object.

The whole module skips when the kernels are absent (PATHWAY_TPU_DISABLE_NATIVE=1
runs the same workloads through the Python paths elsewhere in the suite).
"""

from __future__ import annotations

import datetime
import random
import struct

import numpy as np
import pytest

from pathway_tpu.engine.batch import Columns, DeltaBatch
from pathway_tpu.engine.routing import _shard_of, shards_of_values
from pathway_tpu.engine.value import (
    ERROR,
    Json,
    Pointer,
    PyObjectWrapper,
    _hash_values_batch_py,
    hash_values_batch,
    ref_scalar,
)
from pathway_tpu.native import kernels as _native

pytestmark = pytest.mark.skipif(
    _native is None, reason="native kernels disabled or unavailable"
)

UTC = datetime.timezone.utc


def _bits_to_float(bits: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", bits))[0]


_SCALAR_POOL = [
    None,
    True,
    False,
    0,
    1,
    -1,
    255,
    -(2**63),
    2**63,
    2**100,
    -(2**126),
    (2**127) - 1,  # largest digestable int
    0.0,
    -0.0,
    1.5,
    -2.25,
    1e300,
    float("inf"),
    float("-inf"),
    float("nan"),
    _bits_to_float(0x7FF8000000000000 | 0xBEEF),  # payload NaN
    9007199254740993.0,  # 2**53 + 1: float==int(float) boundary
    -9.223372036854776e18,  # just outside the signed-int16 fast path
    "",
    "hello",
    "héllo wörld",
    "日本語テキスト",
    b"",
    b"\x00\xff" * 3,
    (),
    (1, "two", 3.0),
    (1, (2, (3, (4,)))),
    [1, 2],
    ref_scalar(7),
    ref_scalar("x", 2),
    ERROR,
    datetime.datetime(2024, 5, 1, 12, 30),
    datetime.datetime(2024, 5, 1, 12, 30, tzinfo=UTC),
    datetime.timedelta(days=2, microseconds=5),
    Json({"a": [1, 2], "b": "c"}),
    PyObjectWrapper((1, 2)),
    np.int64(5),
    np.float64(2.5),
]


def _random_row(rng: random.Random) -> tuple:
    return tuple(
        rng.choice(_SCALAR_POOL) for _ in range(rng.randrange(0, 5))
    )


class TestHashTuplesBatch:
    def test_randomized_rows_match_python_reference(self):
        rng = random.Random(42)
        rows = [_random_row(rng) for _ in range(400)]
        for salt in (b"", b"shard", b"join"):
            want = _hash_values_batch_py(rows, salt=salt)
            got = hash_values_batch(rows, salt=salt)
            assert got.dtype == want.dtype and got.shape == want.shape
            assert (got == want).all()

    def test_object_ndarray_input(self):
        rng = random.Random(1)
        rows = [_random_row(rng) for _ in range(64)]
        arr = np.empty(len(rows), object)
        arr[:] = rows
        assert (
            hash_values_batch(arr) == _hash_values_batch_py(rows)
        ).all()

    def test_repr_fallback_mode(self):
        class Weird:
            def __repr__(self):
                return "<weird>"

        rows = [(Weird(),), (1, Weird()), ({"a": 1},), (1,)]
        want = _hash_values_batch_py(rows, on_type_error="repr")
        got = hash_values_batch(rows, on_type_error="repr")
        assert (got == want).all()

    def test_dict_values_digest_identically(self):
        # dicts have no tag of their own: both paths reach the
        # _H_PYOBJ + repr route and must agree byte for byte
        rows = [(1,), ({"a": 1, "b": [2]},)]
        assert (
            hash_values_batch(rows) == _hash_values_batch_py(rows)
        ).all()

    def test_raise_mode_propagates_type_error(self):
        class Boom:
            def __repr__(self):
                raise TypeError("unrepresentable")

        rows = [(1,), (Boom(),)]
        with pytest.raises(TypeError):
            hash_values_batch(rows, on_type_error="raise")
        with pytest.raises(TypeError):
            _hash_values_batch_py(rows, on_type_error="raise")

    def test_overflow_parity_past_2_127(self):
        # both paths refuse 16-byte-signed overflow identically
        for rows in ([(2**127,)], [(-(2**127) - 1,)]):
            with pytest.raises(OverflowError):
                _hash_values_batch_py(rows)
            with pytest.raises(OverflowError):
                hash_values_batch(rows)

    def test_bare_mode_matches_one_tuples(self):
        vals = [v for v in _SCALAR_POOL if not isinstance(v, list)]
        arr = np.empty(len(vals), object)
        arr[:] = vals
        from pathway_tpu.engine.routing import _bare_digest_fallback

        got = _native.hash_tuples_batch(
            arr, b"", True, Pointer, ERROR, _bare_digest_fallback
        )
        want = _hash_values_batch_py(
            [(v,) for v in vals], on_type_error="repr"
        )
        assert (got == want).all()


class TestShardValues:
    def test_randomized_values_match_shard_of(self):
        rng = random.Random(7)
        vals = [rng.choice(_SCALAR_POOL) for _ in range(300)]
        for n in (1, 2, 3, 7, 64):
            got = shards_of_values(vals, n)
            assert got.tolist() == [_shard_of(v, n) for v in vals]

    def test_pointer_subclass_falls_back_whole_call(self):
        class SubPtr(Pointer):
            pass

        vals = [SubPtr(5), ref_scalar(1), "x"]
        assert _native.shard_values(
            vals, b"shard", 3, Pointer, ERROR, lambda v: b"\0" * 16
        ) is None
        # the public wrapper still answers via the numpy path
        got = shards_of_values(vals, 3)
        assert got.tolist() == [_shard_of(v, 3) for v in vals]


class TestMatchPairs:
    def test_exact_ordering_vs_sort_matcher(self):
        from pathway_tpu.engine.graph import _match_join_pairs_multi

        rng = random.Random(3)
        for _ in range(120):
            k = rng.randrange(1, 4)
            nl, nr = rng.randrange(0, 30), rng.randrange(0, 30)
            lc = [
                np.array(
                    [rng.randrange(-3, 4) for _ in range(nl)], np.int64
                )
                for _ in range(k)
            ]
            rc = [
                np.array(
                    [rng.randrange(-3, 4) for _ in range(nr)], np.int64
                )
                for _ in range(k)
            ]
            li, ri = _native.match_pairs_i64(lc, rc)
            # reference: brute-force pairs in (probe asc, build asc) order
            probe_left = nl >= nr
            pairs = []
            outer, inner = (lc, rc) if probe_left else (rc, lc)
            for i in range(len(outer[0])):
                for j in range(len(inner[0])):
                    if all(o[i] == c[j] for o, c in zip(outer, inner)):
                        pairs.append((i, j) if probe_left else (j, i))
            assert list(zip(li.tolist(), ri.tolist())) == pairs
            # and the wired python entry point agrees
            li2, ri2 = _match_join_pairs_multi(lc, rc)
            assert li2.tolist() == li.tolist()
            assert ri2.tolist() == ri.tolist()

    def test_negative_zero_and_float_codes(self):
        from pathway_tpu.engine.graph import _as_match_codes

        f = np.array([0.0, -0.0, 1.5, 2.0])
        codes = _as_match_codes(f)
        assert codes is not None
        assert codes[0] == codes[1]  # -0.0 == 0.0 must match
        assert _as_match_codes(np.array([1.0, float("nan")])) is None
        u = np.array([0, 2**64 - 1, 5], np.uint64)
        cu = _as_match_codes(u)
        assert cu is not None and len(np.unique(cu)) == 3


class TestEntriesToSide:
    def _entries(self, n, val=lambda i: (i, float(i), i % 2 == 0)):
        return [(ref_scalar(i), val(i), 1) for i in range(n)]

    def test_typed_columns_and_keys(self):
        entries = self._entries(10)
        got = _native.entries_to_side(entries, [0, 2], 3, Pointer)
        assert got is not None
        kb, cols = got
        want_kb = np.frombuffer(
            b"".join(int(e[0]).to_bytes(16, "little") for e in entries),
            np.uint8,
        ).reshape(10, 16)
        assert (kb == want_kb).all()
        assert cols[0].dtype == np.int64 and cols[0].tolist() == list(range(10))
        assert cols[1].dtype == np.float64
        assert cols[2].dtype == np.bool_
        assert cols[2].tolist() == [i % 2 == 0 for i in range(10)]

    def test_bails_preserve_python_path(self):
        # non-unit diff
        bad = self._entries(3)
        bad[1] = (bad[1][0], bad[1][1], -1)
        assert _native.entries_to_side(bad, [0], 3, Pointer) is None
        # non-Pointer key
        assert (
            _native.entries_to_side([(1, (2,), 1)], [0], 1, Pointer) is None
        )
        # string join key column has no typed array: whole-call bail
        assert (
            _native.entries_to_side(
                [(ref_scalar(0), ("a",), 1)], [0], 1, Pointer
            )
            is None
        )

    def test_bigint_payload_column_degrades_to_objects(self):
        entries = [
            (ref_scalar(i), (i, 2**70 + i), 1) for i in range(4)
        ]
        got = _native.entries_to_side(entries, [0], 2, Pointer)
        assert got is not None
        _kb, cols = got
        assert cols[1].dtype == object
        assert cols[1].tolist() == [2**70 + i for i in range(4)]
        # bigint in the JOIN KEY column itself cannot be typed: bail
        assert _native.entries_to_side(entries, [1], 2, Pointer) is None


class TestSessionOverlay:
    def _reference(self, buffer, state, upsert):
        out = []
        overlay: dict = {}

        def effective(key):
            if key in overlay:
                return overlay[key]
            return state.get(key)

        if upsert:
            for key, row, diff in buffer:
                prev = effective(key)
                if diff > 0:
                    if prev is not None:
                        out.append((key, prev, -1))
                    out.append((key, row, 1))
                    overlay[key] = row
                elif prev is not None:
                    out.append((key, prev, -1))
                    overlay[key] = None
        else:
            for key, row, diff in buffer:
                if diff < 0 and row is None:
                    row = effective(key)
                    if row is None:
                        continue
                if diff > 0:
                    overlay[key] = row
                elif effective(key) == row:
                    overlay[key] = None
                out.append((key, row, diff))
        return out

    @pytest.mark.parametrize("upsert", [False, True])
    def test_randomized_commits_match_reference(self, upsert):
        rng = random.Random(11 + upsert)
        for _ in range(150):
            keys = [ref_scalar(i) for i in range(rng.randrange(1, 6))]
            state = {
                k: ("old", int(k) % 97)
                for k in keys
                if rng.random() < 0.5
            }
            buffer = []
            for _ in range(rng.randrange(0, 12)):
                k = rng.choice(keys)
                if rng.random() < 0.6:
                    buffer.append((k, ("new", rng.randrange(5)), 1))
                elif upsert or rng.random() < 0.5:
                    buffer.append((k, None, -1))
                else:
                    buffer.append((k, ("new", rng.randrange(5)), -1))
            got = _native.session_overlay(list(buffer), dict(state), upsert)
            assert got == self._reference(buffer, state, upsert)

    def test_flush_end_to_end(self):
        import pathway_tpu as pw  # noqa: F401 — ensures graph wiring imports

        from pathway_tpu.engine.graph import InputSession, Scope

        scope = Scope()
        sess = InputSession(scope, 2, upsert=True)
        k1, k2 = ref_scalar(1), ref_scalar(2)
        sess.insert(k1, ("a", 1))
        sess.insert(k2, ("b", 2))
        sess.insert(k1, ("a2", 3))  # retracts ("a", 1) first
        sess.remove(k2)
        batch = sess.flush()
        assert sorted(batch.entries, key=lambda e: (int(e[0]), e[2])) == sorted(
            [(k1, ("a2", 3), 1)], key=lambda e: (int(e[0]), e[2])
        )


class TestConsolidateParity:
    def test_low64_colliding_pointers(self):
        # two distinct keys sharing their low 64 bits: the uniqueness
        # screen's cheap pass collides, the full pass must split them
        a = Pointer((1 << 100) | 12345)
        b = Pointer((2 << 100) | 12345)
        assert int(a) & ((1 << 64) - 1) == int(b) & ((1 << 64) - 1)
        from pathway_tpu.engine.graph import _keys_unique

        kb = np.frombuffer(
            int(a).to_bytes(16, "little") + int(b).to_bytes(16, "little"),
            np.uint8,
        ).reshape(2, 16)
        assert _keys_unique(kb, 2)
        dup = np.frombuffer(
            int(a).to_bytes(16, "little") * 2, np.uint8
        ).reshape(2, 16)
        assert not _keys_unique(dup, 2)
        batch = DeltaBatch([(a, (1,), 1), (b, (1,), 1), (a, (1,), 1)])
        got = batch.consolidate()
        assert sorted(got.entries) == sorted([(a, (1,), 2), (b, (1,), 1)])

    def test_columnar_consolidate_matches_row_consolidate(self):
        rng = random.Random(23)
        for _ in range(60):
            n = rng.randrange(0, 30)
            keys = [ref_scalar(rng.randrange(max(1, n // 2) or 1)) for _ in range(n)]
            c0 = np.array([rng.randrange(3) for _ in range(n)], np.int64)
            c1 = np.array([rng.choice(["x", "y"]) for _ in range(n)])
            diffs = (
                None
                if rng.random() < 0.3
                else np.array(
                    [rng.choice([-1, 1, 1, 2]) for _ in range(n)],
                    np.int64,
                )
            )
            kb = np.frombuffer(
                b"".join(int(k).to_bytes(16, "little") for k in keys),
                np.uint8,
            ).reshape(n, 16).copy() if n else np.empty((0, 16), np.uint8)
            cbatch = DeltaBatch.from_columns(
                Columns(n, [c0, c1], kbytes=kb, diffs=diffs),
                consolidated=False,
                insert_only=False,
            )
            rbatch = DeltaBatch(
                list(
                    zip(
                        keys,
                        zip(c0.tolist(), c1.tolist()),
                        diffs.tolist() if diffs is not None else [1] * n,
                    )
                )
            )
            got = cbatch.consolidate()
            # the merge ran columnar: row entries were never materialised
            assert cbatch._entries is None
            assert list(got.entries) == list(rbatch.consolidate().entries)

    def test_columnar_consolidate_bails_on_value_bit_divergence(self):
        kb = np.frombuffer(
            int(ref_scalar(0)).to_bytes(16, "little")
            + int(ref_scalar(1)).to_bytes(16, "little"),
            np.uint8,
        ).reshape(2, 16)
        for col in (
            np.array([1.0, float("nan")]),
            np.array([0.0, -0.0]),
            np.array([object(), object()], dtype=object),
        ):
            batch = DeltaBatch.from_columns(
                Columns(
                    2, [col], kbytes=kb, diffs=np.array([1, -1], np.int64)
                ),
                consolidated=False,
            )
            assert batch._consolidate_columns() is None


class TestBuildFromSource:
    def test_recompiled_kernels_match(self, tmp_path):
        """The shipped .so is a cache, not the artifact: recompile
        enginecore.cpp from source in a temp dir and spot-check digests
        against the in-process module."""
        import importlib.util
        import shutil
        import subprocess
        import sysconfig

        from pathway_tpu import native as native_pkg

        src = tmp_path / "enginecore.cpp"
        shutil.copyfile(native_pkg._SRC, src)
        so = tmp_path / "fresh_enginecore.so"
        cmd = [
            "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
            f"-I{sysconfig.get_paths()['include']}",
            f"-I{np.get_include()}",
            str(src), "-o", str(so),
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        spec = importlib.util.spec_from_file_location("_enginecore", so)
        fresh = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(fresh)
        rng = random.Random(5)
        rows = [_random_row(rng) for _ in range(64)]

        def row_fb(row):
            from pathway_tpu.engine.value import _digest16

            return _digest16(row, b"")

        got = fresh.hash_tuples_batch(rows, b"", False, Pointer, ERROR, row_fb)
        want = _hash_values_batch_py(rows)
        assert (got == want).all()
        assert set(fresh.hit_counts()) == set(_native.hit_counts())


class TestHitCounters:
    def test_native_engages_on_groupby_join(self):
        """End-to-end smoke: a groupby + join pipeline must actually HIT
        the native kernels, not silently run the Python fallbacks."""
        import pathway_tpu as pw
        from pathway_tpu import native
        from pathway_tpu.internals.parse_graph import G

        G.clear()
        native.reset_hit_counts()
        rows = [(i % 7, i, float(i)) for i in range(200)]
        t = pw.debug.table_from_rows(
            pw.schema_from_types(g=int, k=int, v=float), rows
        )
        agg = t.groupby(t.g).reduce(t.g, total=pw.reducers.sum(t.v))
        joined = t.join(agg, t.g == agg.g).select(
            t.k, total=pw.right.total
        )
        df = pw.debug.table_to_pandas(joined)
        assert len(df) == 200
        hits = native.hit_counts()
        assert any(v > 0 for v in hits.values()), hits
        # the join matcher or the side builder engaged natively
        assert (
            hits.get("match_pairs_i64", 0)
            + hits.get("entries_to_side", 0)
            + hits.get("join_insert_inner", 0)
            + hits.get("hash_join_pairs", 0)
        ) > 0, hits
        G.clear()

    def test_counts_move_and_reset(self):
        from pathway_tpu import native

        native.reset_hit_counts()
        before = native.hit_counts()
        assert before and all(v == 0 for v in before.values())
        hash_values_batch([(1, "a"), (2, "b")])
        after = native.hit_counts()
        assert after["hash_tuples_batch"] == 1
        native.reset_hit_counts()
        assert all(v == 0 for v in native.hit_counts().values())
