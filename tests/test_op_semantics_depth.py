"""Depth tests for core operator semantics, mirroring the reference's
test_common.py / test_joins.py / test_reducers.py coverage style
(reference python/pathway/tests/): golden markdown tables through the real
engine, exercising edge cases the broad API tests skip — duplicate join
keys, retraction-driven reducer recomputes, outer-join None handling,
multi-column keys, concat/update corner cases, expression edge semantics.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from tests.utils import (
    T,
    assert_table_equality_wo_index,
    run_tables,
)


def rows_of(table):
    (snap,) = run_tables(table)
    return sorted(snap.values(), key=repr)


def srt(rows):
    return sorted(rows, key=repr)


# -- joins --------------------------------------------------------------------


class TestJoinDepth:
    def test_inner_join_duplicate_keys_cross_product(self):
        left = T(
            """
            k | a
            1 | x
            1 | y
            2 | z
            """
        )
        right = T(
            """
            k | b
            1 | p
            1 | q
            """
        )
        j = left.join(right, pw.left.k == pw.right.k).select(
            a=pw.left.a, b=pw.right.b
        )
        assert rows_of(j) == srt(
            [("x", "p"), ("x", "q"), ("y", "p"), ("y", "q")]
        )

    def test_multi_column_join_key(self):
        left = T(
            """
            k1 | k2 | a
            1  | 1  | x
            1  | 2  | y
            """
        )
        right = T(
            """
            k1 | k2 | b
            1  | 1  | p
            1  | 3  | q
            """
        )
        j = left.join(
            right,
            pw.left.k1 == pw.right.k1,
            pw.left.k2 == pw.right.k2,
        ).select(a=pw.left.a, b=pw.right.b)
        assert rows_of(j) == [("x", "p")]

    def test_left_join_unmatched_fills_none(self):
        left = T(
            """
            k | a
            1 | x
            2 | y
            """
        )
        right = T(
            """
            k | b
            1 | p
            """
        )
        j = left.join_left(right, pw.left.k == pw.right.k).select(
            a=pw.left.a, b=pw.right.b
        )
        assert rows_of(j) == srt([("x", "p"), ("y", None)])

    def test_right_join_unmatched_fills_none(self):
        left = T(
            """
            k | a
            1 | x
            """
        )
        right = T(
            """
            k | b
            1 | p
            3 | r
            """
        )
        j = left.join_right(right, pw.left.k == pw.right.k).select(
            a=pw.left.a, b=pw.right.b
        )
        assert rows_of(j) == srt([(None, "r"), ("x", "p")])

    def test_outer_join_both_sides(self):
        left = T(
            """
            k | a
            1 | x
            2 | y
            """
        )
        right = T(
            """
            k | b
            2 | p
            3 | q
            """
        )
        j = left.join_outer(right, pw.left.k == pw.right.k).select(
            a=pw.left.a, b=pw.right.b
        )
        assert rows_of(j) == srt([(None, "q"), ("x", None), ("y", "p")])

    def test_self_join(self):
        t = T(
            """
            a | b
            1 | 2
            2 | 3
            3 | 4
            """
        )
        j = t.join(t.copy(), pw.left.b == pw.right.a).select(
            first=pw.left.a, second=pw.right.b
        )
        assert rows_of(j) == [(1, 3), (2, 4)]

    def test_join_then_groupby(self):
        orders = T(
            """
            cust | amount
            a    | 10
            a    | 20
            b    | 5
            """
        )
        names = T(
            """
            cust | name
            a    | alice
            b    | bob
            """
        )
        j = orders.join(names, pw.left.cust == pw.right.cust).select(
            name=pw.right.name, amount=pw.left.amount
        )
        totals = j.groupby(pw.this.name).reduce(
            name=pw.this.name, total=pw.reducers.sum(pw.this.amount)
        )
        assert rows_of(totals) == [("alice", 30), ("bob", 5)]

    def test_join_id_deterministic(self):
        """Join row ids derive from the operand ids: equal inputs =>
        equal output ids across two identical joins."""
        left = T(
            """
            k | a
            1 | x
            """
        )
        right = T(
            """
            k | b
            1 | p
            """
        )
        j1 = left.join(right, pw.left.k == pw.right.k).select(a=pw.left.a)
        j2 = left.join(right, pw.left.k == pw.right.k).select(a=pw.left.a)
        s1, s2 = run_tables(j1, j2)
        assert set(s1.keys()) == set(s2.keys())

    def test_duplicate_custom_join_id_winner_insertion_order_independent(
        self,
    ):
        """Two rows in DIFFERENT join-key groups claim the same custom
        result id: exactly one survives, and which one must not depend on
        the order the rows were inserted — group visitation is repr-sorted,
        so the k=1 group wins in every run, process and insertion order."""

        def run(rows):
            left = pw.debug.table_from_rows(
                pw.schema_from_types(k=int, tag=str), rows
            )
            keyed = left.select(
                k=pw.this.k,
                tag=pw.this.tag,
                # every row claims the SAME result id
                rid=left.pointer_from(pw.this.k * 0),
            )
            right = pw.debug.table_from_rows(
                pw.schema_from_types(k=int), [(1,), (2,)]
            )
            j = keyed.join(
                right, keyed.k == right.k, id=keyed.rid
            ).select(keyed.tag)
            (snap,) = run_tables(j)
            return sorted(snap.values())

        rows = [(1, "first"), (2, "second")]
        winner = run(rows)
        assert winner == [("first",)]  # repr-least join key owns the id
        assert run(list(reversed(rows))) == winner


# -- reducers under retraction ------------------------------------------------


class TestReducerRetraction:
    """min/max/argmin/unique must recompute correctly when the current
    extremum is retracted (reference reduce.rs per-reducer impls)."""

    def _streamed(self, reducer_expr_fn, values_then_removed):
        """Insert all values, then retract some, via the engine API."""
        from pathway_tpu.engine import (
            ReducerKind,
            Scheduler,
            Scope,
            make_reducer,
            ref_scalar,
        )

        values, removed = values_then_removed
        scope = Scope()
        sess = scope.input_session(2)
        gb = scope.group_by_table(
            sess,
            by_cols=[0],
            reducers=[(make_reducer(reducer_expr_fn), [1])],
        )
        sched = Scheduler(scope)
        for i, v in enumerate(values):
            sess.insert(ref_scalar(i), (0, v))
        sched.commit()
        for i, v in removed:
            sess.remove(ref_scalar(i), (0, v))
        sched.commit()
        states = list(gb.current.values())
        assert len(states) == 1
        return states[0][1]

    def test_max_retraction_recomputes(self):
        from pathway_tpu.engine import ReducerKind

        vals = [5, 9, 3]
        out = self._streamed(ReducerKind.MAX, (vals, [(1, 9)]))
        assert out == 5

    def test_min_retraction_recomputes(self):
        from pathway_tpu.engine import ReducerKind

        vals = [5, 2, 7]
        out = self._streamed(ReducerKind.MIN, (vals, [(1, 2)]))
        assert out == 5

    def test_unique_becomes_valid_after_retraction(self):
        """unique errors while two distinct values coexist, and recovers
        when one is retracted."""
        from pathway_tpu.engine import (
            ReducerKind,
            Scheduler,
            Scope,
            make_reducer,
            ref_scalar,
        )
        from pathway_tpu.engine.value import is_error

        scope = Scope()
        sess = scope.input_session(2)
        gb = scope.group_by_table(
            sess,
            by_cols=[0],
            reducers=[(make_reducer(ReducerKind.UNIQUE), [1])],
        )
        sched = Scheduler(scope)
        sess.insert(ref_scalar(1), (0, "a"))
        sess.insert(ref_scalar(2), (0, "b"))
        sched.commit()
        (state,) = gb.current.values()
        assert is_error(state[1])
        sess.remove(ref_scalar(2), (0, "b"))
        sched.commit()
        (state,) = gb.current.values()
        assert state[1] == "a"

    def test_sorted_tuple_and_tuple(self):
        t = T(
            """
            g | v
            a | 3
            a | 1
            a | 2
            """
        )
        r = t.groupby(pw.this.g).reduce(
            g=pw.this.g,
            st=pw.reducers.sorted_tuple(pw.this.v),
        )
        assert rows_of(r) == [("a", (1, 2, 3))]

    def test_count_distinct(self):
        t = T(
            """
            g | v
            a | 1
            a | 1
            a | 2
            b | 9
            """
        )
        r = t.groupby(pw.this.g).reduce(
            g=pw.this.g, n=pw.reducers.count_distinct(pw.this.v)
        )
        assert rows_of(r) == [("a", 2), ("b", 1)]

    def test_argmax_returns_row_id(self):
        t = T(
            """
            g | v
            a | 3
            a | 7
            """
        )
        r = t.groupby(pw.this.g).reduce(
            g=pw.this.g, best=pw.reducers.argmax(pw.this.v)
        )
        (snap_r, snap_t) = run_tables(r, t)
        ((_g, best),) = snap_r.values()
        assert snap_t[best] == ("a", 7)

    def test_avg_floats(self):
        t = T(
            """
            g | v
            a | 1.0
            a | 2.0
            a | 4.0
            """
        )
        r = t.groupby(pw.this.g).reduce(
            g=pw.this.g, m=pw.reducers.avg(pw.this.v)
        )
        assert rows_of(r) == [("a", pytest.approx(7.0 / 3.0))]


# -- table-op corners ---------------------------------------------------------


class TestTableOpCorners:
    def test_concat_disjoint_then_filter(self):
        a = T(
            """
            v
            1
            2
            """
        )
        b = T(
            """
            v
            3
            4
            """
        )
        c = a.concat_reindex(b).filter(pw.this.v % 2 == 0)
        assert rows_of(c) == [(2,), (4,)]

    def test_update_rows_overrides_and_extends(self):
        base = T(
            """
            k | v
            1 | 10
            2 | 20
            """
        ).with_id_from(pw.this.k)
        patch = T(
            """
            k | v
            2 | 99
            3 | 30
            """
        ).with_id_from(pw.this.k)
        merged = base.update_rows(patch)
        assert rows_of(merged) == [(1, 10), (2, 99), (3, 30)]

    def test_intersect_and_difference(self):
        a = T(
            """
            k | v
            1 | 10
            2 | 20
            3 | 30
            """
        ).with_id_from(pw.this.k)
        b = T(
            """
            k | w
            2 | x
            3 | y
            4 | z
            """
        ).with_id_from(pw.this.k)
        inter = a.intersect(b)
        diff = a.difference(b)
        assert rows_of(inter) == [(2, 20), (3, 30)]
        assert rows_of(diff) == [(1, 10)]

    def test_flatten_empty_iterables_drop_rows(self):
        t = pw.debug.table_from_rows(
            pw.schema_from_types(k=int, vals=tuple),
            [(1, (10, 11)), (2, ()), (3, (30,))],
        )
        f = t.flatten(pw.this.vals)
        assert sorted(r[-1] for r in rows_of(f)) == [10, 11, 30]

    def test_restrict_to_subset_universe(self):
        a = T(
            """
            k | v
            1 | 10
            2 | 20
            3 | 30
            """
        ).with_id_from(pw.this.k)
        small = a.filter(pw.this.v > 15)
        r = a.restrict(small)
        assert rows_of(r) == [(2, 20), (3, 30)]

    def test_rename_and_without(self):
        t = T(
            """
            a | b | c
            1 | 2 | 3
            """
        )
        r = t.rename_columns(x=pw.this.a).without(pw.this.b)
        assert set(r.column_names()) == {"x", "c"}

    def test_having_filters_to_present_keys(self):
        items = T(
            """
            k | v
            1 | 10
            2 | 20
            3 | 30
            """
        ).with_id_from(pw.this.k)
        keys = T(
            """
            k
            1
            3
            """
        ).with_id_from(pw.this.k)
        assert rows_of(items.having(keys.id)) == [(1, 10), (3, 30)]

    def test_groupby_multiple_columns(self):
        t = T(
            """
            a | b | v
            1 | x | 10
            1 | x | 1
            1 | y | 2
            2 | x | 3
            """
        )
        r = t.groupby(pw.this.a, pw.this.b).reduce(
            a=pw.this.a,
            b=pw.this.b,
            s=pw.reducers.sum(pw.this.v),
        )
        assert rows_of(r) == [(1, "x", 11), (1, "y", 2), (2, "x", 3)]


# -- expression edge semantics ------------------------------------------------


class TestExpressionEdges:
    def test_integer_division_and_modulo_negative(self):
        t = T(
            """
            a  | b
            -7 | 2
            7  | -2
            """
        )
        r = t.select(q=pw.this.a // pw.this.b, m=pw.this.a % pw.this.b)
        # Python floor-division semantics (reference BinaryOp on Int)
        assert rows_of(r) == srt([(-4, 1), (-4, -1)])

    def test_division_by_zero_poisons_only_that_row(self):
        t = T(
            """
            a | b
            6 | 2
            6 | 0
            """
        )
        r = t.select(q=pw.fill_error(pw.this.a // pw.this.b, -1))
        assert rows_of(r) == srt([(-1,), (3,)])

    def test_coalesce_and_is_none(self):
        t = pw.debug.table_from_rows(
            pw.schema_from_types(a=int | None),
            [(1,), (None,)],
        )
        r = t.select(v=pw.coalesce(pw.this.a, 0))
        assert rows_of(r) == srt([(0,), (1,)])

    def test_boolean_chain_short_circuits_row_wise(self):
        t = T(
            """
            a | b
            0 | 1
            2 | 3
            """
        )
        r = t.filter((pw.this.a > 0) & (pw.this.b > 2))
        assert rows_of(r) == [(2, 3)]

    def test_make_tuple_and_index(self):
        t = T(
            """
            a | b
            1 | 2
            """
        )
        r = t.select(pair=pw.make_tuple(pw.this.a, pw.this.b))
        assert rows_of(r) == [((1, 2),)]

    def test_string_mult_and_slicing_via_apply(self):
        t = T(
            """
            s
            abc
            """
        )
        r = t.select(u=pw.apply(lambda s: s[::-1].upper(), pw.this.s))
        assert rows_of(r) == [("CBA",)]

    def test_if_else_branch_types(self):
        t = T(
            """
            a
            1
            5
            """
        )
        r = t.select(v=pw.if_else(pw.this.a > 3, pw.this.a * 10, 0))
        assert rows_of(r) == [(0,), (50,)]

    def test_pointer_from_roundtrip(self):
        t = T(
            """
            k | v
            1 | 10
            """
        ).with_id_from(pw.this.k)
        r = t.select(p=t.pointer_from(pw.this.k))
        (snap_r, snap_t) = run_tables(r, t)
        ((ptr,),) = snap_r.values()
        assert ptr in snap_t


# -- streaming update-stream assertions --------------------------------------


class TestUpdateStreams:
    def test_groupby_update_stream_retracts_superseded(self):
        """Each commit's aggregate supersedes the last: update stream shows
        (old, -1), (new, +1) pairs (DiffEntry-style assertion)."""
        from pathway_tpu.engine import Scheduler, Scope, ref_scalar
        from pathway_tpu.engine import ReducerKind, make_reducer

        scope = Scope()
        sess = scope.input_session(2)
        gb = scope.group_by_table(
            sess,
            by_cols=[0],
            reducers=[(make_reducer(ReducerKind.SUM), [1])],
        )
        log = []
        scope.subscribe_table(
            gb, on_change=lambda k, row, t, d: log.append((row, t, d))
        )
        sched = Scheduler(scope)
        sess.insert(ref_scalar(1), ("g", 10))
        sched.commit()
        sess.insert(ref_scalar(2), ("g", 5))
        sched.commit()
        assert log == [
            (("g", 10), 0, 1),
            (("g", 10), 1, -1),
            (("g", 15), 1, 1),
        ]

    def test_filter_update_stream_row_leaves_and_reenters(self):
        from pathway_tpu.engine import Scheduler, Scope, ref_scalar
        from pathway_tpu.engine import expression as ex

        scope = Scope()
        sess = scope.input_session(1)
        cond = scope.expression_table(
            sess,
            [ex.ColumnRef(0), ex.Binary(">", ex.ColumnRef(0), ex.Const(5))],
        )
        flt = scope.filter_table(cond, 1)
        log = []
        scope.subscribe_table(
            flt, on_change=lambda k, row, t, d: log.append((row[0], t, d))
        )
        sched = Scheduler(scope)
        key = ref_scalar("x")
        sess.insert(key, (10,))
        sched.commit()
        sess.remove(key, (10,))
        sess.insert(key, (3,))
        sched.commit()
        sess.remove(key, (3,))
        sess.insert(key, (7,))
        sched.commit()
        assert log == [(10, 0, 1), (10, 1, -1), (7, 2, 1)]


# -- temporal depth -----------------------------------------------------------


class TestTemporalDepth:
    def test_sliding_window_row_in_multiple_windows(self):
        import pathway_tpu.stdlib.temporal as tmp

        t = T(
            """
            t | v
            0 | 1
            3 | 1
            5 | 1
            """
        )
        win = t.windowby(
            pw.this.t, window=tmp.sliding(hop=2, duration=4)
        ).reduce(
            start=pw.this._pw_window_start, cnt=pw.reducers.count()
        )
        got = dict(rows_of(win))
        # t=3 lands in windows starting at 0 and 2; t=5 in 2 and 4
        assert got[0] == 2 and got[2] == 2 and got[4] == 1

    def test_session_windows_merge_on_bridge_row(self):
        """Two separated sessions merge when a bridging event arrives."""
        import pathway_tpu.stdlib.temporal as tmp

        t = T(
            """
            t  | v
            0  | 1
            1  | 1
            10 | 1
            5  | 1
            """
        )
        win = t.windowby(pw.this.t, window=tmp.session(max_gap=6)).reduce(
            cnt=pw.reducers.count()
        )
        # gaps: 0-1-5-10 all within 6 => ONE session of 4 rows
        assert rows_of(win) == [(4,)]

    def test_tumbling_negative_times_and_origin(self):
        import pathway_tpu.stdlib.temporal as tmp

        t = T(
            """
            t  | v
            -5 | 1
            -1 | 1
            1  | 1
            """
        )
        win = t.windowby(
            pw.this.t, window=tmp.tumbling(duration=4)
        ).reduce(
            start=pw.this._pw_window_start, cnt=pw.reducers.count()
        )
        got = dict(rows_of(win))
        assert got == {-8: 1, -4: 1, 0: 1}

    def test_interval_join_asymmetric_bounds(self):
        import pathway_tpu.stdlib.temporal as tmp

        left = T(
            """
            t | a
            4 | x
            """
        )
        right = T(
            """
            t | b
            1 | p
            3 | q
            6 | r
            """
        )
        j = left.interval_join(
            right,
            pw.left.t,
            pw.right.t,
            tmp.interval(-3, 1),
        ).select(a=pw.left.a, b=pw.right.b)
        assert rows_of(j) == srt([("x", "p"), ("x", "q")])

    def test_intervals_over_samples_surrounding_rows(self):
        import pathway_tpu.stdlib.temporal as tmp

        data = T(
            """
            t  | v
            0  | 1
            4  | 2
            8  | 3
            12 | 4
            """
        )
        probes = T(
            """
            t
            5
            """
        )
        r = data.windowby(
            data.t,
            window=tmp.intervals_over(
                at=probes.t, lower_bound=-4, upper_bound=4
            ),
        ).reduce(vals=pw.reducers.sorted_tuple(pw.this.v))
        # window [1, 9] around t=5 catches v=2 (t=4) and v=3 (t=8)
        assert [row[-1] for row in rows_of(r)] == [(2, 3)]

    def test_window_join_tumbling(self):
        import pathway_tpu.stdlib.temporal as tmp

        left = T(
            """
            t | a
            1 | x
            5 | y
            """
        )
        right = T(
            """
            t | b
            2 | p
            9 | q
            """
        )
        j = left.window_join(
            right, pw.left.t, pw.right.t, tmp.tumbling(duration=4)
        ).select(a=pw.left.a, b=pw.right.b)
        # window [0,4): (x,p); windows [4,8) and [8,12) have one side only
        assert rows_of(j) == [("x", "p")]


# -- SQL depth ----------------------------------------------------------------


class TestSqlDepth:
    def _t(self):
        return T(
            """
            name  | dept | salary
            alice | eng  | 100
            bob   | eng  | 80
            carol | ops  | 60
            """
        )

    def test_where_string_literal_and_parens(self):
        r = pw.sql(
            "SELECT name FROM t WHERE (dept = 'eng' AND salary > 90) OR dept = 'ops'",
            t=self._t(),
        )
        assert rows_of(r) == [("alice",), ("carol",)]

    def test_group_by_avg_alias(self):
        r = pw.sql(
            "SELECT dept, AVG(salary) AS pay FROM t GROUP BY dept",
            t=self._t(),
        )
        assert rows_of(r) == [("eng", 90.0), ("ops", 60.0)]

    def test_union_all_keeps_duplicates(self):
        t = self._t()
        r = pw.sql(
            "SELECT dept FROM t UNION ALL SELECT dept FROM t", t=t
        )
        assert len(rows_of(r)) == 6

    def test_arithmetic_in_projection(self):
        r = pw.sql(
            "SELECT name, salary * 2 + 1 AS double FROM t WHERE name = 'bob'",
            t=self._t(),
        )
        assert rows_of(r) == [("bob", 161)]

    def test_having_on_aggregate(self):
        r = pw.sql(
            "SELECT dept, SUM(salary) AS total FROM t GROUP BY dept "
            "HAVING SUM(salary) > 100",
            t=self._t(),
        )
        assert rows_of(r) == [("eng", 180)]

    def test_count_star(self):
        r = pw.sql("SELECT dept, COUNT(*) AS n FROM t GROUP BY dept", t=self._t())
        assert rows_of(r) == [("eng", 2), ("ops", 1)]


class TestWindowJoinSelectForms:
    """WindowJoinResult.select accepts bare strings (left column), pw.left/
    pw.right sentinels, and direct refs to the original tables."""

    def _join(self):
        import pathway_tpu.stdlib.temporal as tmp

        left = T(
            """
            t | a
            1 | x
            """
        )
        right = T(
            """
            t | b
            2 | p
            """
        )
        return left, right, left.window_join(
            right, left.t, right.t, tmp.tumbling(duration=4)
        )

    def test_string_kwarg_is_left_column(self):
        _l, _r, j = self._join()
        assert rows_of(j.select(a="a")) == [("x",)]

    def test_sentinels_and_direct_refs(self):
        left, right, j = self._join()
        assert rows_of(
            j.select(a=pw.left.a, b=pw.right.b, t2=left.t + right.t)
        ) == [("x", "p", 3)]


class TestTemporalJoinModes:
    """Left/right/outer temporal join modes (reference _interval_join.py
    interval_join_left/right/outer, _asof_join.py)."""

    def _lr(self):
        left = T(
            """
            t  | a
            1  | x
            10 | y
            """
        )
        right = T(
            """
            t | b
            2 | p
            """
        )
        return left, right

    def test_interval_join_left_pads_unmatched(self):
        import pathway_tpu.stdlib.temporal as tmp

        left, right = self._lr()
        j = tmp.interval_join_left(
            left, right, left.t, right.t, tmp.interval(-2, 2)
        ).select(a=pw.left.a, b=pw.right.b)
        assert rows_of(j) == srt([("x", "p"), ("y", None)])

    def test_interval_join_outer_pads_both(self):
        import pathway_tpu.stdlib.temporal as tmp

        left = T(
            """
            t  | a
            10 | y
            """
        )
        right = T(
            """
            t | b
            2 | p
            """
        )
        j = tmp.interval_join_outer(
            left, right, left.t, right.t, tmp.interval(-2, 2)
        ).select(a=pw.left.a, b=pw.right.b)
        assert rows_of(j) == srt([("y", None), (None, "p")])

    def test_interval_join_with_equality_condition(self):
        import pathway_tpu.stdlib.temporal as tmp

        left = T(
            """
            t | g | a
            1 | u | x
            1 | v | y
            """
        )
        right = T(
            """
            t | g | b
            2 | u | p
            """
        )
        j = left.interval_join(
            right,
            pw.left.t,
            pw.right.t,
            tmp.interval(-2, 2),
            pw.left.g == pw.right.g,
        ).select(a=pw.left.a, b=pw.right.b)
        assert rows_of(j) == [("x", "p")]

    def test_asof_join_forward_and_nearest(self):
        import pathway_tpu.stdlib.temporal as tmp

        left = T(
            """
            t | a
            5 | x
            """
        )
        right = T(
            """
            t  | b
            3  | early
            6  | late
            20 | far
            """
        )
        fwd = tmp.asof_join(
            left, right, left.t, right.t, direction="forward"
        ).select(a=pw.left.a, b=pw.right.b)
        assert rows_of(fwd) == [("x", "late")]
        near = tmp.asof_join(
            left, right, left.t, right.t, direction="nearest"
        ).select(a=pw.left.a, b=pw.right.b)
        assert rows_of(near) == [("x", "late")]  # |6-5| < |5-3|... no: 1 < 2

    def test_window_behavior_keep_results_false_drops_expired(self):
        """cutoff with keep_results=False retracts expired windows entirely
        at end of stream (reference TimeColumnForget)."""
        import pathway_tpu.stdlib.temporal as tmp
        from pathway_tpu.debug import StreamGenerator

        gen = StreamGenerator()
        t = gen.table_from_list_of_batches(
            [
                [{"t": 1}],
                [{"t": 25}],   # watermark far past window [0, 10)
                [{"t": 3}],    # late: dropped by cutoff
            ],
            pw.schema_from_types(t=int),
        )
        win = t.windowby(
            pw.this.t,
            window=tmp.tumbling(duration=10),
            behavior=tmp.common_behavior(cutoff=0, keep_results=True),
        ).reduce(
            start=pw.this["_pw_window_start"], n=pw.reducers.count()
        )
        (snap,) = run_tables(win)
        got = dict(snap.values())
        assert got[0] == 1  # late t=3 never counted
        assert got[20] == 1


class TestStdlibStatefulOrdered:
    """pw.statistical.interpolate / pw.ordered.diff / pw.stateful.deduplicate
    (reference stdlib/{statistical,ordered,stateful})."""

    def test_interpolate_fills_interior_and_boundaries(self):
        from pathway_tpu.stdlib.statistical import interpolate

        t = pw.debug.table_from_rows(
            pw.schema_from_types(ts=int, v=float | None),
            [(0, None), (1, 10.0), (2, None), (3, 30.0), (4, None)],
        )
        r = interpolate(t, pw.this.ts, pw.this.v)
        got = {row[0]: row[1] for row in rows_of(r)}
        assert got[1] == 10.0 and got[3] == 30.0
        assert got[2] == 20.0          # linear midpoint
        assert got[0] == 10.0 and got[4] == 30.0  # boundary nearest

    def test_ordered_diff_per_instance(self):
        from pathway_tpu.stdlib.ordered import diff

        t = pw.debug.table_from_rows(
            pw.schema_from_types(ts=int, g=str, v=int),
            [(1, "a", 10), (2, "a", 13), (3, "a", 11), (1, "b", 5), (4, "b", 9)],
        )
        r = diff(t, pw.this.ts, pw.this.v, instance=pw.this.g)
        cols = r.column_names()
        di = cols.index("diff_v")
        gi = cols.index("g")
        ti = cols.index("ts")
        got = {
            (row[gi], row[ti]): row[di] for row in rows_of(r)
        }
        assert got[("a", 1)] is None and got[("b", 1)] is None
        assert got[("a", 2)] == 3 and got[("a", 3)] == -2
        assert got[("b", 4)] == 4

    def test_stateful_deduplicate_acceptor(self):
        """Acceptor-gated dedup: a new value replaces the kept one only when
        the acceptor approves (reference pw.stateful.deduplicate)."""
        from pathway_tpu.stdlib.stateful import deduplicate

        t = pw.debug.table_from_rows(
            pw.schema_from_types(g=str, v=int),
            [("a", 5), ("a", 3), ("a", 9), ("b", 1)],
        )
        r = deduplicate(
            t,
            value=pw.this.v,
            instance=pw.this.g,
            acceptor=lambda new, old: new > old,
        )
        cols = r.column_names()
        vi = cols.index("v")
        gi = cols.index("g")
        got = {row[gi]: row[vi] for row in rows_of(r)}
        assert got == {"a": 9, "b": 1}
