"""stdlib depth: graphs with known-answer fixtures, ordered/statistical
transforms, stateful deduplicate semantics, utils long tail
(VERDICT r2 #9; reference python/pathway/stdlib/* doctest+test shape)."""

from __future__ import annotations

import math

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


def rows(table):
    df = pw.debug.table_to_pandas(table)
    return sorted(map(tuple, df.itertuples(index=False)), key=repr)


class TestGraphsKnownAnswers:
    def _edges(self, pairs):
        return pw.debug.table_from_rows(
            pw.schema_from_types(u=int, v=int), pairs
        )

    def test_pagerank_star_center_dominates(self):
        import pathway_tpu.stdlib.graphs as graphs

        G.clear()
        # star: 1..4 all point at 0; 0 points at 1
        edges = self._edges([(1, 0), (2, 0), (3, 0), (4, 0), (0, 1)])
        ranks = graphs.pagerank(edges.select(u=edges.u, v=edges.v))
        got = {r[0]: r[1] for r in rows(ranks)}
        center = got[0]
        assert all(center > got[n] for n in (2, 3, 4))
        # 1 receives all of 0's rank: second place
        assert got[1] > got[2]

    def test_pagerank_symmetric_cycle_is_uniform(self):
        import pathway_tpu.stdlib.graphs as graphs

        G.clear()
        edges = self._edges([(0, 1), (1, 2), (2, 0)])
        ranks = graphs.pagerank(edges.select(u=edges.u, v=edges.v))
        vals = [r[1] for r in rows(ranks)]
        assert max(vals) - min(vals) < 1e-6  # symmetry => equal ranks

    def test_bellman_ford_shortest_paths(self):
        import pathway_tpu.stdlib.graphs as graphs

        G.clear()
        edges = pw.debug.table_from_rows(
            pw.schema_from_types(u=int, v=int, dist=float),
            [
                (0, 1, 1.0),
                (1, 2, 1.0),
                (0, 2, 5.0),  # longer direct path must lose
                (2, 3, 1.0),
            ],
        )
        vertices = pw.debug.table_from_rows(
            pw.schema_from_types(v=int, is_source=bool),
            [(0, True), (1, False), (2, False), (3, False)],
        )
        res = graphs.bellman_ford(vertices, edges)
        got = {r[0]: r[1] for r in rows(res)}
        assert got[1] == 1.0
        assert got[2] == 2.0  # via 0->1->2, not the direct 5.0
        assert got[3] == 3.0

    def test_bellman_ford_unreachable_absent_or_inf(self):
        import pathway_tpu.stdlib.graphs as graphs

        G.clear()
        edges = pw.debug.table_from_rows(
            pw.schema_from_types(u=int, v=int, dist=float),
            [(0, 1, 1.0), (5, 6, 1.0)],  # 5,6 disconnected from 0
        )
        vertices = pw.debug.table_from_rows(
            pw.schema_from_types(v=int, is_source=bool),
            [(0, True), (1, False), (5, False), (6, False)],
        )
        res = graphs.bellman_ford(vertices, edges)
        got = {r[0]: r[1] for r in rows(res)}
        assert got.get(1) == 1.0
        assert got.get(6) in (None, math.inf) or 6 not in got

    def test_louvain_separates_two_cliques(self):
        import pathway_tpu.stdlib.graphs as graphs

        G.clear()
        clique_a = [(a, b) for a in range(4) for b in range(4) if a < b]
        clique_b = [
            (a, b) for a in range(10, 14) for b in range(10, 14) if a < b
        ]
        bridge = [(3, 10)]
        edges = self._edges(clique_a + clique_b + bridge)
        comms = graphs.louvain_communities(
            edges.select(u=edges.u, v=edges.v)
        )
        got = {r[0]: r[1] for r in rows(comms)}
        assert len({got[n] for n in range(4)}) == 1
        assert len({got[n] for n in range(10, 14)}) == 1
        assert got[0] != got[10]


class TestOrderedAndStatistical:
    def test_ordered_diff_consecutive(self):
        import pathway_tpu.stdlib.ordered as ordered

        G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(t=int, v=float),
            [(1, 10.0), (2, 13.0), (3, 11.5), (4, 20.0)],
        )
        d = ordered.diff(t, t.t, t.v)
        flat = sorted(
            v
            for row in rows(d)
            for v in row
            if isinstance(v, float)
        )
        # consecutive diffs: [first is None], 3.0, -1.5, 8.5
        assert 3.0 in flat and -1.5 in flat and 8.5 in flat

    def test_interpolate_fills_gaps_linearly(self):
        import pathway_tpu.stdlib.statistical as statistical

        G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(t=int, v=float),
            [(0, 0.0), (10, 100.0), (5, None), (2, None)],
        )
        res = statistical.interpolate(t, t.t, t.v)
        df = pw.debug.table_to_pandas(res)
        by_t = {int(r[0]): float(r[1]) for r in df.itertuples(index=False)}
        assert by_t[2] == pytest.approx(20.0)
        assert by_t[5] == pytest.approx(50.0)
        assert by_t[0] == 0.0 and by_t[10] == 100.0


class TestStatefulDeduplicate:
    def test_acceptor_controls_replacement(self):
        import pathway_tpu.stdlib.stateful as stateful

        G.clear()

        class Feed(pw.io.python.ConnectorSubject):
            def run(self):
                import time as _t

                for v in (5, 3, 9, 7):
                    self.next(inst="x", val=v)
                    self.commit()
                    _t.sleep(0.05)

        t = pw.io.python.read(
            Feed(),
            schema=pw.schema_from_types(inst=str, val=int),
            autocommit_duration_ms=None,
        )
        # accept only increases: 5 -> 9 survive; 3 and 7 rejected
        res = stateful.deduplicate(
            t,
            value=t.val,
            instance=t.inst,
            acceptor=lambda new, old: new > old,
        )
        seen = []
        pw.io.subscribe(
            res,
            on_change=lambda key, row, time, is_addition: seen.append(
                (row["val"], is_addition)
            ),
        )
        pw.run()
        accepted = [v for v, add in seen if add]
        assert accepted == [5, 9]
        # the replacement retracted the old accepted value
        assert (5, False) in seen


class TestUtilsLongTail:
    def test_pandas_transformer_round_trip(self):
        G.clear()
        import pandas as pd

        from pathway_tpu.stdlib.utils import pandas_transformer

        @pandas_transformer(output_schema=pw.schema_from_types(total=int))
        def totals(df: pd.DataFrame) -> pd.DataFrame:
            return pd.DataFrame({"total": [int(df["v"].sum())]})

        t = pw.debug.table_from_rows(
            pw.schema_from_types(v=int), [(1,), (2,), (3,)]
        )
        out = totals(t)
        assert rows(out) == [(6,)]

    def test_table_from_pandas_and_back(self):
        G.clear()
        import pandas as pd

        df = pd.DataFrame({"a": [1, 2], "b": ["x", "y"]})
        t = pw.debug.table_from_pandas(df)
        back = pw.debug.table_to_pandas(t)
        assert sorted(back["a"]) == [1, 2]
        assert sorted(back["b"]) == ["x", "y"]

    def test_compute_and_print_smoke(self, capsys):
        G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(a=int), [(1,), (2,)]
        )
        pw.debug.compute_and_print(t)
        out = capsys.readouterr().out
        assert "a" in out and "1" in out and "2" in out


class TestLiveDashboard:
    def test_dashboard_serves_live_snapshots(self):
        """Streaming run with the web dashboard attached: / serves the
        page, /data reflects rows and commit history as they land
        (reference stdlib/viz/plotting.py live dashboards)."""
        import json
        import threading
        import time
        import urllib.request

        import pathway_tpu as pw
        from pathway_tpu.internals.parse_graph import G
        from pathway_tpu.stdlib.viz import LiveDashboard

        G.clear()
        done = threading.Event()

        class Feed(pw.io.python.ConnectorSubject):
            def run(self) -> None:
                for i in range(30):
                    self.next(k=i % 3, v=float(i))
                done.wait(10)

        t = pw.io.python.read(
            Feed(),
            schema=pw.schema_from_types(k=int, v=float),
            autocommit_duration_ms=20,
        )
        agg = t.groupby(t.k).reduce(k=t.k, s=pw.reducers.sum(t.v))
        dash = LiveDashboard(port=0)
        dash.add(agg, title="sums")
        dash.start()
        runner = threading.Thread(target=pw.run, daemon=True)
        runner.start()
        try:
            base = f"http://127.0.0.1:{dash.port}"
            with urllib.request.urlopen(base + "/", timeout=10) as resp:
                page = resp.read().decode()
            assert "pathway live dashboard" in page
            deadline = time.monotonic() + 20
            data = {}
            while time.monotonic() < deadline:
                with urllib.request.urlopen(
                    base + "/data", timeout=10
                ) as resp:
                    data = json.loads(resp.read().decode())
                if data.get("sums", {}).get("n_rows") == 3:
                    break
                time.sleep(0.1)
            assert data["sums"]["n_rows"] == 3, data
            assert data["sums"]["columns"] == ["k", "s"]
            assert data["sums"]["commits"] >= 1
            assert data["sums"]["count_history"]
            got = {r[0]: float(r[1]) for r in data["sums"]["rows"]}
            assert got == {
                "0": sum(float(i) for i in range(30) if i % 3 == 0),
                "1": sum(float(i) for i in range(30) if i % 3 == 1),
                "2": sum(float(i) for i in range(30) if i % 3 == 2),
            }
        finally:
            done.set()
            dash.close()
            runner.join(timeout=15)
