"""Elasticsearch HTTP-bulk and MongoDB BSON/OP_MSG wire protocols
(VERDICT r4 weak #5: 'no test speaks actual HTTP-bulk/BSON frames';
reference formatters src/connectors/data_format.rs:1822,1975)."""

from __future__ import annotations

import json

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._es_wire import (
    EsBulkClient,
    EsError,
    FakeElasticsearchServer,
    auth_header_basic,
)
from pathway_tpu.io._mongo_wire import (
    FakeMongoServer,
    MongoError,
    MongoWireClient,
    decode_bson,
    encode_bson,
)


@pytest.fixture()
def es():
    srv = FakeElasticsearchServer()
    yield srv
    srv.close()


@pytest.fixture()
def mongod():
    srv = FakeMongoServer()
    yield srv
    srv.close()


class TestEsBulkWire:
    def test_bulk_ndjson_roundtrip(self, es):
        client = EsBulkClient(es.host())
        client.index("logs", {"msg": "a", "n": 1})
        client.index("logs", {"msg": "b", "n": 2})
        assert es.indices.get("logs") is None  # buffered, not sent
        client.flush()
        assert [d["msg"] for d in es.indices["logs"]] == ["a", "b"]
        assert es.bulk_requests == [2]  # ONE bulk call carried both

    def test_auth_basic(self):
        srv = FakeElasticsearchServer(
            auth_header=auth_header_basic("elastic", "pw")
        )
        try:
            bad = EsBulkClient(srv.host())
            bad.index("x", {"a": 1})
            with pytest.raises(EsError, match="401"):
                bad.flush()
            ok = EsBulkClient(
                srv.host(),
                auth_header=auth_header_basic("elastic", "pw"),
            )
            ok.index("x", {"a": 1})
            ok.flush()
            assert srv.indices["x"] == [{"a": 1}]
        finally:
            srv.close()

    def test_bulk_item_error_raises(self, es):
        # force an unsupported action line through a raw request
        client = EsBulkClient(es.host())
        body = (
            json.dumps({"delete": {"_index": "x"}})
            + "\n"
        ).encode()
        resp = client._request("POST", "/_bulk", body)
        assert resp["errors"] is True

    def test_pw_io_elasticsearch_end_to_end(self, es):
        G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(k=int, v=str), [(1, "x"), (2, "y")]
        )
        pw.io.elasticsearch.write(t, es.host(), index_name="events")
        pw.run()
        docs = sorted(
            (d["k"], d["v"], d["diff"]) for d in es.indices["events"]
        )
        assert docs == [(1, "x", 1), (2, "y", 1)]
        # batched: one _bulk request per commit, not per row
        assert len(es.bulk_requests) == 1


class TestBsonCodec:
    def test_roundtrip_all_types(self):
        doc = {
            "s": "héllo\x00world"[:5],  # utf-8, no NUL (cstring keys ok)
            "i": 42,
            "big": (1 << 62),
            "neg": -(1 << 62),
            "f": 2.5,
            "t": True,
            "fls": False,
            "none": None,
            "bin": b"\x00\x01\xff",
            "nested": {"a": 1, "b": [1, "two", 3.0]},
            "arr": [True, None, {"x": 1}],
        }
        back, end = decode_bson(encode_bson(doc))
        assert back == doc
        assert end == len(encode_bson(doc))

    def test_bool_is_not_int64(self):
        raw = encode_bson({"b": True, "i": 1})
        assert b"\x08b\x00" in raw  # bool tag
        assert b"\x12i\x00" in raw  # int64 tag

    def test_unsupported_huge_int_raises(self):
        with pytest.raises(MongoError, match="int64"):
            encode_bson({"x": 1 << 64})


class TestMongoWire:
    def test_hello_and_insert_find(self, mongod):
        client = MongoWireClient(port=mongod.port, database="db")
        assert client.server_info["maxWireVersion"] == 17
        client.insert_many(
            "events", [{"k": 1, "v": "a"}, {"k": 2, "v": "b"}]
        )
        rows = client.find("events")
        assert [(r["k"], r["v"]) for r in rows] == [(1, "a"), (2, "b")]
        rows1 = client.find("events", {"k": 2})
        assert [(r["k"], r["v"]) for r in rows1] == [(2, "b")]
        # handshake + both commands traveled as OP_MSG
        assert mongod.commands[:2] == ["hello", "insert"]
        client.close()

    def test_unknown_command_raises(self, mongod):
        client = MongoWireClient(port=mongod.port)
        with pytest.raises(MongoError, match="CommandNotFound"):
            client.command({"shutdown": 1, "$db": "admin"})
        client.close()

    def test_pw_io_mongodb_end_to_end(self, mongod):
        G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(k=int, v=str), [(1, "x"), (2, "y")]
        )
        pw.io.mongodb.write(
            t,
            f"mongodb://127.0.0.1:{mongod.port}",
            database="db",
            collection="events",
        )
        pw.run()
        docs = sorted(
            (d["k"], d["v"], d["diff"])
            for d in mongod.snapshot("db.events")
        )
        assert docs == [(1, "x", 1), (2, "y", 1)]
        # the engine batches one insert command per commit
        assert mongod.commands.count("insert") == 1
