"""Device residency (engine/device_residency.py): parity corpus.

``PATHWAY_TPU_DEVICE_RESIDENCY=1`` keeps collective-exchange outputs
bound for device-eligible consumers resident on device (and re-packs
still-resident inputs without a host round trip); ``=0`` pins the
PR-16 behavior of materializing every exchange output to host.  The two
modes must be bit-identical — sink values, diffs, checkpoint round
trips — on the in-process sharded scheduler, the framework runners and
the single-process distributed scheduler, with the collective forced on
in BOTH runs so residency is the only variable (the same discipline
tests/test_collective_exchange.py applies to the exchange itself).  The
corpus includes retractions, NaN float keys and values, cancelling
batches, empty commits, group extinction, non-codeable columns
declining mid-chain, and chaos legs that kill the device kernel and the
resident-egress wrap — both must fall back with exactly-once delivery
intact.  A cross-check extends the PR-16 EXCHANGE_STATS invariant:
elided + host + collective == repartitions even when collective
deliveries stay device-resident (no double count).
"""

from __future__ import annotations

import numpy as np
import pytest

pytest.importorskip("jax")

import pathway_tpu as pw
from pathway_tpu.engine import collective_exchange as cx
from pathway_tpu.engine import device_residency as dres
from pathway_tpu.engine import routing
from pathway_tpu.engine.batch import Columns
from pathway_tpu.engine.graph import Scope
from pathway_tpu.engine.persistence import (
    MemoryBackend,
    OperatorSnapshotManager,
)
from pathway_tpu.engine.reducers import CountReducer, SumReducer
from pathway_tpu.engine.sharded import ShardedScheduler
from pathway_tpu.engine.value import ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner, ShardedGraphRunner
from pathway_tpu.optimize.placement import PlacementPolicy

N_WORKERS = 4  # conftest forces 8 host-platform sim devices — mesh_ready


def _set_env(monkeypatch, residency_on, device_ops=False):
    # the collective is forced in BOTH modes so residency is the only
    # variable under test; device ops are forced only for framework runs
    # (the optimizer's placement pass does the eligibility annotation)
    monkeypatch.setenv("PATHWAY_TPU_COLLECTIVE_EXCHANGE", "1")
    monkeypatch.setenv(
        "PATHWAY_TPU_DEVICE_OPS", "1" if device_ops else "0"
    )
    monkeypatch.setenv(
        "PATHWAY_TPU_DEVICE_RESIDENCY", "1" if residency_on else "0"
    )


def _canon(obj):
    """NaN-safe, ndarray-safe canonical form for equality asserts."""
    if isinstance(obj, np.ndarray):
        obj = obj.tolist()
    if isinstance(obj, (list, tuple)):
        return tuple(_canon(x) for x in obj)
    if isinstance(obj, float) and obj != obj:
        return "NaN"
    return obj


# -- env contract + seam predicates -------------------------------------------


def test_enabled_env_contract(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "0")
    assert not dres.enabled() and not dres.forced()
    monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "off")
    assert not dres.enabled()
    monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "1")
    assert dres.enabled() and dres.forced()
    monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "force")
    assert dres.enabled() and dres.forced()
    # auto on the CPU sim backend: keeping buffers on a jax-CPU
    # "device" saves nothing, so auto stays off
    monkeypatch.delenv("PATHWAY_TPU_DEVICE_RESIDENCY", raising=False)
    assert not dres.enabled()


class _FakeConsumer:
    def __init__(self, kind=None, index=0, downstream=None):
        if kind is not None:
            self._device_ops_eligible = kind
        if downstream is not None:
            self._device_residency_downstream = downstream
        self.index = index


def test_consumer_seam_key(monkeypatch):
    assert dres.consumer_seam_key(None) is None
    assert dres.consumer_seam_key(_FakeConsumer()) is None
    assert dres.consumer_seam_key(
        _FakeConsumer(kind="groupby", index=7)
    ) == ("groupby", 7)
    # a row-local feeder marked by the placement pass belongs to the
    # downstream operator's seam
    assert dres.consumer_seam_key(
        _FakeConsumer(downstream=("join", 3))
    ) == ("join", 3)


def test_consumer_resident_ok(monkeypatch):
    eligible = _FakeConsumer(kind="groupby", index=7)
    monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "0")
    assert not dres.consumer_resident_ok(eligible)
    monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "1")
    assert dres.consumer_resident_ok(eligible)
    # forced mode never keeps a batch resident for an unannotated
    # consumer — there is no device-side reader to hand it to
    assert not dres.consumer_resident_ok(_FakeConsumer())
    assert not dres.consumer_resident_ok(None)


# -- DeviceResidentColumns unit behavior --------------------------------------


def _packed_fixture(n=640, with_diffs=True):
    """A host Columns + its packed wire payload (the exchange layout)."""
    kb = (np.arange(n * 16, dtype=np.int64) % 251).astype(np.uint8)
    kb = np.ascontiguousarray(kb.reshape(n, 16))
    cols = [
        np.arange(n, dtype=np.int64) * 3 - 7,
        (np.arange(n, dtype=np.float64) * 0.5 - 2.0),
    ]
    diffs = None
    if with_diffs:
        diffs = np.where(np.arange(n) % 5 == 0, -1, 1).astype(np.int64)
    host = Columns(n, cols, kbytes=kb, diffs=diffs)
    payload, layout, has_diffs = cx._pack_payload(host)
    assert payload is not None
    return host, payload, layout, has_diffs


def _resident_from(payload, layout, has_diffs, seam_key=None):
    import jax.numpy as jnp

    return dres.DeviceResidentColumns.from_device_rows(
        jnp.asarray(payload), layout, has_diffs, seam_key=seam_key
    )


def test_resident_columns_lazy_then_bit_exact():
    dres.reset_counters()
    host, payload, layout, has_diffs = _packed_fixture()
    res = _resident_from(payload, layout, has_diffs)
    # diffs are eager (every delivery path screens them); host slots are
    # not — nothing materialized yet
    assert res.n == host.n
    assert np.array_equal(res.diffs, host.diffs)
    assert res.resident() and not res._materialized()
    assert dres.RESIDENCY_STATS["materializations"] == 0
    # first host access materializes bit-exactly through the wire spec
    assert np.array_equal(res.kbytes(), host.kbytes())
    assert res._materialized()
    assert dres.RESIDENCY_STATS["materializations"] == 1
    for got, want in zip(res.cols, host.cols):
        assert got.dtype == want.dtype
        assert np.array_equal(got, want)
    # the device buffer survives materialization (re-pack stays possible)
    assert res.resident()
    # second access is a no-op, not a second fetch
    res.kbytes()
    assert dres.RESIDENCY_STATS["materializations"] == 1


def test_resident_columns_no_diffs():
    host, payload, layout, has_diffs = _packed_fixture(with_diffs=False)
    assert not has_diffs
    res = _resident_from(payload, layout, has_diffs)
    assert res.diffs is None  # all-(+1) stays the None encoding
    for got, want in zip(res.cols, host.cols):
        assert np.array_equal(got, want)


def test_device_column_views():
    host, payload, layout, has_diffs = _packed_fixture()
    res = _resident_from(payload, layout, has_diffs)
    for i, want in enumerate(host.cols):
        dev = res.device_column(i)
        assert dev is not None
        got = np.asarray(dev)
        assert got.dtype == want.dtype and np.array_equal(got, want)
    # the device view never forced host materialization
    assert not res._materialized()


def test_decay_materializes_and_drops_buffer():
    host, payload, layout, has_diffs = _packed_fixture()
    res = _resident_from(payload, layout, has_diffs)
    res.decay()
    assert not res.resident()
    assert res.device_rows() is None and res.device_column(0) is None
    # decayed batches read as plain host data, bit-exactly
    assert np.array_equal(res.kbytes(), host.kbytes())
    assert np.array_equal(res.cols[1], host.cols[1])
    res.decay()  # idempotent


def test_decay_resident_batches_sweeps_live_set():
    host, payload, layout, has_diffs = _packed_fixture()
    a = _resident_from(payload, layout, has_diffs)
    b = _resident_from(payload, layout, has_diffs)
    assert a.resident() and b.resident()
    dres.decay_resident_batches()
    assert not a.resident() and not b.resident()
    assert np.array_equal(a.cols[0], host.cols[0])
    dres.decay_resident_batches()  # empty sweep is a no-op


def test_gather_after_materialize_matches_host():
    host, payload, layout, has_diffs = _packed_fixture()
    res = _resident_from(payload, layout, has_diffs)
    idx = np.arange(0, host.n, 3, dtype=np.int64)
    got, want = res.gather(idx), host.gather(idx)
    assert np.array_equal(got.kbytes(), want.kbytes())
    assert np.array_equal(got.diffs, want.diffs)
    for g, w in zip(got.cols, want.cols):
        assert np.array_equal(g, w)


# -- exchange ingress/egress unit parity --------------------------------------


def _run_exchange(columns, shards, consumer, monkeypatch, residency_on):
    _set_env(monkeypatch, residency_on)
    parts = cx.exchange(0, columns, shards, N_WORKERS, consumer=consumer)
    assert parts is not None
    return parts


def _parts_canon(parts):
    out = []
    for p in parts:
        if p is None:
            out.append(None)
            continue
        out.append(
            (
                p.kbytes().tobytes(),
                None if p.diffs is None else p.diffs.tobytes(),
                tuple(
                    (c.dtype.str, c.tobytes()) for c in p.cols
                ),
            )
        )
    return out


def test_exchange_resident_egress_parity(monkeypatch):
    """Resident egress parts materialize bit-identically to the host
    fetch, and the trimmed lazy fetch moves strictly fewer D2H bytes
    than the whole padded buffer."""
    host, payload, layout, has_diffs = _packed_fixture(n=700)
    shards = (np.arange(700, dtype=np.int64) * 7) % N_WORKERS
    consumer = _FakeConsumer(kind="groupby", index=7)
    dres.reset_counters()
    off = _run_exchange(host, shards, consumer, monkeypatch, False)
    assert dres.RESIDENCY_STATS["resident_batches"] == 0
    d2h_off = dres.stats()["d2h"]["bytes"]
    assert d2h_off > 0

    dres.reset_counters()
    on = _run_exchange(host, shards, consumer, monkeypatch, True)
    assert all(
        p is None or isinstance(p, dres.DeviceResidentColumns) for p in on
    )
    assert dres.RESIDENCY_STATS["resident_batches"] > 0
    assert _parts_canon(on) == _parts_canon(off)  # materializes lazily
    d2h_on = dres.stats()["d2h"]["bytes"]
    assert d2h_on < d2h_off
    assert dres.stats()["bytes_saved"] > 0


def test_exchange_resident_ingress_repack(monkeypatch):
    """A still-resident input re-packs from device rows: only the index
    matrix crosses H2D, and the delivered parts are bit-identical to
    packing the same batch from host."""
    host, payload, layout, has_diffs = _packed_fixture(n=650)
    shards = (np.arange(650, dtype=np.int64) * 11) % N_WORKERS

    dres.reset_counters()
    off = _run_exchange(host, shards, None, monkeypatch, False)
    h2d_host = dres.stats()["h2d"]["bytes"]

    res = _resident_from(payload, layout, has_diffs)
    dres.reset_counters()
    on = _run_exchange(res, shards, None, monkeypatch, True)
    s = dres.stats()
    assert s["events"]["device_consumes"] == 1
    assert s["h2d"]["bytes"] < h2d_host  # payload never re-crossed
    assert s["bytes_saved"] > 0
    assert _parts_canon(on) == _parts_canon(off)


def test_exchange_resident_egress_failure_falls_back(monkeypatch):
    """A failure while wrapping resident egress parts declines cleanly:
    the whole buffer is fetched, host parts are delivered bit-exactly,
    and nothing was half-pushed."""
    host, payload, layout, has_diffs = _packed_fixture(n=600)
    shards = np.arange(600, dtype=np.int64) % N_WORKERS
    consumer = _FakeConsumer(kind="groupby", index=7)
    off = _run_exchange(host, shards, consumer, monkeypatch, False)

    def boom(*a, **k):
        raise RuntimeError("simulated resident-wrap failure")

    monkeypatch.setattr(
        dres.DeviceResidentColumns, "from_device_rows", boom
    )
    dres.reset_counters()
    on = _run_exchange(host, shards, consumer, monkeypatch, True)
    assert dres.RESIDENCY_STATS["declines"] > 0
    assert all(not isinstance(p, dres.DeviceResidentColumns) for p in on)
    assert _parts_canon(on) == _parts_canon(off)


# -- raw-scope corpus: retractions, NaN, cancelling batches -------------------


def _build_scopes(n_workers):
    scopes, sessions, aggs = [], [], []
    for _w in range(n_workers):
        sc = Scope()
        sess = sc.input_session(3)
        agg = sc.group_by_table(
            sess,
            by_cols=[0],
            reducers=[
                (SumReducer(), [1]),
                (SumReducer(), [2]),
                (CountReducer(), []),
            ],
        )
        # raw scopes bypass the optimizer: annotate eligibility by hand
        # (exactly what optimize/placement.run_pass stamps)
        agg._device_ops_eligible = "groupby"
        scopes.append(sc)
        sessions.append(sess)
        aggs.append(agg)
    return scopes, sessions, aggs


def _feed(sess, sched, nan_keys=False, nan_vals=False):
    live = {}

    def key(i):
        if nan_keys and i % 97 == 0:
            return float("nan")
        return float(i % 7) if nan_keys else i % 7

    def ins(i, row):
        live[i] = row
        sess.insert(ref_scalar(i), row)

    def rm(i):
        sess.remove(ref_scalar(i), live.pop(i))

    for i in range(600):
        v = float("nan") if nan_vals and i % 89 == 0 else i * 0.5
        ins(i, (key(i), i, v))
    sched.commit()
    for i in range(100, 150):  # retract + reinsert modified
        rm(i)
        ins(i, (key(i), i + 1000, i * 0.25))
    sched.commit()
    sched.commit()  # empty commit
    ins(10_000, (key(3), 1, 1.0))  # cancelling batch: net-zero delta
    rm(10_000)
    sched.commit()
    for i in [k for k in list(live) if _canon(live[k][0]) == _canon(key(6))]:
        rm(i)  # retract an entire group to extinction
    sched.commit()
    return live


def _run_sharded(on, monkeypatch, nan_keys=False, nan_vals=False):
    _set_env(monkeypatch, on)
    scopes, sessions, aggs = _build_scopes(N_WORKERS)
    sched = ShardedScheduler(scopes)
    _feed(sessions[0], sched, nan_keys=nan_keys, nan_vals=nan_vals)
    merged = {}
    for agg in aggs:
        merged.update(agg.current)
    return {k: _canon(v) for k, v in merged.items()}


@pytest.mark.parametrize(
    "nan_keys,nan_vals", [(False, False), (True, False), (False, True)]
)
def test_raw_scope_parity(nan_keys, nan_vals, monkeypatch):
    dres.reset_counters()
    off = _run_sharded(False, monkeypatch, nan_keys, nan_vals)
    assert dres.RESIDENCY_STATS["resident_batches"] == 0  # off stayed host
    on = _run_sharded(True, monkeypatch, nan_keys, nan_vals)
    assert off == on
    assert dres.RESIDENCY_STATS["resident_batches"] > 0  # non-vacuous
    if nan_keys:
        assert "NaN" in repr(off)
    if nan_vals:
        assert any("NaN" in repr(v) for v in off.values())


def test_raw_scope_transfer_bytes_strictly_lower(monkeypatch):
    """The acceptance metric at unit scale: the same feed moves strictly
    fewer h2d+d2h bytes with residency on (the padded all-to-all tail
    never crosses; only trimmed rows materialize)."""
    dres.reset_counters()
    off = _run_sharded(False, monkeypatch)
    s_off = dres.stats()
    dres.reset_counters()
    on = _run_sharded(True, monkeypatch)
    s_on = dres.stats()
    assert off == on
    total_off = s_off["h2d"]["bytes"] + s_off["d2h"]["bytes"]
    total_on = s_on["h2d"]["bytes"] + s_on["d2h"]["bytes"]
    assert 0 < total_on < total_off
    assert s_on["bytes_saved"] > 0 and s_off["bytes_saved"] == 0


def test_commit_boundary_decays_residents(monkeypatch):
    """Drain-before-persistence: no resident batch survives a commit
    boundary, so snapshots only ever see host-resident state."""
    _set_env(monkeypatch, True)
    scopes, sessions, aggs = _build_scopes(N_WORKERS)
    sched = ShardedScheduler(scopes)
    dres.reset_counters()
    for i in range(600):
        sessions[0].insert(ref_scalar(i), (i % 7, i, i * 0.5))
    sched.commit()
    assert dres.RESIDENCY_STATS["resident_batches"] > 0
    assert not dres._LIVE_RESIDENT  # swept at the boundary


def test_kernel_failure_declines_to_host(monkeypatch):
    """A device error mid-collective performs NO pushes; the host path
    delivers the whole batch (the PR-6 rollback seam) bit-identically,
    with residency never engaging on the failed exchange."""
    off = _run_sharded(False, monkeypatch)

    def boom(n):
        def dead_kernel(payload, gidx):
            raise RuntimeError("simulated worker loss mid-collective")

        return dead_kernel

    monkeypatch.setattr(cx, "_kernel", boom)
    cx.reset_counters()
    dres.reset_counters()
    chaos = _run_sharded(True, monkeypatch)
    assert chaos == off
    assert cx.COLLECTIVE_STATS["errors"] > 0
    assert dres.RESIDENCY_STATS["resident_batches"] == 0


def test_object_column_mid_chain_decline(monkeypatch):
    """A mixed-type column is not raw-byte codeable: the exchange
    declines before residency is even consulted and the host path
    delivers bit-identically (no partial pushes)."""

    def run(on):
        _set_env(monkeypatch, on)
        scopes, sessions, aggs = [], [], []
        for _w in range(N_WORKERS):
            sc = Scope()
            sess = sc.input_session(2)
            agg = sc.group_by_table(
                sess, by_cols=[0], reducers=[(CountReducer(), [])]
            )
            agg._device_ops_eligible = "groupby"
            scopes.append(sc)
            sessions.append(sess)
            aggs.append(agg)
        sched = ShardedScheduler(scopes)
        for i in range(300):
            v = i if i % 2 else f"s{i}"  # mixed types -> object column
            sessions[0].insert(ref_scalar(i), (i % 7, v))
        sched.commit()
        merged = {}
        for agg in aggs:
            merged.update(agg.current)
        return {k: _canon(v) for k, v in merged.items()}

    cx.reset_counters()
    dres.reset_counters()
    off = run(False)
    on = run(True)
    assert off == on
    assert cx.COLLECTIVE_STATS["declined_non_codeable"] > 0
    assert dres.RESIDENCY_STATS["resident_batches"] == 0


# -- EXCHANGE_STATS invariant with resident deliveries ------------------------


def test_exchange_stats_invariant_with_residency(monkeypatch):
    """PR-16 delivery-plane invariant, extended: a collective delivery
    that stays device-resident still counts exactly once —
    elided + host + collective == repartitions in both modes."""
    stats = routing.EXCHANGE_STATS
    for on in (False, True):
        dres.reset_counters()
        before = {
            k: stats[k]
            for k in (
                "elided",
                "host_deliveries",
                "collective_deliveries",
                "repartitions",
            )
        }
        _run_sharded(on, monkeypatch)
        delta = {k: stats[k] - before[k] for k in before}
        assert delta["repartitions"] > 0
        assert (
            delta["elided"]
            + delta["host_deliveries"]
            + delta["collective_deliveries"]
            == delta["repartitions"]
        )
        assert delta["collective_deliveries"] > 0
        if on:
            # resident deliveries rode the collective plane, not a new one
            assert dres.RESIDENCY_STATS["resident_batches"] > 0


# -- framework runners ---------------------------------------------------------


def _chain():
    """The acceptance workload shape: device groupby feeding a join
    through a repartition seam."""
    t = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, v=int, w=float),
        [(i % 50, i, i * 0.25) for i in range(800)],
    )
    g = t.groupby(t.k).reduce(
        k=t.k, total=pw.reducers.sum(t.v), cnt=pw.reducers.count()
    )
    d = pw.debug.table_from_rows(
        pw.schema_from_types(k2=int, label=int),
        [(i, i % 3) for i in range(50)],
    )
    j = g.join(d, g.k == d.k2)
    return j.select(k=g.k, total=g.total, cnt=g.cnt, label=d.label)


def _groupby_only():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, v=int, w=float),
        [(i % 7, i, i * 0.5) for i in range(700)],
    )
    sel = t.select(k=t.k, v=t.v * 2 + 1, w=t.w)
    flt = sel.filter(sel.v > 7)
    return flt.groupby(flt.k).reduce(
        k=flt.k,
        total=pw.reducers.sum(flt.v),
        wsum=pw.reducers.sum(flt.w),
        cnt=pw.reducers.count(),
    )


def _capture(build, runner_factory, monkeypatch, on, device_ops=True):
    _set_env(monkeypatch, on, device_ops=device_ops)
    G.clear()
    try:
        (state,) = runner_factory().capture(build())
    finally:
        G.clear()
    return {k: _canon(v) for k, v in state.items()}


@pytest.mark.parametrize("name", ["chain", "groupby_only"])
def test_framework_sharded_parity(name, monkeypatch):
    build = {"chain": _chain, "groupby_only": _groupby_only}[name]
    dres.reset_counters()
    off = _capture(
        build, lambda: ShardedGraphRunner(N_WORKERS), monkeypatch, False
    )
    assert dres.RESIDENCY_STATS["resident_batches"] == 0
    on = _capture(
        build, lambda: ShardedGraphRunner(N_WORKERS), monkeypatch, True
    )
    assert off == on
    # the optimizer's placement pass (not hand annotation) found the
    # eligible consumers behind the fused/pushed-down delivery nodes
    assert dres.RESIDENCY_STATS["resident_batches"] > 0


def test_framework_matches_single_worker(monkeypatch):
    base = _capture(_chain, GraphRunner, monkeypatch, False)
    on = _capture(
        _chain, lambda: ShardedGraphRunner(N_WORKERS), monkeypatch, True
    )
    assert base == on


# -- checkpoint round trips across modes --------------------------------------


class TestCheckpointCompat:
    """Residency is a runtime decision, not graph structure: a snapshot
    taken with residency forced must restore under a residency-off run
    (and vice versa) with identical state — resident batches decay at
    commit boundaries, so snapshots only ever serialize host state."""

    def _snap(self, on, backend, monkeypatch, restore_only=False):
        _set_env(monkeypatch, on)
        scopes, sessions, aggs = _build_scopes(N_WORKERS)
        mgr = OperatorSnapshotManager(backend)
        if restore_only:
            restored = mgr.restore(scopes, [])
            assert restored is not None
            merged = {}
            for agg in aggs:
                merged.update(agg.current)
            return merged
        sched = ShardedScheduler(scopes)
        for i in range(600):
            sessions[0].insert(ref_scalar(i), (i % 7, i, i * 0.5))
        sched.commit()
        for i in range(100, 150):
            sessions[0].remove(ref_scalar(i), (i % 7, i, i * 0.5))
        sched.commit()
        mgr.snapshot(scopes, [], sched.time)
        merged = {}
        for agg in aggs:
            merged.update(agg.current)
        return merged

    @pytest.mark.parametrize(
        "snap_on,restore_on", [(True, False), (False, True)]
    )
    def test_cross_restore(self, snap_on, restore_on, monkeypatch):
        backend = MemoryBackend()
        live = self._snap(snap_on, backend, monkeypatch)
        restored = self._snap(
            restore_on, backend, monkeypatch, restore_only=True
        )
        assert {k: _canon(v) for k, v in restored.items()} == {
            k: _canon(v) for k, v in live.items()
        }


# -- single-process distributed scheduler -------------------------------------


def test_distributed_single_process_residency(monkeypatch):
    from pathway_tpu.engine import distributed as dist

    def run(on):
        _set_env(monkeypatch, on)
        scopes, sessions, aggs = [], [], []
        for _w in range(2):
            sc = Scope()
            sess = sc.input_session(2)
            agg = sc.group_by_table(
                sess,
                by_cols=[0],
                reducers=[(SumReducer(), [1]), (CountReducer(), [])],
            )
            agg._device_ops_eligible = "groupby"
            scopes.append(sc)
            sessions.append(sess)
            aggs.append(agg)
        transport = dist.MeshTransport(0, 1, addresses=[("127.0.0.1", 0)])
        try:
            sched = dist.DistributedScheduler(
                scopes, 0, 1, transport, n_shared=len(scopes[0].nodes)
            )
            sched.announce_topology()
            for i in range(500):
                sessions[0].insert(ref_scalar(i), (i % 13, float(i)))
            sched.commit_local()
            for i in range(50, 80):
                sessions[0].remove(ref_scalar(i), (i % 13, float(i)))
            sched.commit_local()
        finally:
            transport.close()
        merged = {}
        for agg in aggs:
            merged.update(agg.current)
        return {k: _canon(v) for k, v in merged.items()}

    dres.reset_counters()
    off = run(False)
    assert dres.RESIDENCY_STATS["resident_batches"] == 0
    on = run(True)
    assert off == on
    assert dres.RESIDENCY_STATS["resident_batches"] > 0


# -- chain-aware placement -----------------------------------------------------


class TestChainAwarePlacement:
    def _policy(self):
        return PlacementPolicy(
            enabled_fn=lambda: True,
            forced_fn=lambda: False,
            min_rows_fn=lambda: 0,
        )

    def _probe(self, pol, host_ns, device_ns):
        # order matters: host first so the bootstrap device-credit in
        # record() does not pre-place the operator on device
        for _ in range(pol.PROBE_CALLS):
            pol.record("groupby", 1, False, 1, host_ns)
        for _ in range(pol.PROBE_CALLS):
            pol.record("groupby", 1, True, 1, device_ns)

    def test_seam_credit_flips_placement(self, monkeypatch):
        monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "1")
        pol = self._policy()
        # device measures slightly slower than host: stays host under
        # the 1.2x hysteresis
        self._probe(pol, host_ns=100, device_ns=110)
        assert not pol.choose("groupby", 1, 1000)
        assert not pol.is_device("groupby", 1)
        # a device-placed neighbor across the seam + a measured seam
        # cost credit the device side past the hysteresis
        pol.seed("join", 2, device=True)
        pol.link("groupby", 1, "join", 2)
        pol.record_seam("groupby", 1, 1, 50)
        assert pol.choose("groupby", 1, 1000)
        assert pol.is_device("groupby", 1)
        dec = pol.decisions()["groupby:1"]
        assert dec["links"] == ["join:2"] and dec["seam_events"] == 1
        assert dec["seam_ns_per_row"] == 50.0

    def test_no_credit_when_residency_off(self, monkeypatch):
        monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "0")
        pol = self._policy()
        self._probe(pol, host_ns=100, device_ns=110)
        pol.seed("join", 2, device=True)
        pol.link("groupby", 1, "join", 2)
        pol.record_seam("groupby", 1, 1, 50)
        assert not pol.choose("groupby", 1, 1000)

    def test_no_credit_without_device_neighbor(self, monkeypatch):
        monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "1")
        pol = self._policy()
        self._probe(pol, host_ns=100, device_ns=110)
        pol.seed("join", 2)  # neighbor exists but sits on host
        pol.link("groupby", 1, "join", 2)
        pol.record_seam("groupby", 1, 1, 50)
        assert not pol.choose("groupby", 1, 1000)

    def test_reset_clears_links(self):
        pol = self._policy()
        pol.link("groupby", 1, "join", 2)
        pol.reset()
        assert pol.decisions() == {}


def test_placement_pass_marks_feeders_and_links(monkeypatch):
    """optimize.run_pass stamps non-eligible feeders with their
    downstream operator's seam and links eligible neighbors."""
    from pathway_tpu.optimize import placement as pl

    monkeypatch.setenv("PATHWAY_TPU_DEVICE_OPS", "1")
    _set_env(monkeypatch, True, device_ops=True)
    G.clear()
    try:
        runner = ShardedGraphRunner(N_WORKERS)
        pl.POLICY.reset()
        runner.capture(_chain())
        linked = any(
            d["links"] for d in pl.POLICY.decisions().values()
        )
    finally:
        G.clear()
    assert linked


# -- metrics + stats shape -----------------------------------------------------


def test_stats_shape(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "1")
    dres.reset_counters()
    s = dres.stats()
    assert s["enabled"] is True and s["forced"] is True
    assert s["events"] == {
        "resident_batches": 0,
        "materializations": 0,
        "device_consumes": 0,
        "declines": 0,
    }
    assert s["h2d"] == {"events": 0, "bytes": 0}
    assert s["d2h"] == {"events": 0, "bytes": 0}
    assert s["bytes_saved"] == 0


def test_metric_families_registered(monkeypatch):
    from pathway_tpu.internals import metrics as m

    dres.reset_counters()
    _run_sharded(True, monkeypatch)
    snap = m.REGISTRY.snapshot()
    for fam in (
        "pathway_device_transfer_h2d_events_total",
        "pathway_device_transfer_h2d_bytes_total",
        "pathway_device_transfer_d2h_events_total",
        "pathway_device_transfer_d2h_bytes_total",
        "pathway_device_residency_bytes_saved_total",
        "pathway_device_residency_events_total",
    ):
        assert fam in snap, fam
    kinds = {
        s["labels"].get("kind")
        for s in snap["pathway_device_residency_events_total"]["series"]
    }
    assert {
        "resident_batches",
        "materializations",
        "device_consumes",
        "declines",
    } <= kinds


def test_pipeline_stats_include_residency(monkeypatch):
    from pathway_tpu.engine import device_pipeline as dp

    monkeypatch.setenv("PATHWAY_TPU_DEVICE_RESIDENCY", "1")
    s = dp.PIPELINE.stats()
    assert "device_residency" in s
    assert s["device_residency"]["forced"] is True
