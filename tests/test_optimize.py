"""Pre-execution graph rewriter (pathway_tpu.optimize): correctness.

Three passes — stateless-chain fusion, projection pushdown, exchange
elision — each rewrite must be observationally invisible: optimize-on
and optimize-off runs produce bit-identical outputs (values, diffs,
error logs) on the single-worker, sharded in-process, and TCP-mesh
schedulers. The optimizer's elision oracle is the analyzer's PWA201
pass, so the two counts must always agree.
"""

from __future__ import annotations

import csv
import os
import socket
import subprocess
import sys
import textwrap

import pytest

import pathway_tpu as pw
import pathway_tpu.engine.graph as g
from pathway_tpu.analysis import analyze_scope
from pathway_tpu.engine import expression as ex
from pathway_tpu.engine import sharded as sharded_mod
from pathway_tpu.engine.graph import Scheduler, Scope
from pathway_tpu.engine.persistence import (
    MemoryBackend,
    OperatorSnapshotManager,
)
from pathway_tpu.engine.reducers import CountReducer, SumReducer
from pathway_tpu.engine.routing import EXCHANGE_STATS
from pathway_tpu.engine.sharded import ShardedScheduler
from pathway_tpu.engine.value import Pointer, ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner, ShardedGraphRunner
from pathway_tpu.optimize import (
    FusedChainNode,
    optimize_scopes,
    optimizer_stats,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def opt_on(monkeypatch):
    """Tests asserting that rewrites HAPPEN must see the optimizer
    enabled even when the ambient environment disables it (the
    tools/check.py optimize-off leg reruns this file with
    PATHWAY_TPU_OPTIMIZE=0; parity tests pass either way, but these
    would vacuously fail)."""
    monkeypatch.setenv("PATHWAY_TPU_OPTIMIZE", "1")


# -- engine-level graph builders ----------------------------------------------


def _chain_scope(with_sink=True, events=None):
    """source -> expr -> filter -> expr -> expr (+ subscribe): one fusable
    stateless chain with both vectorizable and pass-through columns."""
    sc = Scope()
    sess = sc.input_session(2)
    e1 = sc.expression_table(
        sess,
        [
            ex.ColumnRef(0),
            ex.ColumnRef(1),
            ex.Binary(">", ex.ColumnRef(0), ex.Const(10)),
        ],
    )
    f1 = sc.filter_table(e1, 2)
    e2 = sc.expression_table(
        f1,
        [ex.ColumnRef(0), ex.Binary("*", ex.ColumnRef(1), ex.Const(3.0))],
    )
    e3 = sc.expression_table(
        e2,
        [ex.ColumnRef(0), ex.Binary("+", ex.ColumnRef(1), ex.Const(1.0))],
    )
    if with_sink and events is not None:
        sc.subscribe_table(
            e3,
            on_change=lambda k, row, t, d: events.append((k, row, t, d)),
        )
    return sc, sess, e3


def _rows(n, start=0):
    return [
        (ref_scalar(i), (i, float(i) * 0.5)) for i in range(start, start + n)
    ]


def _run_chain(optimize, n=600, updates=True):
    events: list = []
    sc, sess, tail = _chain_scope(events=events)
    sched = Scheduler(sc, optimize=optimize)
    for k, r in _rows(n):
        sess.insert(k, r)
    sched.commit()
    if updates:
        # second commit with retractions + small batches (row path)
        for k, r in _rows(50, start=100):
            sess.remove(k, r)
            sess.insert(k, (r[0], r[1] + 9.0))
        sched.commit()
    return sc, tail, sorted(events, key=lambda e: (int(e[0]), e[3], e[2]))


# -- fusion ------------------------------------------------------------------


class TestChainFusion:
    def test_chain_fuses_and_reports_stats(self, opt_on):
        sc, tail, _ = _run_chain(True)
        stats = optimizer_stats()
        assert stats["chains_fused"] == 1
        assert stats["nodes_fused"] == 4  # e1, f1, e2, e3
        assert isinstance(tail, FusedChainNode)

    def test_event_stream_parity(self):
        _, tail_off, ev_off = _run_chain(False)
        _, tail_on, ev_on = _run_chain(True)
        assert ev_off == ev_on
        assert dict(tail_off.current) == dict(tail_on.current)

    def test_insert_only_bulk_parity(self):
        _, tail_off, ev_off = _run_chain(False, n=2000, updates=False)
        _, tail_on, ev_on = _run_chain(True, n=2000, updates=False)
        assert ev_off == ev_on
        assert dict(tail_off.current) == dict(tail_on.current)

    def test_interior_nodes_are_inert(self, opt_on):
        sc, tail, _ = _run_chain(True)
        interiors = [
            node
            for node in sc.nodes
            if getattr(node, "_pw_fused_into", None) is not None
        ]
        assert len(interiors) == 3  # e1, f1, e2 fold into the e3 tail
        for node in interiors:
            assert node.consumers == []
            assert node.inputs == []
            assert not node.current  # never received a batch
            # the node slot itself must survive: schedulers address
            # replicas by scope.nodes[index]
            assert sc.nodes[node.index] is node

    def test_node_indices_are_stable_after_fusion(self):
        sc, _, _ = _run_chain(True)
        assert [n.index for n in sc.nodes] == list(range(len(sc.nodes)))

    def test_filter_error_value_parity(self):
        def run(optimize):
            events: list = []
            sc = Scope()
            sess = sc.input_session(2)
            e1 = sc.expression_table(
                sess,
                [
                    ex.ColumnRef(0),
                    # 1/x poisons x == 0 rows with ERROR
                    ex.Binary("/", ex.Const(1.0), ex.ColumnRef(1)),
                    ex.Binary(">", ex.ColumnRef(0), ex.Const(-1)),
                ],
            )
            f1 = sc.filter_table(
                sc.expression_table(
                    e1,
                    [
                        ex.ColumnRef(0),
                        ex.ColumnRef(1),
                        ex.Binary("<", ex.ColumnRef(1), ex.Const(1e9)),
                    ],
                ),
                2,
            )
            sc.subscribe_table(
                f1,
                on_change=lambda k, row, t, d: events.append((k, row, d)),
            )
            sched = Scheduler(sc, optimize=optimize)
            for i in range(40):
                sess.insert(ref_scalar(i), (i, float(i % 5)))
            sched.commit()
            log = sorted(sc.error_log_default.current.values())
            return sorted(events, key=lambda e: (int(e[0]), e[2])), log

        ev_off, log_off = run(False)
        ev_on, log_on = run(True)
        assert ev_off == ev_on
        assert log_off == log_on
        assert log_on  # the corpus actually exercised the error path

    def test_nonvectorizable_udf_chain_parity(self):
        def run(optimize):
            events: list = []
            sc = Scope()
            sess = sc.input_session(2)
            e1 = sc.expression_table(
                sess,
                [
                    ex.ColumnRef(0),
                    ex.Apply(lambda v: v * 2.0, (ex.ColumnRef(1),)),
                ],
            )
            e2 = sc.expression_table(
                e1,
                [ex.Binary("+", ex.ColumnRef(0), ex.ColumnRef(1))],
            )
            sc.subscribe_table(
                e2,
                on_change=lambda k, row, t, d: events.append((k, row, d)),
            )
            sched = Scheduler(sc, optimize=optimize)
            for k, r in _rows(500):
                sess.insert(k, r)
            sched.commit()
            return sorted(events, key=lambda e: (int(e[0]), e[2]))

        assert run(False) == run(True)

    def test_observed_node_is_never_fused(self, opt_on):
        # a mid-chain node whose state is read directly (capture path)
        # must stay un-fused even though it links like a chain member
        events: list = []
        sc, sess, tail = _chain_scope(events=events)
        mid = sc.nodes[2]  # the filter
        mid._pw_observed = True
        Scheduler(sc, optimize=True)
        assert not isinstance(mid, FusedChainNode)
        assert type(tail).__name__ == "FusedChainNode"  # e2->e3 still fuse

    def test_env_escape_hatch(self, monkeypatch):
        monkeypatch.setenv("PATHWAY_TPU_OPTIMIZE", "0")
        sc, tail, _ = _run_chain(True)
        assert not isinstance(tail, FusedChainNode)
        assert optimizer_stats() == {
            "chains_fused": 0,
            "nodes_fused": 0,
            "columns_dropped": 0,
            "exchanges_elided": 0,
        }

    def test_optimize_is_idempotent(self, opt_on):
        events: list = []
        sc, _, _ = _chain_scope(events=events)
        first = optimize_scopes([sc])
        second = optimize_scopes([sc])  # cached, no double rewrite
        assert first == second
        assert sum(isinstance(n, FusedChainNode) for n in sc.nodes) == 1


# -- projection pushdown -----------------------------------------------------


class TestProjectionPushdown:
    def _wide(self, optimize, n_wide=8):
        events: list = []
        sc = Scope()
        rows = [
            (ref_scalar(i), tuple(float(i + c) for c in range(n_wide)))
            for i in range(50)
        ]
        src = sc.static_table(rows, n_wide)
        e1 = sc.expression_table(
            src, [ex.Binary("+", ex.ColumnRef(1), ex.ColumnRef(5))]
        )
        sc.subscribe_table(
            e1, on_change=lambda k, row, t, d: events.append((k, row, d))
        )
        sc.run(optimize=optimize)
        return sc, src, sorted(events, key=lambda e: (int(e[0]), e[2]))

    def test_static_source_narrowed(self, opt_on):
        sc, src, ev_on = self._wide(True)
        assert src.arity == 2
        assert all(len(r) == 2 for _, r in src._rows)
        assert optimizer_stats()["columns_dropped"] == 6
        _, src_off, ev_off = self._wide(False)
        assert src_off.arity == 8
        assert ev_on == ev_off

    def test_expression_producer_narrowed(self, opt_on):
        def run(optimize):
            events: list = []
            sc = Scope()
            sess = sc.input_session(2)
            wide = sc.expression_table(
                sess,
                [
                    ex.Binary("*", ex.ColumnRef(1), ex.Const(float(c + 1)))
                    for c in range(6)
                ],
            )
            n1 = sc.expression_table(
                wide, [ex.Binary("+", ex.ColumnRef(0), ex.ColumnRef(4))]
            )
            n2 = sc.expression_table(
                wide, [ex.Binary("*", ex.ColumnRef(2), ex.ColumnRef(4))]
            )
            sc.subscribe_table(
                n1, on_change=lambda k, row, t, d: events.append((k, row, d))
            )
            sc.subscribe_table(
                n2, on_change=lambda k, row, t, d: events.append((k, row, d))
            )
            sched = Scheduler(sc, optimize=optimize)
            for k, r in _rows(300):
                sess.insert(k, r)
            sched.commit()
            return wide, sorted(
                events, key=lambda e: (int(e[0]), e[2], repr(e[1]))
            )

        wide_on, ev_on = run(True)
        wide_off, ev_off = run(False)
        assert len(wide_on.expressions) == 3  # cols 0, 2, 4 survive
        assert wide_on.arity == 3
        assert len(wide_off.expressions) == 6
        assert ev_on == ev_off

    def test_no_narrowing_without_sinks(self):
        sc = Scope()
        rows = [(ref_scalar(i), (float(i), float(i), float(i))) for i in range(9)]
        src = sc.static_table(rows, 3)
        sc.expression_table(src, [ex.ColumnRef(0)])
        sc.run(optimize=True)
        # no SubscribeNode: intermediate .current reads are legal, so the
        # pushdown pass must leave every producer at full width
        assert src.arity == 3

    def test_groupby_consumer_blocks_narrowing(self):
        events: list = []
        sc = Scope()
        rows = [(ref_scalar(i), (i % 3, float(i), float(i))) for i in range(30)]
        src = sc.static_table(rows, 3)
        gb = sc.group_by_table(
            src, by_cols=[0], reducers=[(SumReducer(), [1])]
        )
        sc.subscribe_table(
            gb, on_change=lambda k, row, t, d: events.append((k, row, d))
        )
        sc.run(optimize=True)
        # GroupbyNode pre-builds its columnar plan at __init__ — it is not
        # a remappable consumer, so its producer keeps full arity even
        # though column 2 is dead
        assert src.arity == 3
        assert events


# -- exchange elision ---------------------------------------------------------


def _sharded_scopes(n=3, events=None):
    """Replicated graph with an elidable non-chain edge (expr -> concat)
    and a fusable chain feeding a groupby."""
    scopes = []
    for w in range(n):
        sc = Scope()
        rows = [(Pointer(i), (i % 7, float(i))) for i in range(400)]
        src = sc.static_table(rows, 2)
        e1 = sc.expression_table(
            src,
            [ex.ColumnRef(0), ex.Binary("*", ex.ColumnRef(1), ex.Const(2.0))],
        )
        f1 = sc.filter_table(
            sc.expression_table(
                e1,
                [
                    ex.ColumnRef(0),
                    ex.ColumnRef(1),
                    ex.Binary(">", ex.ColumnRef(1), ex.Const(50.0)),
                ],
            ),
            2,
        )
        gb = sc.group_by_table(
            f1, by_cols=[0], reducers=[(SumReducer(), [1])]
        )
        e2 = sc.expression_table(
            gb,
            [ex.ColumnRef(0), ex.Binary("+", ex.ColumnRef(1), ex.Const(1.0))],
        )
        cc = sc.concat_tables(
            [e2, sc.static_table([(Pointer(10**6), (99, -1.0))], 2)]
        )
        if w == 0 and events is not None:
            sc.subscribe_table(
                cc,
                on_change=lambda k, row, t, d: events.append((k, row, d)),
            )
        scopes.append(sc)
    return scopes


class TestExchangeElision:
    def _run(self, optimize, n=3):
        events: list = []
        scopes = _sharded_scopes(n, events)
        sched = ShardedScheduler(scopes, optimize=optimize)
        sched.finish()
        return sched, sorted(
            events, key=lambda e: (int(e[0]), e[2], repr(e[1]))
        )

    def test_sharded_parity_and_live_elision(self, opt_on):
        _, ev_off = self._run(False)
        before = EXCHANGE_STATS["elided"]
        sched, ev_on = self._run(True)
        assert ev_off == ev_on
        assert sched._elided  # at least the expr -> concat edge survives
        assert EXCHANGE_STATS["elided"] > before

    def test_verify_mode_accepts_proven_elisions(self, monkeypatch):
        # PATHWAY_TPU_VERIFY_ELISION recomputes the routing for every
        # elided delivery — a mis-proof raises AssertionError here
        monkeypatch.setattr(sharded_mod, "_VERIFY_ELISION", True)
        _, ev_off = self._run(False)
        _, ev_on = self._run(True)
        assert ev_off == ev_on

    def test_pwa201_count_matches_optimizer_stats(self, opt_on):
        # the analyzer finding set IS the elision oracle: counts agree
        events: list = []
        [scope] = _sharded_scopes(1, events)
        report = analyze_scope(scope)
        pwa201 = [f for f in report.findings if f.code == "PWA201"]
        optimize_scopes([_sharded_scopes(1, [])[0]])
        assert optimizer_stats()["exchanges_elided"] == len(pwa201)
        assert pwa201  # non-vacuous

    def test_elision_disabled_with_optimizer_off(self):
        sched, _ = self._run(False)
        assert sched._elided == set()


# -- framework parity corpus --------------------------------------------------


def _corpus():
    def groupby():
        t = pw.debug.table_from_rows(
            pw.schema_from_types(k=str, v=int),
            [(f"k{i % 5}", i) for i in range(60)],
        )
        sel = t.select(k=t.k, v=t.v * 2 + 1)
        flt = sel.filter(sel.v > 7)
        return flt.groupby(flt.k).reduce(
            k=flt.k, total=pw.reducers.sum(flt.v), cnt=pw.reducers.count()
        )

    def join():
        orders = pw.debug.table_from_rows(
            pw.schema_from_types(oid=int, cust=str, amount=float),
            [(i, f"c{i % 4}", float(i) * 1.5) for i in range(40)],
        )
        custs = pw.debug.table_from_rows(
            pw.schema_from_types(name=str, region=str),
            [(f"c{i}", f"r{i % 2}") for i in range(4)],
        )
        j = orders.join(custs, orders.cust == custs.name)
        return j.select(
            cust=orders.cust, region=custs.region, amount=orders.amount
        )

    def temporal():
        import pathway_tpu.stdlib.temporal as tmp

        t = pw.debug.table_from_rows(
            pw.schema_from_types(t=int, k=str, v=int),
            [(i % 23, f"k{i % 3}", i) for i in range(50)],
        )
        win = t.windowby(
            t.t, window=tmp.tumbling(duration=10), instance=t.k
        )
        return win.reduce(
            instance=pw.this["_pw_instance"],
            start=pw.this["_pw_window_start"],
            total=pw.reducers.sum(pw.this.v),
        )

    def iterate():
        t = pw.debug.table_from_rows(
            pw.schema_from_types(x=int), [(5,), (16,), (7,), (1,)]
        )

        def body(vals):
            return {
                "vals": vals.select(
                    x=pw.apply(
                        lambda v: v
                        if v == 1
                        else (v // 2 if v % 2 == 0 else 3 * v + 1),
                        vals.x,
                    )
                )
            }

        return pw.iterate(body, vals=t).vals

    return {
        "groupby": groupby,
        "join": join,
        "temporal": temporal,
        "iterate": iterate,
    }


def _capture(build, runner_factory, monkeypatch, optimize):
    monkeypatch.setenv("PATHWAY_TPU_OPTIMIZE", "1" if optimize else "0")
    G.clear()
    try:
        (state,) = runner_factory().capture(build())
    finally:
        G.clear()
    return dict(state)


@pytest.mark.parametrize("name", ["groupby", "join", "temporal", "iterate"])
def test_single_worker_parity(name, monkeypatch):
    build = _corpus()[name]
    off = _capture(build, GraphRunner, monkeypatch, False)
    on = _capture(build, GraphRunner, monkeypatch, True)
    assert off == on


@pytest.mark.parametrize("name", ["groupby", "join", "temporal", "iterate"])
def test_sharded_parity(name, monkeypatch):
    build = _corpus()[name]
    off = _capture(
        build, lambda: ShardedGraphRunner(3), monkeypatch, False
    )
    on = _capture(build, lambda: ShardedGraphRunner(3), monkeypatch, True)
    assert off == on


# -- TCP-mesh parity ----------------------------------------------------------


MESH_PROGRAM = """
    import os
    import pathway_tpu as pw

    words = pw.io.csv.read(
        {indir!r},
        schema=pw.schema_from_types(word=str, n=int),
        mode="static",
    )
    sel = words.select(word=pw.this.word, n=pw.this.n * 3 + 1)
    flt = sel.filter(sel.n > 10)
    counts = flt.groupby(flt.word).reduce(
        word=flt.word, total=pw.reducers.sum(flt.n)
    )
    pw.io.csv.write(counts, {out!r})
    pw.run()
"""


def _free_port_base(n: int) -> int:
    for _ in range(64):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
        probe.close()
        if base + n >= 65535:
            continue
        if all(_bindable(base + i) for i in range(n)):
            return base
    raise RuntimeError("no free port range found")


def _bindable(port: int) -> bool:
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", port))
        return True
    except OSError:
        return False
    finally:
        s.close()


def _spawn_mesh(tmp_path, code: str, optimize: bool, out):
    from pathway_tpu.cli import spawn

    prog = tmp_path / f"prog_{int(optimize)}.py"
    prog.write_text(textwrap.dedent(code))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PATHWAY_TPU_OPTIMIZE"] = "1" if optimize else "0"
    env.pop("PATHWAY_PERSISTENT_STORAGE", None)
    rc = spawn(
        sys.executable,
        [str(prog)],
        threads=1,
        processes=3,
        first_port=_free_port_base(3),
        env=env,
    )
    assert rc == 0
    with open(out, newline="") as fh:
        rows = list(csv.DictReader(fh))
    return sorted(
        (r["word"], int(r["total"]))
        for r in rows
        if int(r["diff"]) > 0
    )


def test_mesh_parity_optimize_on_off(tmp_path):
    indir = tmp_path / "in"
    indir.mkdir()
    with open(indir / "words.csv", "w") as fh:
        fh.write("word,n\n")
        fh.writelines(f"w{i % 11},{i % 9}\n" for i in range(300))
    results = {}
    for optimize in (False, True):
        out = tmp_path / f"out_{int(optimize)}.csv"
        results[optimize] = _spawn_mesh(
            tmp_path,
            MESH_PROGRAM.format(indir=str(indir), out=str(out)),
            optimize,
            out,
        )
    assert results[True] == results[False]
    assert results[True]  # the pipeline produced rows


# -- checkpoint compatibility -------------------------------------------------


class TestCheckpointCompat:
    def _snap(self, optimize, backend, restore_only=False):
        events: list = []
        sc, sess, tail = _chain_scope(events=events)
        sched = Scheduler(sc, optimize=optimize)
        mgr = OperatorSnapshotManager(backend)
        if restore_only:
            restored = mgr.restore(sc, [])
            return sc, tail, restored
        for k, r in _rows(600):
            sess.insert(k, r)
        sched.commit()
        mgr.snapshot(sc, [], sched.time)
        return sc, tail, None

    def test_round_trip_same_optimize_mode(self, opt_on):
        backend = MemoryBackend()
        _, tail1, _ = self._snap(True, backend)
        _, tail2, restored = self._snap(True, backend, restore_only=True)
        assert restored is not None
        assert dict(tail2.current) == dict(tail1.current)

    def test_round_trip_unoptimized(self):
        backend = MemoryBackend()
        _, tail1, _ = self._snap(False, backend)
        _, tail2, restored = self._snap(False, backend, restore_only=True)
        assert restored is not None
        assert dict(tail2.current) == dict(tail1.current)

    def test_cross_restore_refused_fused_to_unfused(self, opt_on):
        backend = MemoryBackend()
        self._snap(True, backend)
        with pytest.raises(ValueError, match="PATHWAY_TPU_OPTIMIZE|optimizer"):
            self._snap(False, backend, restore_only=True)

    def test_cross_restore_refused_unfused_to_fused(self, opt_on):
        backend = MemoryBackend()
        self._snap(False, backend)
        with pytest.raises(ValueError, match="PATHWAY_TPU_OPTIMIZE|optimizer"):
            self._snap(True, backend, restore_only=True)

    def test_pushdown_only_mismatch_refused(self, opt_on):
        # sigs stay identical (no fusion), only the pushdown fingerprint
        # differs — the versioned "optimize" payload check must trip
        def build(optimize, backend, restore_only=False):
            sc = Scope()
            rows = [
                (ref_scalar(i), tuple(float(i + c) for c in range(6)))
                for i in range(20)
            ]
            src = sc.static_table(rows, 6)
            a = sc.expression_table(
                src, [ex.Binary("+", ex.ColumnRef(1), ex.ColumnRef(3))]
            )
            b = sc.expression_table(
                src, [ex.Binary("*", ex.ColumnRef(1), ex.ColumnRef(3))]
            )
            sc.subscribe_table(a, on_change=lambda *args: None)
            sc.subscribe_table(b, on_change=lambda *args: None)
            sched = Scheduler(sc, optimize=optimize)
            mgr = OperatorSnapshotManager(backend)
            if restore_only:
                return mgr.restore(sc, [])
            sched.run_static()
            mgr.snapshot(sc, [], sched.time)

        backend = MemoryBackend()
        build(True, backend)
        with pytest.raises(ValueError, match="optimizer"):
            build(False, backend, restore_only=True)


# -- optimizer stats surface --------------------------------------------------


def test_exchange_stats_has_elided_counter():
    assert "elided" in EXCHANGE_STATS
    from pathway_tpu.engine import distributed as dist

    # distributed re-exports the SAME dict object (historical import path)
    assert dist.EXCHANGE_STATS is EXCHANGE_STATS


def test_groupby_reducers_still_work_after_fused_input():
    # chain feeding a groupby: the groupby consumes the fused tail's
    # output exactly as it consumed the unfused filter's
    def run(optimize):
        sc = Scope()
        sess = sc.input_session(2)
        e1 = sc.expression_table(
            sess,
            [
                ex.ColumnRef(0),
                ex.ColumnRef(1),
                ex.Binary(">", ex.ColumnRef(1), ex.Const(5.0)),
            ],
        )
        f1 = sc.filter_table(e1, 2)
        e2 = sc.expression_table(
            f1,
            [
                ex.Binary("%", ex.ColumnRef(0), ex.Const(4)),
                ex.ColumnRef(1),
            ],
        )
        gb = sc.group_by_table(
            e2,
            by_cols=[0],
            reducers=[(SumReducer(), [1]), (CountReducer(), [])],
        )
        sched = Scheduler(sc, optimize=optimize)
        for k, r in _rows(400):
            sess.insert(k, r)
        sched.commit()
        for k, r in _rows(30, start=50):
            sess.remove(k, r)
        sched.commit()
        return sorted(gb.current.values())

    assert run(False) == run(True)
