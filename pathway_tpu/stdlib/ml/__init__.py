"""ML helpers (reference: stdlib/ml/ — index.KNNIndex, classifiers,
smart_table_ops)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory, DataIndex


class KNNIndex:
    """Reference-compatible wrapper (stdlib/ml/index.py:301 KNNIndex) over
    the TPU HBM brute-force index."""

    def __init__(
        self,
        data_embedding: Any,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "cosine",
        metadata: Any = None,
    ) -> None:
        metric = {"cosine": "cos", "euclidean": "l2sq"}.get(
            distance_type, distance_type
        )
        self._index = DataIndex(
            data,
            BruteForceKnnFactory(dimensions=n_dimensions, metric=metric),
            data_embedding,
            metadata_column=metadata,
        )
        self.data = data

    def get_nearest_items(
        self,
        query_embedding: Any,
        k: int = 3,
        collapse_rows: bool = True,
    ) -> Table:
        deps = list(query_embedding._dependencies())
        query_table = deps[0].table
        if collapse_rows:
            return self._index.query_docs_as_of_now(
                query_table,
                query_embedding,
                doc_columns=self.data.column_names(),
                number_of_matches=k,
            )
        return self._index.query_as_of_now(
            query_table, query_embedding, number_of_matches=k,
            collapse_rows=False,
        )

    def get_nearest_items_asof_now(self, *args: Any, **kwargs: Any) -> Table:
        return self.get_nearest_items(*args, **kwargs)


from pathway_tpu.stdlib.ml import classifiers, hmm, smart_table_ops  # noqa: E402
from pathway_tpu.stdlib.ml.classifiers import (  # noqa: E402
    knn_lsh_classifier_train,
    knn_lsh_classify,
    knn_lsh_generic_classifier_train,
)
from pathway_tpu.stdlib.ml.smart_table_ops import (  # noqa: E402
    fuzzy_match_tables,
    fuzzy_self_match,
    smart_fuzzy_match,
)
