"""Fuzzy joins: feature-weighted record matching as incremental dataflow.

Reference: stdlib/ml/smart_table_ops/_fuzzy_join.py — rows are tokenized
into features, features weighted by inverse frequency, pair weight = sum
of shared-feature weights, and the returned matching keeps mutual-best
pairs (each kept pair is the heaviest for both its left and its right
row). Being plain joins/groupbys, matches revise automatically as rows
arrive or leave.
"""

from __future__ import annotations

import enum
import math
import re
from typing import Any, Callable

from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import apply as pw_apply, make_tuple
from pathway_tpu.internals.table import Table


class FuzzyJoinFeatureGeneration(enum.IntEnum):
    AUTO = 0
    TOKENIZE = 1
    LETTERS = 2


class FuzzyJoinNormalization(enum.IntEnum):
    WEIGHT = 0
    LOG_WEIGHT = 1
    NONE = 2


def _tokenize(obj: Any) -> tuple:
    return tuple(re.findall(r"\w+", str(obj).lower()))


def _letters(obj: Any) -> tuple:
    return tuple(c for c in str(obj).lower() if c.isalnum())


def _discrete_weight(cnt: float) -> float:
    """Reference _fuzzy_join.py:60: rare features dominate, very common
    features contribute nothing."""
    if cnt <= 1:
        return 10.0
    if cnt <= 3:
        return 5.0
    if cnt <= 100:
        return 1.0
    return 0.0


def _log_weight(cnt: float) -> float:
    return 1.0 / math.log(1.0 + cnt) if cnt > 0 else 0.0


_GENERATORS: dict[int, Callable[[Any], tuple]] = {
    FuzzyJoinFeatureGeneration.AUTO: _tokenize,
    FuzzyJoinFeatureGeneration.TOKENIZE: _tokenize,
    FuzzyJoinFeatureGeneration.LETTERS: _letters,
}

_WEIGHTS: dict[int, Callable[[float], float]] = {
    FuzzyJoinNormalization.WEIGHT: _discrete_weight,
    FuzzyJoinNormalization.LOG_WEIGHT: _log_weight,
    FuzzyJoinNormalization.NONE: lambda _c: 1.0,
}


def _features_of(table: Table, generator: Callable[[Any], tuple]) -> Table:
    cols = table.column_names()

    def concat_row(*values: Any) -> tuple:
        return tuple(
            tok for v in values if v is not None for tok in generator(v)
        )

    feats = table.select(
        _pw_feats=pw_apply(concat_row, *[table[c] for c in cols])
    )
    flat = feats.flatten(feats["_pw_feats"], origin_id="_pw_node")
    return flat.select(
        feature=flat["_pw_feats"], node=flat["_pw_node"]
    )


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    by_hand_match: Table | None = None,
    feature_generation: FuzzyJoinFeatureGeneration = FuzzyJoinFeatureGeneration.AUTO,
    normalization: FuzzyJoinNormalization = FuzzyJoinNormalization.WEIGHT,
) -> Table:
    """-> table(left: Pointer, right: Pointer, weight: float) of
    mutual-best fuzzy matches (reference fuzzy_match_tables :106)."""
    generator = _GENERATORS[feature_generation]
    weight_fn = _WEIGHTS[normalization]

    lf = _features_of(left_table, generator)
    rf = _features_of(right_table, generator)

    both = lf.select(feature=lf.feature).concat_reindex(
        rf.select(feature=rf.feature)
    )
    counts = both.groupby(both.feature).reduce(
        feature=both.feature, cnt=reducers.count()
    )

    lw = lf.join(counts, lf.feature == counts.feature).select(
        feature=lf.feature,
        node=lf.node,
        w=pw_apply(weight_fn, counts.cnt),
    )
    pairs = lw.join(rf, lw.feature == rf.feature).select(
        left=lw.node, right=rf.node, w=lw.w
    )
    scored = pairs.groupby(pairs.left, pairs.right).reduce(
        left=pairs.left,
        right=pairs.right,
        weight=reducers.sum(pairs.w),
    )
    # mutual-best: a pair survives when it is the heaviest (deterministic
    # tie-break by pair id) for both endpoints
    ranked = scored.select(
        left=scored.left,
        right=scored.right,
        weight=scored.weight,
        _pw_rank=make_tuple(scored.weight, scored.id),
    )
    best_l = ranked.groupby(ranked.left).reduce(
        left=ranked.left, best=reducers.max(ranked["_pw_rank"])
    )
    best_r = ranked.groupby(ranked.right).reduce(
        right=ranked.right, best=reducers.max(ranked["_pw_rank"])
    )
    with_l = ranked.join(best_l, ranked.left == best_l.left, id=ranked.id).select(
        left=ranked.left,
        right=ranked.right,
        weight=ranked.weight,
        _pw_rank=ranked["_pw_rank"],
        _pw_best_l=best_l.best,
    )
    with_lr = with_l.join(
        best_r, with_l.right == best_r.right, id=with_l.id
    ).select(
        left=with_l.left,
        right=with_l.right,
        weight=with_l.weight,
        _pw_ok=pw_apply(
            lambda rank, bl, br: rank == bl and rank == br,
            with_l["_pw_rank"],
            with_l["_pw_best_l"],
            best_r.best,
        ),
    )
    return with_lr.filter(with_lr["_pw_ok"])[["left", "right", "weight"]]


def fuzzy_self_match(
    table: Table,
    **kwargs: Any,
) -> Table:
    """Match a table against itself (reference fuzzy_self_match :249)."""
    other = table.select(**{c: table[c] for c in table.column_names()})
    matched = fuzzy_match_tables(table, other, **kwargs)
    # drop self-pairs: same source row matched to its own copy
    copies = other.select(_pw_orig=pw_apply(lambda *_a: None, *[other[c] for c in other.column_names()]))
    return matched.filter(
        pw_apply(lambda l, r: l != r, matched.left, matched.right)
    )


def smart_fuzzy_match(
    left_column: Any,
    right_column: Any,
    **kwargs: Any,
) -> Table:
    """Column-level convenience wrapper (reference smart_fuzzy_match :199)."""
    left = left_column.table.select(data=left_column)
    right = right_column.table.select(data=right_column)
    return fuzzy_match_tables(left, right, **kwargs)


fuzzy_match = fuzzy_match_tables
