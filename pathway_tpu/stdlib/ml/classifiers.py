"""LSH k-approximate nearest neighbors — pure dataflow, fully incremental.

Reference: stdlib/ml/classifiers/_knn_lsh.py:136-320. Because the whole
pipeline is ordinary joins/groupbys/UDFs, answers to *old* queries are
retracted and re-emitted whenever the data changes — this is the
incremental ``query`` contract the engine's as-of-now index deliberately
does not provide (SURVEY Appendix B).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import jmespath_lite
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import (
    apply as pw_apply,
    coalesce,
)
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.ml._lsh import (
    generate_cosine_lsh_bucketer,
    generate_euclidean_lsh_bucketer,
)


def _euclidean_distance(candidates: np.ndarray, query: np.ndarray) -> np.ndarray:
    return np.sum((candidates - query) ** 2, axis=1).astype(float)


def _cosine_distance(candidates: np.ndarray, query: np.ndarray) -> np.ndarray:
    return 1 - candidates @ query / (
        np.linalg.norm(candidates, axis=1) * np.linalg.norm(query)
    )


def knn_lsh_classifier_train(
    data: Table,
    L: int,
    type: str = "euclidean",  # noqa: A002
    **kwargs: Any,
) -> Callable:
    """Index ``data`` (column ``data``: vector; optional ``metadata``);
    returns ``lsh_perform_query(queries, k=None, with_distances=False)``
    (reference _knn_lsh.py:64)."""
    if type == "euclidean":
        bucketer = generate_euclidean_lsh_bucketer(
            kwargs["d"], kwargs["M"], L, kwargs.get("A", 1.0)
        )
        return knn_lsh_generic_classifier_train(
            data, bucketer, _euclidean_distance, L
        )
    if type == "cosine":
        bucketer = generate_cosine_lsh_bucketer(kwargs["d"], kwargs["M"], L)
        return knn_lsh_generic_classifier_train(
            data, bucketer, _cosine_distance, L
        )
    raise ValueError(f"unsupported LSH distance type {type!r}")


def knn_lsh_generic_classifier_train(
    data: Table, lsh_projection: Callable, distance_function: Callable, L: int
) -> Callable:
    has_meta = "metadata" in data.column_names()
    indexed = data.select(
        data=data.data,
        _pw_meta=data["metadata"]
        if has_meta
        else pw_apply(lambda _d: None, data.data),
        _pw_buckets=pw_apply(lsh_projection, data.data),
    )

    # per band: bucket -> sorted tuple of member row ids
    bands = []
    for b in range(L):
        banded = indexed.select(
            _pw_band=indexed["_pw_buckets"].get(b),
        )
        bands.append(
            banded.groupby(banded["_pw_band"]).reduce(
                _pw_band=banded["_pw_band"],
                items=reducers.sorted_tuple(banded.id),
            )
        )

    def lsh_perform_query(
        queries: Table, k: int | None = None, with_distances: bool = False
    ) -> Table:
        qcols = queries.column_names()
        q = queries.select(
            data=queries.data,
            _pw_k=queries["k"] if k is None else pw_apply(lambda _d: k, queries.data),
            _pw_filter=queries["metadata_filter"]
            if "metadata_filter" in qcols
            else pw_apply(lambda _d: None, queries.data),
            _pw_buckets=pw_apply(lsh_projection, queries.data),
        )
        # per band, look up the query's bucket members (empty when absent)
        merged = q
        for b, band_tbl in enumerate(bands):
            qb = merged.select(
                **{n: merged[n] for n in merged.column_names()},
                _pw_band=merged["_pw_buckets"].get(b),
            )
            hit = qb.join(
                band_tbl,
                qb["_pw_band"] == band_tbl["_pw_band"],
                id=qb.id,
            ).select(
                **{n: qb[n] for n in merged.column_names()},
                **{f"_pw_items_{b}": band_tbl.items},
            )
            base = qb.select(
                **{n: qb[n] for n in merged.column_names()},
                **{f"_pw_items_{b}": pw_apply(lambda _d: (), qb.data)},
            )
            merged = base.update_rows(hit)

        def merge_buckets(*tuples: tuple) -> tuple:
            seen: dict = {}
            for t in tuples:
                for p in t:
                    seen[p] = None
            return tuple(seen)

        flattened = merged.select(
            data=merged.data,
            _pw_k=merged["_pw_k"],
            _pw_filter=merged["_pw_filter"],
            _pw_ids=pw_apply(
                merge_buckets,
                *[merged[f"_pw_items_{b}"] for b in range(L)],
            ),
        )
        nonempty = flattened.filter(
            pw_apply(lambda ids: ids != (), flattened["_pw_ids"])
        )
        exploded = nonempty.flatten(nonempty["_pw_ids"], origin_id="_pw_origin")
        fetched = indexed.ix(exploded["_pw_ids"])
        cands = exploded.select(
            _pw_origin=exploded["_pw_origin"],
            _pw_cand_id=exploded["_pw_ids"],
            _pw_cand_data=fetched.data,
            _pw_cand_meta=fetched["_pw_meta"],
        )
        regrouped = cands.groupby(id=cands["_pw_origin"]).reduce(
            _pw_cand_ids=reducers.tuple(cands["_pw_cand_id"]),
            _pw_cand_datas=reducers.tuple(cands["_pw_cand_data"]),
            _pw_cand_metas=reducers.tuple(cands["_pw_cand_meta"]),
        )
        from pathway_tpu.internals.universe import solver

        # group keys are nonempty's row ids (groupby id=origin)
        solver.register_subset(regrouped._universe, nonempty._universe)

        def knns(query_vec, cand_ids, cand_datas, cand_metas, meta_filter, kk):
            try:
                picked = [
                    (cid, cdata)
                    for cid, cdata, cmeta in zip(cand_ids, cand_datas, cand_metas)
                    if meta_filter is None
                    or jmespath_lite.search(
                        meta_filter,
                        cmeta.value if hasattr(cmeta, "value") else cmeta,
                    )
                    is True
                ]
            except jmespath_lite.JMESPathError:
                picked = []
            if not picked:
                return ()
            ids, vecs = zip(*picked)
            arr = np.asarray(vecs, dtype=np.float64)
            dists = distance_function(arr, np.asarray(query_vec, np.float64))
            order = np.argsort(dists, kind="stable")[: int(kk)]
            return tuple((ids[i], float(dists[i])) for i in order)

        answered = nonempty.restrict(regrouped).select(
            _pw_knns=pw_apply(
                knns,
                nonempty.data,
                regrouped["_pw_cand_ids"],
                regrouped["_pw_cand_datas"],
                regrouped["_pw_cand_metas"],
                nonempty["_pw_filter"],
                nonempty["_pw_k"],
            ),
        )
        result = q.join(
            answered, q.id == answered.id, id=q.id, how="left"
        ).select(
            query_id=q.id,
            knns_ids_with_dists=coalesce(answered["_pw_knns"], ()),
        )
        if with_distances:
            return result
        return result.select(
            query_id=result["query_id"],
            knns_ids=pw_apply(
                lambda pairs: tuple(p for p, _d in pairs),
                result["knns_ids_with_dists"],
            ),
        )

    return lsh_perform_query


def knn_lsh_classify(
    knn_model: Callable, data_labels: Table, queries: Table, k: int
) -> Table:
    """Majority label among the k approximate neighbors
    (reference _knn_lsh.py:309 knn_lsh_classify)."""
    knns = knn_model(queries, k)
    exploded = knns.filter(
        pw_apply(lambda ids: ids != (), knns["knns_ids"])
    )
    flat = exploded.flatten(exploded["knns_ids"], origin_id="_pw_origin")
    labels = data_labels.ix(flat["knns_ids"])
    pairs = flat.select(
        _pw_origin=flat["_pw_origin"],
        label=labels[data_labels.column_names()[0]],
    )

    def majority(labels_tuple: tuple):
        from statistics import mode

        return mode(labels_tuple)

    return (
        pairs.groupby(id=pairs["_pw_origin"])
        .reduce(_pw_labels=reducers.tuple(pairs.label))
        .select(predicted_label=pw_apply(majority, pw_this_labels()))
    )


def pw_this_labels():
    from pathway_tpu.internals.thisclass import this

    return this["_pw_labels"]
