"""LSH bucketers: map vectors to L band-bucket ids.

Reference: stdlib/ml/classifiers/_lsh.py — random projections, M ANDs per
band, L ORs (bands), fingerprinted to one integer per band.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _fingerprint(arr: np.ndarray) -> int:
    digest = hashlib.blake2s(
        np.ascontiguousarray(arr, dtype=np.int64).tobytes(), digest_size=4
    ).digest()
    return int.from_bytes(digest, "little", signed=True)


def generate_euclidean_lsh_bucketer(d: int, M: int, L: int, A: float = 1.0, seed: int = 0):
    """Euclidean LSH: project on M*L random unit lines, bucketize by length
    A, fingerprint each band of M lines (reference _lsh.py:31)."""
    gen = np.random.default_rng(seed=seed)
    total = M * L
    lines = gen.standard_normal((d, total))
    lines = lines / np.linalg.norm(lines, axis=0)
    shift = gen.random(size=total) * A

    def bucketify(x) -> tuple:
        x = np.asarray(x, dtype=np.float64).reshape(d)
        buckets = np.floor_divide(x @ lines + shift, A).astype(np.int64)
        return tuple(_fingerprint(band) for band in np.split(buckets, L))

    return bucketify


def generate_cosine_lsh_bucketer(d: int, M: int, L: int, seed: int = 0):
    """Cosine LSH: sign patterns of M*L random hyperplanes
    (reference _lsh.py:59)."""
    gen = np.random.default_rng(seed=seed)
    total = M * L
    planes = gen.standard_normal((d, total))

    def bucketify(x) -> tuple:
        x = np.asarray(x, dtype=np.float64).reshape(d)
        signs = (x @ planes >= 0).astype(np.int64)
        return tuple(_fingerprint(band) for band in np.split(signs, L))

    return bucketify
