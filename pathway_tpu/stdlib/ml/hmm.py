"""Hidden Markov Model decoding as a stateful reducer.

Reference: stdlib/ml/hmm.py:11 create_hmm_reducer — the HMM is a networkx
DiGraph whose nodes carry ``calc_emission_log_ppb(observation)`` and whose
edges carry ``log_transition_ppb``; the reducer Viterbi-decodes the
observation stream of each group and emits the most likely state path.
Use with ``pw.reducers.stateful_single``.
"""

from __future__ import annotations

from typing import Any, Callable


def create_hmm_reducer(
    graph: Any,
    beam_size: int | None = None,
    num_results_kept: int | None = None,
) -> Callable[[list], tuple]:
    """Returns ``decode(observations) -> tuple[state, ...]`` for use as a
    stateful reducer combine function."""
    states = list(graph.nodes)
    emission = {
        s: graph.nodes[s]["calc_emission_log_ppb"] for s in states
    }
    transitions: dict[Any, list[tuple[Any, float]]] = {s: [] for s in states}
    for u, v, attrs in graph.edges(data=True):
        transitions[v].append((u, attrs["log_transition_ppb"]))
    start_nodes = list(graph.graph.get("start_nodes", states))

    def decode(observations: list) -> tuple:
        if not observations:
            return ()
        # Viterbi over the observation sequence
        neg_inf = float("-inf")
        scores: dict[Any, float] = {}
        paths: dict[Any, tuple] = {}
        first = observations[0]
        for s in start_nodes:
            e = emission[s](first)
            if e is not None:
                scores[s] = e
                paths[s] = (s,)
        for obs in observations[1:]:
            new_scores: dict[Any, float] = {}
            new_paths: dict[Any, tuple] = {}
            for s in states:
                best_prev, best_score = None, neg_inf
                for prev, logp in transitions[s]:
                    prev_score = scores.get(prev, neg_inf)
                    if prev_score + logp > best_score:
                        best_prev, best_score = prev, prev_score + logp
                if best_prev is None:
                    continue
                e = emission[s](obs)
                if e is None:
                    continue
                new_scores[s] = best_score + e
                new_paths[s] = paths[best_prev] + (s,)
            if beam_size is not None and len(new_scores) > beam_size:
                kept = sorted(
                    new_scores, key=lambda st: new_scores[st], reverse=True
                )[:beam_size]
                new_scores = {st: new_scores[st] for st in kept}
                new_paths = {st: new_paths[st] for st in kept}
            scores, paths = new_scores, new_paths
            if not scores:
                return ()
        best = max(scores, key=lambda st: scores[st])
        path = paths[best]
        if num_results_kept is not None:
            path = path[-num_results_kept:]
        return path

    return decode
