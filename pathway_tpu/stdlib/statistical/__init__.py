"""Statistical helpers (reference: stdlib/statistical/_interpolate.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table, TableSpec


def interpolate(
    table: Table, timestamp: Any, *value_columns: Any, mode: str = "linear"
) -> Table:
    """Linearly interpolate None values of ``value_columns`` over the series
    ordered by ``timestamp`` (reference: pw.statistical.interpolate).

    Boundary Nones take the nearest known value. Recomputed per affected
    commit over the table's current state (the host-loop strategy the
    engine uses for order-dependent operators).
    """
    from pathway_tpu.internals.desugaring import resolve_this

    t_ref = resolve_this(timestamp, table)
    cols = table.column_names()
    t_idx = cols.index(t_ref.name)
    v_idx = [cols.index(resolve_this(v, table).name) for v in value_columns]

    def transform(state: dict) -> dict:
        items = sorted(state.items(), key=lambda kv: (kv[1][t_idx], int(kv[0])))
        out = {}
        for vi in v_idx:
            known = [
                (i, row[t_idx], row[vi])
                for i, (_k, row) in enumerate(items)
                if row[vi] is not None
            ]
            filled: list = []
            for i, (_key, row) in enumerate(items):
                if row[vi] is not None:
                    filled.append(row[vi])
                    continue
                before = [k for k in known if k[0] < i]
                after = [k for k in known if k[0] > i]
                if before and after:
                    _i0, t0, v0 = before[-1]
                    _i1, t1, v1 = after[0]
                    t = row[t_idx]
                    frac = (t - t0) / (t1 - t0) if t1 != t0 else 0.0
                    filled.append(v0 + (v1 - v0) * frac)
                elif before:
                    filled.append(before[-1][2])
                elif after:
                    filled.append(after[0][2])
                else:
                    filled.append(None)
            for (key, row), value in zip(items, filled):
                base = out.get(key, list(row))
                base = list(base)
                base[vi] = value
                out[key] = base
        return {k: tuple(v) for k, v in out.items()}

    return table._derived(
        TableSpec("table_transform", [table], {"fn": transform}),
        {n: (dt.ANY if i in v_idx else table._dtypes[n]) for i, n in enumerate(cols)},
        universe=table._universe,
    )
