"""Temporal stdlib: windows, temporal behaviors, interval/asof joins.

Reference: python/pathway/stdlib/temporal/ — `windowby` with tumbling/
sliding/session windows (_window.py:593-863), CommonBehavior /
ExactlyOnceBehavior (temporal_behavior.py:21,79), interval_join
(_interval_join.py), asof_join (_asof_join.py), asof_now_join
(_asof_now_join.py). Behaviors lower to the engine's event-time
buffer/forget operators (engine/temporal.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import reducers as _reducers
from pathway_tpu.internals.expression import (
    ColumnExpression,
    apply as pw_apply,
    make_tuple,
    wrap_expression,
)
from pathway_tpu.internals.table import Table, TableSpec
from pathway_tpu.internals.desugaring import resolve_this, resolve_side


# -- behaviors ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CommonBehavior:
    """delay: emit window results only once the watermark passes
    window *start* + delay (reference anchors initial output at the
    beginning of the window, _window.py:396); cutoff: forget windows
    whose end passed watermark - cutoff; keep_results: whether forgotten
    windows keep their final output (reference temporal_behavior.py:21)."""

    delay: Any = None
    cutoff: Any = None
    keep_results: bool = True


def common_behavior(
    delay: Any = None, cutoff: Any = None, keep_results: bool = True
) -> CommonBehavior:
    return CommonBehavior(delay, cutoff, keep_results)


@dataclasses.dataclass(frozen=True)
class ExactlyOnceBehavior:
    """Each window emitted exactly once, then frozen. Lowered per-window to
    ``CommonBehavior(delay=duration + shift, cutoff=shift)`` at materialize
    time (reference temporal_behavior.py:79, _window.py:371-387)."""

    shift: Any = None


def exactly_once_behavior(shift: Any = None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift)


# -- windows -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TumblingWindow:
    duration: Any
    origin: Any = 0

    def assign(self, t: Any) -> tuple:
        start = ((t - self.origin) // self.duration) * self.duration + self.origin
        return ((start, start + self.duration),)


@dataclasses.dataclass(frozen=True)
class SlidingWindow:
    hop: Any
    duration: Any
    origin: Any = 0

    def assign(self, t: Any) -> tuple:
        # windows [s, s+duration) with s ≡ origin (mod hop) containing t
        out = []
        s = ((t - self.origin - self.duration) // self.hop) * self.hop + self.origin
        while s <= t:
            if s <= t < s + self.duration:
                out.append((s, s + self.duration))
            s += self.hop
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class SessionWindow:
    max_gap: Any


def tumbling(duration: Any, origin: Any = 0) -> TumblingWindow:
    return TumblingWindow(duration, origin)


def sliding(hop: Any, duration: Any, origin: Any = 0) -> SlidingWindow:
    return SlidingWindow(hop, duration, origin)


def session(max_gap: Any) -> SessionWindow:
    return SessionWindow(max_gap)


def _assign_windows(
    table: Table, time_expr: Any, window: Any, instance: Any
) -> Table:
    """Window assignment: every row gains ``_pw_time``/``_pw_instance``/
    ``_pw_window_start``/``_pw_window_end`` (sliding windows flatten rows
    into one copy per containing window). Shared by ``windowby`` and
    ``window_join``."""
    t = table
    base_cols = {n: t[n] for n in t.column_names()}
    inst_expr = (
        instance if instance is not None else pw_apply(lambda _t: 0, time_expr)
    )
    if isinstance(window, SessionWindow):
        pre = t.select(**base_cols, _pw_time=time_expr, _pw_instance=inst_expr)
        n = len(pre.column_names())
        return pre._derived(
            TableSpec(
                "session_assign",
                [pre],
                {
                    "time_col": n - 2,
                    "instance_col": n - 1,
                    "max_gap": window.max_gap,
                },
            ),
            {
                **{c: pre._dtypes[c] for c in pre.column_names()},
                "_pw_window_start": dt.ANY,
                "_pw_window_end": dt.ANY,
            },
        )
    pre = t.select(
        **base_cols,
        _pw_time=time_expr,
        _pw_instance=inst_expr,
        _pw_windows=pw_apply(lambda tv: window.assign(tv), time_expr),
    )
    flat = pre.flatten(pre["_pw_windows"])
    return flat.select(
        **{n: flat[n] for n in t.column_names()},
        _pw_time=flat["_pw_time"],
        _pw_instance=flat["_pw_instance"],
        _pw_window_start=flat["_pw_windows"].get(0),
        _pw_window_end=flat["_pw_windows"].get(1),
    )


class WindowedTable:
    """`t.windowby(...)`; materialize with `.reduce(**aggregations)`.

    Inside reduce, ``pw.this['_pw_window_start'] / ['_pw_window_end'] /
    ['_pw_instance']`` reference the window bounds (reference exposes the
    same columns)."""

    def __init__(
        self,
        table: Table,
        time_expr: ColumnExpression,
        window: Any,
        instance: ColumnExpression | None,
        behavior: CommonBehavior | None,
    ) -> None:
        self.table = table
        self.time_expr = time_expr
        self.window = window
        self.instance = instance
        self.behavior = behavior

    def _assigned(self) -> Table:
        return _assign_windows(
            self.table, self.time_expr, self.window, self.instance
        )

    def _lowered_behavior(self) -> CommonBehavior | None:
        """ExactlyOnce → CommonBehavior(duration + shift, shift, True), as
        the reference does per-window (_window.py:371-387)."""
        b = self.behavior
        if not isinstance(b, ExactlyOnceBehavior):
            return b
        if isinstance(self.window, TumblingWindow):
            duration = self.window.duration
        elif isinstance(self.window, SlidingWindow):
            duration = self.window.duration
        else:
            raise ValueError(
                "exactly_once_behavior is unsupported for session windows"
            )
        shift = b.shift if b.shift is not None else 0
        return CommonBehavior(
            delay=duration + shift, cutoff=shift, keep_results=True
        )

    def _behaved(self, assigned: Table) -> Table:
        behavior = self._lowered_behavior()
        if behavior is None:
            return assigned
        cols = assigned.column_names()
        time_col = cols.index("_pw_time")
        out = assigned
        # CUTOFF gates arrivals FIRST, on the raw stream: its watermark
        # must advance with every arriving row. Downstream of the delay
        # buffer it would only see released rows — held rows would not
        # move it, and a late row for an already-emitted window could
        # slip past the cutoff (caught by the behaviors x windows matrix:
        # exactly_once emitted a second, revised result).
        if behavior.cutoff is not None:
            cutoff = behavior.cutoff
            out = out.select(
                **{n: out[n] for n in cols},
                _pw_threshold=pw_apply(
                    lambda e: e + cutoff, out["_pw_window_end"]
                ),
            )
            kind = "forget" if not behavior.keep_results else "freeze"
            out = out._derived(
                TableSpec(
                    kind,
                    [out],
                    {
                        "threshold_col": len(cols),
                        "time_col": time_col,
                    },
                ),
                {n: out._dtypes[n] for n in out.column_names()},
            )[cols]
        if behavior.delay is not None:
            # anchored at window *start* (reference _window.py:396-398:
            # "delays initial output ... with respect to the beginning of
            # the window")
            delay = behavior.delay
            out = out.select(
                **{n: out[n] for n in cols},
                _pw_threshold=pw_apply(
                    lambda s: s + delay, out["_pw_window_start"]
                ),
            )
            out = out._derived(
                TableSpec(
                    "buffer",
                    [out],
                    {
                        "threshold_col": len(cols),
                        "time_col": time_col,
                    },
                ),
                {n: out._dtypes[n] for n in out.column_names()},
            )[cols]
        return out

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        if isinstance(self.window, IntervalsOverWindow):
            return self._reduce_intervals_over(*args, **kwargs)
        assigned = self._behaved(self._assigned())
        grouped = assigned.groupby(
            assigned["_pw_window_start"],
            assigned["_pw_window_end"],
            assigned["_pw_instance"],
        )
        resolved_kwargs = {}
        for name, value in kwargs.items():
            resolved_kwargs[name] = _retarget(value, self.table, assigned)
        for arg in args:
            resolved = _retarget(arg, self.table, assigned)
            resolved_kwargs[resolved.name] = resolved
        return grouped.reduce(**resolved_kwargs)

    def _reduce_intervals_over(self, *args: Any, **kwargs: Any) -> Table:
        """intervals_over windows: one group per value of ``at`` containing
        rows with time in [at + lower, at + upper]; with is_outer, empty
        windows surface with None aggregates (reference _window.py:771)."""
        w = self.window
        at_ref = w.at
        at_table = at_ref.table
        lb, ub = w.lower_bound, w.upper_bound
        probe = at_table.select(_pw_at=at_ref)
        joined = interval_join(
            probe,
            self.table,
            probe["_pw_at"],
            self.time_expr,
            interval(lb, ub),
            how="inner",
        )
        # instance rides as a GROUP key, not a join equality — every
        # at-window sees all rows, groups split per instance (reference
        # _IntervalsOverWindow._apply, _window.py:557-568)
        inst_kwargs = (
            {"_pw_instance": self.instance}
            if self.instance is not None
            else {}
        )
        flat = joined.select(
            *[self.table[n] for n in self.table.column_names()],
            _pw_window_start=pw_apply(lambda p: p + lb, probe["_pw_at"]),
            _pw_window_end=pw_apply(lambda p: p + ub, probe["_pw_at"]),
            **inst_kwargs,
        )
        by = [flat["_pw_window_start"], flat["_pw_window_end"]]
        if self.instance is not None:
            by.append(flat["_pw_instance"])
        grouped = flat.groupby(*by)
        resolved_kwargs = {}
        for arg in args:
            resolved = _retarget(arg, self.table, flat)
            resolved_kwargs[resolved.name] = resolved
        for name, value in kwargs.items():
            resolved_kwargs[name] = _retarget(value, self.table, flat)
        user_names = list(resolved_kwargs)
        # the bounds always ride along (needed to match empty windows back)
        resolved_kwargs.setdefault("_pw_window_start", flat["_pw_window_start"])
        resolved_kwargs.setdefault("_pw_window_end", flat["_pw_window_end"])
        reduced = grouped.reduce(**resolved_kwargs)
        if not w.is_outer:
            return reduced[user_names]
        # outer: every at-value yields a window even when empty
        windows = probe.groupby(probe["_pw_at"]).reduce(
            _pw_at=probe["_pw_at"]
        )
        windows = windows.select(
            _pw_window_start=pw_apply(lambda p: p + lb, windows["_pw_at"]),
            _pw_window_end=pw_apply(lambda p: p + ub, windows["_pw_at"]),
        )
        join = windows.join(
            reduced,
            windows["_pw_window_start"] == reduced["_pw_window_start"],
            windows["_pw_window_end"] == reduced["_pw_window_end"],
            how="left",
        )
        from pathway_tpu.internals.expression import ColumnReference

        out_cols = {}
        for n in user_names:
            resolved = resolved_kwargs[n]
            bound_ref = (
                resolved.name
                if isinstance(resolved, ColumnReference)
                and resolved.name in ("_pw_window_start", "_pw_window_end")
                else None
            )
            if n in ("_pw_window_start", "_pw_window_end"):
                out_cols[n] = windows[n]
            elif bound_ref is not None:
                # a user-renamed window bound (e.g. start=this._pw_window_start)
                # must keep its value for EMPTY windows too — the reduced
                # side is all-None there
                out_cols[n] = windows[bound_ref]
            else:
                out_cols[n] = reduced[n]
        return join.select(**out_cols)


def _retarget(expression: Any, source: Table, target: Table) -> Any:
    """Rewrite references from the pre-window table onto the assigned table
    (same column names survive the window assignment select)."""
    from pathway_tpu.internals import expression as pex
    from pathway_tpu.internals.desugaring import substitute
    from pathway_tpu.internals.expression import ColumnReference

    expression = resolve_this(expression, target)

    def replace(e: Any) -> Any:
        if isinstance(e, ColumnReference) and e.table is source:
            return ColumnReference(target, e.name)
        return None

    return substitute(wrap_expression(expression), replace)


def windowby(
    table: Table,
    time_expr: Any,
    *,
    window: Any,
    instance: Any = None,
    behavior: CommonBehavior | None = None,
) -> WindowedTable:
    time_resolved = resolve_this(time_expr, table)
    inst_resolved = resolve_this(instance, table) if instance is not None else None
    return WindowedTable(table, time_resolved, window, inst_resolved, behavior)


# -- temporal joins ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound: Any, upper_bound: Any) -> Interval:
    return Interval(lower_bound, upper_bound)


class _TemporalJoinResult:
    def __init__(
        self,
        kind: str,
        left: Table,
        right: Table,
        params: dict,
        on: Sequence[Any],
        how: str,
    ) -> None:
        self._kind = kind
        self._left = left
        self._right = right
        self._params = params
        self._how = how
        from pathway_tpu.internals.desugaring import resolve_join_sides
        from pathway_tpu.internals.expression import BinaryOpExpression

        if left is right:
            raise ValueError(
                "temporal self-joins need distinct table objects; derive a "
                "copy first (e.g. right = left.select(*left))"
            )
        self._on = []
        for cond in on:
            resolved = resolve_join_sides(cond, left, right)
            if not (
                isinstance(resolved, BinaryOpExpression) and resolved._op == "=="
            ):
                raise ValueError("temporal join conditions must be equalities")
            self._on.append((resolved._left, resolved._right))
        if kind in ("interval_join", "asof_join"):
            # several equalities fold into one tuple-valued join key at
            # lowering time (reference takes `*on` the same way,
            # _interval_join.py:583)
            direction = params.get("direction")
            if direction is not None and direction not in (
                "backward",
                "forward",
                "nearest",
            ):
                raise ValueError(
                    f"asof direction must be backward/forward/nearest, "
                    f"got {direction!r}"
                )

    def select(self, *args: Any, **kwargs: Any) -> Table:
        from pathway_tpu.internals.desugaring import resolve_join_sides
        from pathway_tpu.internals.expression import ColumnReference

        exprs: dict[str, ColumnExpression] = {}
        for arg in args:
            resolved = resolve_join_sides(arg, self._left, self._right)
            if not isinstance(resolved, ColumnReference):
                raise ValueError("positional args must be column references")
            exprs[resolved.name] = resolved
        for name, value in kwargs.items():
            exprs[name] = resolve_join_sides(value, self._left, self._right)
        dtypes = {n: e._dtype for n, e in exprs.items()}
        return Table(
            TableSpec(
                self._kind,
                [self._left, self._right],
                {
                    **self._params,
                    "on": self._on,
                    "how": self._how,
                    "exprs": exprs,
                },
            ),
            list(exprs.keys()),
            dtypes,
        )


def interval_join(
    left: Table,
    right: Table,
    left_time: Any,
    right_time: Any,
    interval: Interval,
    *on: Any,
    how: str = "inner",
) -> _TemporalJoinResult:
    return _TemporalJoinResult(
        "interval_join",
        left,
        right,
        {
            "left_time": resolve_side(left_time, left, "left"),
            "right_time": resolve_side(right_time, right, "right"),
            "lower_bound": interval.lower_bound,
            "upper_bound": interval.upper_bound,
        },
        on,
        how,
    )


def interval_join_left(left, right, lt, rt, iv, *on):
    return interval_join(left, right, lt, rt, iv, *on, how="left")


def interval_join_right(left, right, lt, rt, iv, *on):
    return interval_join(left, right, lt, rt, iv, *on, how="right")


def interval_join_outer(left, right, lt, rt, iv, *on):
    return interval_join(left, right, lt, rt, iv, *on, how="outer")


def asof_join(
    left: Table,
    right: Table,
    left_time: Any,
    right_time: Any,
    *on: Any,
    how: str = "inner",
    direction: str = "backward",
) -> _TemporalJoinResult:
    return _TemporalJoinResult(
        "asof_join",
        left,
        right,
        {
            "left_time": resolve_side(left_time, left, "left"),
            "right_time": resolve_side(right_time, right, "right"),
            "direction": direction,
        },
        on,
        how,
    )


def asof_join_left(left, right, lt, rt, *on, direction="backward"):
    return asof_join(left, right, lt, rt, *on, how="left", direction=direction)


def asof_now_join(
    left: Table, right: Table, *on: Any, how: str = "inner"
) -> _TemporalJoinResult:
    return _TemporalJoinResult("asof_now_join", left, right, {}, on, how)


def asof_now_join_left(left, right, *on):
    return asof_now_join(left, right, *on, how="left")


# -- window join --------------------------------------------------------------


class WindowJoinResult:
    """Result of ``window_join``: records of both sides sharing a window
    (and satisfying the ``on`` equalities) are joined; ``.select()``
    accepts references to the original tables plus pw.left/pw.right
    (reference: _window_join.py:24 WindowJoinResult)."""

    def __init__(
        self,
        orig_left: Table,
        orig_right: Table,
        left_assigned: Table,
        right_assigned: Table,
        conds: list,
        how: str,
    ) -> None:
        from pathway_tpu.internals.joins import JoinResult

        self._orig_left = orig_left
        self._orig_right = orig_right
        self._left_assigned = left_assigned
        self._right_assigned = right_assigned
        self._join = JoinResult(left_assigned, right_assigned, tuple(conds), how)

    def _retarget_both(self, expression: Any) -> Any:
        from pathway_tpu.internals.desugaring import (
            resolve_join_sides,
            substitute,
        )
        from pathway_tpu.internals.expression import ColumnReference

        if isinstance(expression, str):
            # bare column name binds to the left side, like resolve_this
            expression = ColumnReference(self._left_assigned, expression)
        # pw.left / pw.right / pw.this(→left) address the join sides
        # (reference WindowJoinResult.select accepts them alongside refs)
        e = resolve_join_sides(
            expression, self._left_assigned, self._right_assigned
        )

        # rewrite direct refs to the ORIGINAL tables onto the assigned twins
        def replace(x: Any) -> Any:
            if isinstance(x, ColumnReference):
                if x.table is self._orig_left:
                    return ColumnReference(self._left_assigned, x.name)
                if x.table is self._orig_right:
                    return ColumnReference(self._right_assigned, x.name)
            return None

        return substitute(e, replace)

    def select(self, *args: Any, **kwargs: Any) -> Table:
        from pathway_tpu.internals.expression import ColumnReference

        out_args = []
        for arg in args:
            r = self._retarget_both(arg)
            if not isinstance(r, ColumnReference):
                raise ValueError("positional args must be column references")
            out_args.append(r)
        out_kwargs = {
            name: self._retarget_both(v) for name, v in kwargs.items()
        }
        return self._join.select(*out_args, **out_kwargs)


def _session_window_sides(
    left: Table,
    right: Table,
    left_time: Any,
    right_time: Any,
    window: SessionWindow,
    on_pairs: list,
    linst: Any,
    rinst: Any,
) -> tuple[Table, Table]:
    """Sessions span the *union* of both sides' records per (instance,
    on-values) group (reference _window_join.py session path)."""
    lt = resolve_side(left_time, left, "left")
    rt = resolve_side(right_time, right, "right")
    lgrp = make_tuple(
        linst if linst is not None else wrap_expression(0),
        *[lexpr for lexpr, _r in on_pairs],
    )
    rgrp = make_tuple(
        rinst if rinst is not None else wrap_expression(0),
        *[rexpr for _l, rexpr in on_pairs],
    )
    lg = left.select(_pw_t=lt, _pw_grp=lgrp)
    rg = right.select(_pw_t=rt, _pw_grp=rgrp)
    merged = lg.concat_reindex(rg)
    n = len(merged.column_names())
    assigned = merged._derived(
        TableSpec(
            "session_assign",
            [merged],
            {"time_col": 0, "instance_col": 1, "max_gap": window.max_gap},
        ),
        {
            **{c: merged._dtypes[c] for c in merged.column_names()},
            "_pw_window_start": dt.ANY,
            "_pw_window_end": dt.ANY,
        },
    )
    sess = assigned.groupby(assigned["_pw_t"], assigned["_pw_grp"]).reduce(
        _pw_t=assigned["_pw_t"],
        _pw_grp=assigned["_pw_grp"],
        _pw_window_start=_reducers.min(assigned["_pw_window_start"]),
        _pw_window_end=_reducers.min(assigned["_pw_window_end"]),
    )

    def attach(table: Table, t_expr: Any, grp_expr: Any) -> Table:
        base = table.select(
            **{n_: table[n_] for n_ in table.column_names()},
            _pw_t=t_expr,
            _pw_grp=grp_expr,
        )
        joined = base.join(
            sess,
            base["_pw_t"] == sess["_pw_t"],
            base["_pw_grp"] == sess["_pw_grp"],
            id=base.id,
        )
        return joined.select(
            *[base[n_] for n_ in table.column_names()],
            _pw_instance=base["_pw_grp"],
            _pw_window_start=sess["_pw_window_start"],
            _pw_window_end=sess["_pw_window_end"],
        )

    return attach(left, lt, lgrp), attach(right, rt, rgrp)


def window_join(
    left: Table,
    right: Table,
    left_time: Any,
    right_time: Any,
    window: Any,
    *on: Any,
    how: str = "inner",
    left_instance: Any = None,
    right_instance: Any = None,
) -> WindowJoinResult:
    """Join records that fall into the same window (reference:
    _window_join.py:156). Sliding windows join matching pairs once per
    shared window; session windows build sessions over the union of both
    sides."""
    from pathway_tpu.internals.desugaring import resolve_join_sides
    from pathway_tpu.internals.expression import BinaryOpExpression

    if left is right:
        raise ValueError(
            "window self-joins need distinct table objects; derive a copy "
            "first (e.g. right = left.select(*left))"
        )
    on_pairs = []
    for cond in on:
        resolved = resolve_join_sides(cond, left, right)
        if not (
            isinstance(resolved, BinaryOpExpression) and resolved._op == "=="
        ):
            raise ValueError("window_join conditions must be equalities")
        on_pairs.append((resolved._left, resolved._right))
    linst = (
        resolve_side(left_instance, left, "left")
        if left_instance is not None
        else None
    )
    rinst = (
        resolve_side(right_instance, right, "right")
        if right_instance is not None
        else None
    )

    if isinstance(window, SessionWindow):
        la, ra = _session_window_sides(
            left, right, left_time, right_time, window, on_pairs, linst, rinst
        )
        conds = [
            la["_pw_window_start"] == ra["_pw_window_start"],
            la["_pw_window_end"] == ra["_pw_window_end"],
            la["_pw_instance"] == ra["_pw_instance"],
        ]
        return WindowJoinResult(left, right, la, ra, conds, how)

    la = _assign_windows(left, resolve_side(left_time, left, "left"), window, linst)
    ra = _assign_windows(right, resolve_side(right_time, right, "right"), window, rinst)
    conds = [
        la["_pw_window_start"] == ra["_pw_window_start"],
        la["_pw_window_end"] == ra["_pw_window_end"],
        la["_pw_instance"] == ra["_pw_instance"],
    ]
    for lexpr, rexpr in on_pairs:
        conds.append(
            _retarget(lexpr, left, la) == _retarget(rexpr, right, ra)
        )
    return WindowJoinResult(left, right, la, ra, conds, how)


def window_join_inner(left, right, lt, rt, window, *on, **kw):
    return window_join(left, right, lt, rt, window, *on, how="inner", **kw)


def window_join_left(left, right, lt, rt, window, *on, **kw):
    return window_join(left, right, lt, rt, window, *on, how="left", **kw)


def window_join_right(left, right, lt, rt, window, *on, **kw):
    return window_join(left, right, lt, rt, window, *on, how="right", **kw)


def window_join_outer(left, right, lt, rt, window, *on, **kw):
    return window_join(left, right, lt, rt, window, *on, how="outer", **kw)


# -- intervals_over -----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class IntervalsOverWindow:
    """Windows anchored at each value of ``at`` (a column, possibly of
    another table): [t + lower_bound, t + upper_bound]
    (reference _window.py:771 intervals_over)."""

    at: Any
    lower_bound: Any
    upper_bound: Any
    is_outer: bool = True


def intervals_over(
    *, at: Any, lower_bound: Any, upper_bound: Any, is_outer: bool = True
) -> IntervalsOverWindow:
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)
