"""Standard library: indexing, temporal, ml, graphs, stateful, utils.

Mirrors the capability surface of the reference's ``pathway.stdlib``
(reference: python/pathway/stdlib/) with TPU-native internals.
"""

from pathway_tpu.stdlib import graphs, indexing, temporal  # noqa: F401

__all__ = ["graphs", "indexing", "temporal"]
