"""Standard library: indexing, temporal, ml, graphs, stateful, utils.

Mirrors the capability surface of the reference's ``pathway.stdlib``
(reference: python/pathway/stdlib/) with TPU-native internals.
"""

from pathway_tpu.stdlib import (  # noqa: F401
    graphs,
    indexing,
    ml,
    ordered,
    stateful,
    statistical,
    temporal,
    utils,
    viz,
)

__all__ = [
    "graphs",
    "indexing",
    "ml",
    "ordered",
    "stateful",
    "statistical",
    "temporal",
    "utils",
    "viz",
]
