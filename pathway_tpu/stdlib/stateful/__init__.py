"""Stateful helpers (reference: stdlib/stateful/deduplicate.py)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.table import Table


def deduplicate(
    table: Table,
    *,
    value: Any,
    instance: Any = None,
    acceptor: Callable[[Any, Any], bool],
    name: str | None = None,
) -> Table:
    """Keep one accepted row per instance (reference:
    pw.stateful.deduplicate — engine DeduplicateNode)."""
    return table.deduplicate(
        value=value, instance=instance, acceptor=acceptor, name=name
    )
