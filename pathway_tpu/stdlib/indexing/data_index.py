"""DataIndex: the retrieval API over as-of-now external indexes.

Reference surface: stdlib/indexing/data_index.py:278 (DataIndex with
``query_as_of_now``), nearest_neighbors.py:65,170 (USearchKnn /
BruteForceKnn factories). Both vector factories here map onto the same
TPU HBM brute-force engine — on TPU the "approximate vs exact" split
disappears because exact masked-matmul search at MiniLM/BGE scales is
faster than CPU HNSW graph walks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    apply as pw_apply,
    make_tuple,
)
from pathway_tpu.internals.reducers import sorted_tuple
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import solver


class InnerIndexFactory:
    """Builds an engine-side ExternalIndex instance per graph build."""

    def build(self) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class TpuKnnFactory(InnerIndexFactory):
    """KNN in TPU HBM (ops/knn.py). ``dimensions`` is the embedding width."""

    dimensions: int
    metric: str = "cos"
    capacity: int = 1024
    mesh: Any = None

    def build(self) -> Any:
        from pathway_tpu.engine.external_index import DeviceKnnIndex

        return DeviceKnnIndex(
            dim=self.dimensions,
            metric=self.metric,
            capacity=self.capacity,
            mesh=self.mesh,
        )


class BruteForceKnnFactory(TpuKnnFactory):
    """Reference-compatible name (nearest_neighbors.py:170); same engine."""


class HostKnnFactory(TpuKnnFactory):
    """CPU/NumPy twin of :class:`TpuKnnFactory` — builds the
    :class:`~pathway_tpu.engine.external_index.HostKnnIndex` bit-exact
    host spec.  Used by the parity corpus and as the accelerator-free
    fallback for the streaming-RAG bench when the device probe fails."""

    def build(self) -> Any:
        from pathway_tpu.engine.external_index import HostKnnIndex

        return HostKnnIndex(
            dim=self.dimensions,
            metric=self.metric,
            capacity=self.capacity,
        )


class DataIndex:
    """An index over ``data_table`` with retrieval as engine dataflow.

    ``data_column`` holds the indexable payload (embedding vector for KNN,
    text for BM25). Query results arrive as new columns on the query table.
    """

    def __init__(
        self,
        data_table: Table,
        inner_index_factory: InnerIndexFactory,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
    ) -> None:
        self.data_table = data_table
        self.factory = inner_index_factory
        self.data_column = data_column
        self.metadata_column = metadata_column

    def query_as_of_now(
        self,
        query_table: Table,
        query_column: ColumnReference,
        number_of_matches: int | ColumnExpression = 3,
        collapse_rows: bool = True,
        with_scores: bool = True,
    ) -> Table:
        """Retrieve for each query row; answers are as-of-arrival.

        Returns (collapse_rows=True) a table keyed by query id with the query
        columns plus ``_pw_index_reply_ids`` (tuple of data-row Pointers) and
        ``_pw_index_reply_scores``. With collapse_rows=False, one output row
        per (query, hit) with ``_pw_index_reply_id`` / ``_pw_index_reply_score``
        columns (row id derives from the query id and rank).
        """
        reply = self.data_table._external_index_as_of_now(
            query_table,
            index_column=self.data_column,
            query_column=query_column,
            index_factory=self.factory.build,
            number_of_matches=number_of_matches,
        )
        if collapse_rows:
            combined = {
                name: query_table[name] for name in query_table.column_names()
            }
            combined["_pw_index_reply_ids"] = reply["_pw_index_reply_ids"]
            combined["_pw_index_reply_scores"] = reply["_pw_index_reply_scores"]
            return query_table.restrict(reply).select(**combined)
        # one row per hit: explode (rank, id, score) triples (zero-hit
        # queries keep a sentinel row so they stay in downstream universes)
        return explode_reply(reply)

    def query_docs_as_of_now(
        self,
        query_table: Table,
        query_column: ColumnReference,
        doc_columns: list[str],
        number_of_matches: int | ColumnExpression = 3,
    ) -> Table:
        """Collapse-with-documents: query columns + per-doc-column tuples
        ordered by rank + a scores tuple (the shape RAG pipelines consume)."""
        flat = self.query_as_of_now(
            query_table,
            query_column,
            number_of_matches=number_of_matches,
            collapse_rows=False,
        )
        return fetch_docs_for_hits(
            self.data_table, query_table, flat, doc_columns
        )


def fetch_docs_for_hits(
    data_table: Table,
    query_table: Table,
    flat_hits: Table,
    doc_columns: list[str],
) -> Table:
    """Shared collapse tail: one-row-per-hit table (``_pw_query_id`` /
    ``_pw_index_reply_rank`` / ``_pw_index_reply_id`` / ``_pw_index_reply_score``)
    -> per-query doc-column tuples ordered by rank + scores tuple."""
    # optional=True: zero-hit sentinel rows carry a None doc id
    docs_at = data_table.ix(flat_hits["_pw_index_reply_id"], optional=True)
    fetched = flat_hits.select(
        _pw_query_id=flat_hits["_pw_query_id"],
        _pw_index_reply_rank=flat_hits["_pw_index_reply_rank"],
        _pw_index_reply_score=flat_hits["_pw_index_reply_score"],
        **{name: docs_at[name] for name in doc_columns},
    )

    def strip_ranks(pairs: tuple) -> tuple:
        # rank -1 marks the zero-hit sentinel; it contributes no values
        return tuple(v for rank, v in pairs if rank >= 0)

    grouped = fetched.groupby(id=fetched["_pw_query_id"])
    agg = {
        name: pw_apply(
            strip_ranks,
            sorted_tuple(
                make_tuple(fetched["_pw_index_reply_rank"], fetched[name])
            ),
        )
        for name in doc_columns
    }
    agg["_pw_index_reply_scores"] = pw_apply(
        strip_ranks,
        sorted_tuple(
            make_tuple(
                fetched["_pw_index_reply_rank"],
                fetched["_pw_index_reply_score"],
            )
        ),
    )
    result = grouped.reduce(**agg)
    # group keys ARE query ids (groupby id=_pw_query_id), so the result
    # universe is a subset of the query table's — teach the solver so
    # callers can select query columns next to the reply columns
    solver.register_subset(result._universe, query_table._universe)
    return result


def explode_reply(reply: Table) -> Table:
    """ids/scores tuples -> one row per hit (rank, id, score), with a
    sentinel row for zero-hit queries (mirrors query_as_of_now's
    collapse_rows=False shape)."""

    def hit_triples(ids: tuple, scores: tuple) -> tuple:
        if not ids:
            return ((-1, None, None),)
        return tuple((i, k, s) for i, (k, s) in enumerate(zip(ids, scores)))

    pairs = reply.select(
        _pw_hits=pw_apply(
            hit_triples,
            reply["_pw_index_reply_ids"],
            reply["_pw_index_reply_scores"],
        ),
        _pw_query_id=reply.id,
    )
    flat = pairs.flatten(pairs["_pw_hits"])
    return flat.select(
        _pw_query_id=flat["_pw_query_id"],
        _pw_index_reply_rank=flat["_pw_hits"].get(0),
        _pw_index_reply_id=flat["_pw_hits"].get(1),
        _pw_index_reply_score=flat["_pw_hits"].get(2),
    )
