"""Inner-index factories with the dual query contract.

Reference: stdlib/indexing/nearest_neighbors.py — ``query`` (fully
incremental: index changes retract + update old answers; only LshKnn
implements it, :262) vs ``query_as_of_now`` (answers frozen at arrival;
USearch/BruteForce route through the engine as-of-now operator, :65/:170).
Here the as-of-now path runs on the TPU HBM index (ops/knn.py); the
incremental path is the pure-dataflow LSH pipeline
(stdlib/ml/classifiers.py), which keeps revising answers because it is
made of ordinary joins and groupbys.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from pathway_tpu.internals.expression import ColumnReference, apply as pw_apply
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import (
    BruteForceKnnFactory,
    DataIndex,
    InnerIndexFactory,
    TpuKnnFactory,
)

USearchKnnFactory = BruteForceKnnFactory  # same HBM engine on TPU


@dataclasses.dataclass
class LshKnnFactory(InnerIndexFactory):
    """Pure-dataflow LSH KNN supporting the incremental ``query`` contract
    (reference LshKnn nearest_neighbors.py:262)."""

    dimensions: int
    L: int = 8
    M: int = 8
    A: float = 1.0
    metric: str = "euclidean"  # or "cosine"

    def build(self) -> Any:  # as-of-now engine path is not provided
        raise NotImplementedError(
            "LshKnn implements the incremental `query` contract; use "
            "DataIndex.query(...) (reference: USearchKnn.query raises the "
            "mirror error for query_as_of_now-only indexes)"
        )


def data_index_query(
    index: DataIndex,
    query_table: Table,
    query_column: ColumnReference,
    number_of_matches: int = 3,
    metadata_filter_column: ColumnReference | None = None,
) -> Table:
    """Incremental retrieval: the result table updates when the *data*
    changes, not only when queries arrive (SURVEY Appendix B `query`)."""
    factory = index.factory
    if not isinstance(factory, LshKnnFactory):
        raise NotImplementedError(
            "incremental query needs an LshKnnFactory index; as-of-now "
            "indexes never revise answers (reference "
            "nearest_neighbors.py:113-122)"
        )
    from pathway_tpu.stdlib.ml.classifiers import knn_lsh_classifier_train

    data = index.data_table.select(
        data=index.data_column,
        **(
            {"metadata": index.metadata_column}
            if index.metadata_column is not None
            else {}
        ),
    )
    model = knn_lsh_classifier_train(
        data,
        L=factory.L,
        type=factory.metric,
        d=factory.dimensions,
        M=factory.M,
        A=factory.A,
    )
    qsel = {"data": query_column}
    if metadata_filter_column is not None:
        qsel["metadata_filter"] = metadata_filter_column
    queries = query_table.select(
        **qsel, k=pw_apply(lambda _d: number_of_matches, query_column)
    )
    result = model(queries, with_distances=True)
    return result.select(
        _pw_index_reply_ids=pw_apply(
            lambda pairs: tuple(p for p, _d in pairs),
            result["knns_ids_with_dists"],
        ),
        _pw_index_reply_scores=pw_apply(
            # scores are negated distances: higher is better, like the
            # engine index replies
            lambda pairs: tuple(-d for _p, d in pairs),
            result["knns_ids_with_dists"],
        ),
    )


# surface the incremental contract as a DataIndex method
def _query(self, query_table, query_column, number_of_matches=3, metadata_filter_column=None):
    return data_index_query(
        self, query_table, query_column, number_of_matches, metadata_filter_column
    )


DataIndex.query = _query
