"""BM25 full-text index (host-side inverted index).

Reference: stdlib/indexing/bm25.py:41 TantivyBM25 over the tantivy crate
(src/external_integration/tantivy_integration.rs). Text scoring is
branch-heavy integer work — the wrong shape for the MXU — so unlike the
vector path this index stays on host: a Python inverted index with Okapi
BM25 scoring, same as-of-now operator contract (engine/external_index.py).
"""

from __future__ import annotations

import dataclasses
import math
import re
from collections import Counter, defaultdict
from typing import Any, Sequence

from pathway_tpu.engine.value import Pointer

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def _tokenize(text: str) -> list[str]:
    return _TOKEN_RE.findall(str(text).lower())


class BM25Index:
    """Okapi BM25 over an in-memory inverted index (ExternalIndex protocol)."""

    def __init__(self, k1: float = 1.2, b: float = 0.75) -> None:
        self.k1 = k1
        self.b = b
        self.postings: dict[str, dict[Pointer, int]] = defaultdict(dict)
        self.doc_tokens: dict[Pointer, list[str]] = {}  # inverse of postings
        self.doc_len: dict[Pointer, int] = {}
        self.total_len = 0

    def add(self, keys: Sequence[Pointer], docs: Sequence[Any]) -> None:
        for key, doc in zip(keys, docs):
            if key in self.doc_len:
                self.remove([key])
            toks = _tokenize(doc)
            self.doc_len[key] = len(toks)
            self.total_len += len(toks)
            counts = Counter(toks)
            self.doc_tokens[key] = list(counts)
            for tok, cnt in counts.items():
                self.postings[tok][key] = cnt

    def remove(self, keys: Sequence[Pointer]) -> None:
        for key in keys:
            length = self.doc_len.pop(key, None)
            if length is None:
                continue
            self.total_len -= length
            for tok in self.doc_tokens.pop(key, ()):
                tok_docs = self.postings.get(tok)
                if tok_docs is not None:
                    tok_docs.pop(key, None)
                    if not tok_docs:
                        del self.postings[tok]

    def op_state(self) -> dict:
        return {
            "postings": {t: dict(d) for t, d in self.postings.items()},
            "doc_tokens": dict(self.doc_tokens),
            "doc_len": dict(self.doc_len),
            "total_len": self.total_len,
        }

    def restore_op_state(self, state: dict) -> None:
        self.postings = defaultdict(dict)
        for t, d in state["postings"].items():
            self.postings[t] = dict(d)
        self.doc_tokens = dict(state["doc_tokens"])
        self.doc_len = dict(state["doc_len"])
        self.total_len = state["total_len"]

    def search(
        self, queries: Sequence[Any], k: int
    ) -> list[list[tuple[Pointer, float]]]:
        n_docs = len(self.doc_len)
        avg_len = (self.total_len / n_docs) if n_docs else 0.0
        out: list[list[tuple[Pointer, float]]] = []
        for query in queries:
            scores: dict[Pointer, float] = defaultdict(float)
            for tok in set(_tokenize(query)):
                tok_docs = self.postings.get(tok)
                if not tok_docs:
                    continue
                df = len(tok_docs)
                idf = math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
                for key, tf in tok_docs.items():
                    dl = self.doc_len[key]
                    denom = tf + self.k1 * (
                        1 - self.b + self.b * dl / max(avg_len, 1e-9)
                    )
                    scores[key] += idf * tf * (self.k1 + 1) / denom
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], int(kv[0])))
            out.append([(key, float(s)) for key, s in ranked[:k]])
        return out


@dataclasses.dataclass
class TantivyBM25Factory:
    """Reference-compatible factory name (bm25.py:41)."""

    k1: float = 1.2
    b: float = 0.75

    def build(self) -> BM25Index:
        return BM25Index(k1=self.k1, b=self.b)
