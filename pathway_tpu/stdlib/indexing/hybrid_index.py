"""HybridIndex — reciprocal-rank fusion over several DataIndexes.

Reference: stdlib/indexing/hybrid_index.py:14 — each retriever's reply
contributes ``1/(k + rank)`` per hit; scores sum across retrievers and the
best ``number_of_matches`` ids win. Retrievers see the *same query table*
but each uses its own query column (text for BM25, vector for KNN).
"""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.internals.expression import ColumnReference, apply as pw_apply
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex


class HybridIndex:
    def __init__(self, retrievers: Sequence[DataIndex], k: float = 60):
        if len(retrievers) < 2:
            raise ValueError(
                "HybridIndex requires at least two indices to be provided "
                "during initialization"
            )
        self.retrievers = list(retrievers)
        self.k = k

    def query_as_of_now(
        self,
        query_table: Table,
        query_columns: Sequence[ColumnReference],
        number_of_matches: Any = 3,
        oversample: int = 3,
    ) -> Table:
        """-> query columns + fused ``_pw_index_reply_ids`` /
        ``_pw_index_reply_scores`` (RRF scores). Each retriever is asked for
        ``number_of_matches * oversample`` candidates so fusion has depth.
        ``number_of_matches`` may be an int or a per-query column."""
        if len(query_columns) != len(self.retrievers):
            raise ValueError("one query column per retriever")
        if isinstance(number_of_matches, int):
            fetch: Any = number_of_matches * oversample
            n_expr = pw_apply(
                lambda _q: number_of_matches, query_columns[0]
            )
        else:
            fetch = pw_apply(lambda kk: kk * oversample, number_of_matches)
            n_expr = number_of_matches
        replies = [
            r.query_as_of_now(
                query_table, qc, number_of_matches=fetch
            )
            for r, qc in zip(self.retrievers, query_columns)
        ]
        k = self.k

        def fuse(n: int, *id_tuples: tuple) -> tuple:
            scores: dict = {}
            for ids in id_tuples:
                for rank, key in enumerate(ids, start=1):
                    scores[key] = scores.get(key, 0.0) + 1.0 / (k + rank)
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], repr(kv[0])))
            top = ranked[: int(n)]
            return (
                tuple(key for key, _s in top),
                tuple(s for _key, s in top),
            )

        combined = {
            name: query_table[name] for name in query_table.column_names()
        }
        fused = query_table.select(
            **combined,
            _pw_fused=pw_apply(
                fuse,
                n_expr,
                *[r["_pw_index_reply_ids"] for r in replies],
            ),
        )
        return fused.select(
            **{name: fused[name] for name in query_table.column_names()},
            _pw_index_reply_ids=fused["_pw_fused"].get(0),
            _pw_index_reply_scores=fused["_pw_fused"].get(1),
        )
