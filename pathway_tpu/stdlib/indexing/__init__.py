"""Indexing: DataIndex over device-resident retrieval engines.

Mirrors the reference's ``pathway.stdlib.indexing``
(reference: stdlib/indexing/data_index.py:278 DataIndex;
nearest_neighbors.py BruteForceKnnFactory/USearchKnnFactory;
bm25.py TantivyBM25Factory) with the vector path running in TPU HBM
(engine/external_index.py over ops/knn.py). The ``query_as_of_now``
contract matches Appendix B of SURVEY.md: answers reflect index state at
query arrival and are revised only when the query row itself changes.
"""

from pathway_tpu.stdlib.indexing.data_index import (
    BruteForceKnnFactory,
    DataIndex,
    HostKnnFactory,
    InnerIndexFactory,
    TpuKnnFactory,
)
from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25Factory
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    LshKnnFactory,
    USearchKnnFactory,
)
from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex

__all__ = [
    "BruteForceKnnFactory",
    "HostKnnFactory",
    "HybridIndex",
    "LshKnnFactory",
    "USearchKnnFactory",
    "DataIndex",
    "InnerIndexFactory",
    "TantivyBM25Factory",
    "TpuKnnFactory",
]
