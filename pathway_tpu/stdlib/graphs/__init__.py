"""Graph algorithms over pw.iterate (reference: python/pathway/stdlib/graphs/
— pagerank/, bellman_ford/, louvain_communities/)."""

from __future__ import annotations

import math
from typing import Any

from pathway_tpu.internals.expression import (
    apply as pw_apply,
    coalesce,
    if_else,
)
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.table import Table
from pathway_tpu.internals import reducers

_INF = math.inf


def _vertices_of(edges: Table) -> Table:
    us = edges.groupby(edges.u).reduce(v=edges.u)
    vs = edges.groupby(edges.v).reduce(v=edges.v)
    return us.update_rows(vs)


def pagerank(
    edges: Table,
    iteration_limit: int = 50,
    damping: float = 0.85,
) -> Table:
    """PageRank over an edge table ``(u, v)`` — returns ``(v, rank)``
    (reference: stdlib/graphs/pagerank)."""
    vertices = _vertices_of(edges)
    out_deg = edges.groupby(edges.u).reduce(v=edges.u, deg=reducers.count())
    ranks0 = vertices.select(v=vertices.v, rank=1.0)

    def body(ranks: Table) -> dict:
        with_rank = edges.join(ranks, edges.u == ranks.v).select(
            u=edges.u, v=edges.v, rank=ranks.rank
        )
        shares = with_rank.join(
            out_deg, with_rank.u == out_deg.v
        ).select(v=with_rank.v, share=with_rank.rank / out_deg.deg)
        inflow = shares.groupby(shares.v).reduce(
            v=shares.v, total=reducers.sum(shares.share)
        )
        new_ranks = vertices.join_left(
            inflow, vertices.v == inflow.v, id=vertices.id
        ).select(
            v=vertices.v,
            rank=pw_apply(
                lambda t: round((1.0 - damping) + damping * (t or 0.0), 12),
                inflow.total,
            ),
        )
        return {"ranks": new_ranks}

    return iterate(body, iteration_limit=iteration_limit, ranks=ranks0).ranks


def bellman_ford(
    vertices: Table,
    edges: Table,
    iteration_limit: int | None = None,
) -> Table:
    """Single-source shortest paths: ``vertices(v, is_source)``,
    ``edges(u, v, dist)`` -> ``(v, dist_from_source)``
    (reference: stdlib/graphs/bellman_ford)."""
    dists0 = vertices.select(
        v=vertices.v,
        dist=if_else(vertices.is_source, 0.0, _INF),
    )

    def body(dists: Table) -> dict:
        relaxed = edges.join(dists, edges.u == dists.v).select(
            v=edges.v, cand=dists.dist + edges.dist
        )
        best = relaxed.groupby(relaxed.v).reduce(
            v=relaxed.v, cand=reducers.min(relaxed.cand)
        )
        new = dists.join_left(best, dists.v == best.v, id=dists.id).select(
            v=dists.v,
            dist=if_else(
                coalesce(best.cand, _INF) < dists.dist,
                coalesce(best.cand, _INF),
                dists.dist,
            ),
        )
        return {"dists": new}

    return iterate(body, iteration_limit=iteration_limit, dists=dists0).dists


def shortest_paths(edges: Table, source: Any, **kw: Any) -> Table:
    """Convenience wrapper: build the vertex table from edges + a source id."""
    vertices = _vertices_of(edges)
    vt = vertices.select(
        v=vertices.v, is_source=pw_apply(lambda x: x == source, vertices.v)
    )
    return bellman_ford(vt, edges, **kw)


def louvain_communities(
    edges: Table,
    *,
    resolution: float = 1.0,
    seed: int = 0,
) -> Table:
    """Community detection on a weighted edge table ``(u, v[, weight])``;
    returns ``(v, community: int)``.

    Reference: stdlib/graphs/louvain_communities/impl.py (modularity-
    maximizing level iteration in dataflow with randomized move order).
    Here the whole affected component is recomputed per commit through a
    deterministic (seeded) networkx Louvain — the same incremental-
    recompute strategy this engine uses for joins, applied at graph scope.
    """
    cols = edges.column_names()
    has_weight = "weight" in cols
    triples = edges.select(
        _pw_e=pw_apply(
            lambda u, v, w=None: (u, v, float(w) if w is not None else 1.0),
            edges.u,
            edges.v,
            *((edges.weight,) if has_weight else ()),
        )
    )
    packed = triples.groupby().reduce(
        _pw_edges=reducers.sorted_tuple(triples["_pw_e"])
    )

    def communities(edge_tuples: tuple) -> tuple:
        import networkx as nx

        g = nx.Graph()
        for u, v, w in edge_tuples:
            g.add_edge(u, v, weight=w)
        partitions = nx.community.louvain_communities(
            g, resolution=resolution, seed=seed
        )
        out = []
        for i, part in enumerate(partitions):
            for node in part:
                out.append((node, i))
        return tuple(sorted(out, key=lambda nc: repr(nc[0])))

    assigned = packed.select(
        _pw_assign=pw_apply(communities, packed["_pw_edges"])
    )
    flat = assigned.flatten(assigned["_pw_assign"])
    return flat.select(
        v=flat["_pw_assign"].get(0),
        community=flat["_pw_assign"].get(1),
    )
