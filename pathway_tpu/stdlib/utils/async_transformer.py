"""AsyncTransformer — fully-async row→row transformation of a table.

Reference: python/pathway/stdlib/utils/async_transformer.py:61-267 — the
input table is subscribed, every insertion schedules ``invoke`` on a
dedicated asyncio loop, and completions loop back into the graph through a
Python-connector source as an upsert stream keyed by the input row id (so
late results revise, deletions retract, and nondeterministic outputs stay
consistent).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any, ClassVar

from pathway_tpu.engine.connectors import (
    UPSERT,
    ParsedEvent,
    Parser,
    QueueReader,
)
from pathway_tpu.engine.value import Json, Pointer
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.udfs.retries import AsyncRetryStrategy
from pathway_tpu.io._utils import input_table

_STATUS_COLUMN = "_async_status"
SUCCESS = "-SUCCESS-"
FAILURE = "-FAILURE-"


class _ResultParser(Parser):
    session_type = "upsert"

    def __init__(self, column_names, dtypes) -> None:
        super().__init__(column_names)
        self.dtypes = dtypes

    def parse(self, payload: Any) -> list[ParsedEvent]:
        kind, key, fields = payload
        if kind == "remove":
            return [ParsedEvent(UPSERT, None, key=(key,))]
        values = []
        for name in self.column_names:
            v = fields.get(name)
            if isinstance(v, (dict, list)):
                v = Json(v)
            values.append(v)
        return [ParsedEvent(UPSERT, tuple(values), key=(key,))]


class AsyncTransformer:
    """Subclass with ``output_schema=...`` and an async ``invoke(**cols)``
    returning a dict matching the schema; read ``.successful`` (alias
    ``.result``), ``.failed``, or ``.finished`` (all rows + status)."""

    output_schema: ClassVar[type]

    def __init_subclass__(cls, /, output_schema: type | None = None, **kwargs):
        super().__init_subclass__(**kwargs)
        if output_schema is not None:
            cls.output_schema = output_schema

    def __init__(
        self,
        input_table: Table,
        *,
        autocommit_duration_ms: int | None = 1500,
        instance: Any = None,
    ) -> None:
        if getattr(self, "output_schema", None) is None:
            raise TypeError(
                "define the subclass with "
                "`class T(AsyncTransformer, output_schema=Schema)`"
            )
        sig = inspect.signature(self.invoke)
        try:
            sig.bind(**{c: None for c in input_table.column_names()})
        except TypeError as e:
            raise TypeError(
                f"invoke() signature does not match the input table columns "
                f"({', '.join(input_table.column_names())}): {e}"
            ) from e

        self._input_table = input_table
        self._column_names = list(input_table.column_names())
        self._reader = QueueReader()
        self._capacity: int | None = None
        self._timeout: float | None = None
        self._retry_strategy: AsyncRetryStrategy | None = None
        self._pending = 0
        self._input_done = False
        self._lock = threading.Lock()
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )
        self._loop_started = False
        self._tasks: dict[Pointer, Any] = {}
        # per-key generation: a removal (or newer insertion) bumps it, so a
        # stale in-flight invoke can never resurrect a deleted/replaced row
        self._gen: dict[Pointer, int] = {}

        from pathway_tpu.io import subscribe

        subscribe(input_table, on_change=self._on_change, _internal=True)

        out_dtypes = dict(self.output_schema.dtypes())
        out_dtypes[_STATUS_COLUMN] = dt.STR
        result_schema = schema_mod.schema_from_types(
            **{n: Any for n in out_dtypes}
        )
        self._finished = input_table_from_reader(
            self._reader,
            result_schema,
            list(out_dtypes),
            self._on_end,
            input_table,
        )

    # -- configuration --------------------------------------------------------

    def with_options(
        self,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: Any = None,
    ) -> "AsyncTransformer":
        self._capacity = capacity
        self._timeout = timeout
        self._retry_strategy = retry_strategy
        return self

    # -- lifecycle hooks (reference :371-383) ---------------------------------

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    async def invoke(self, *args: Any, **kwargs: Any) -> dict:
        raise NotImplementedError

    # -- plumbing -------------------------------------------------------------

    def _ensure_loop(self) -> None:
        if not self._loop_started:
            self._loop_started = True
            self.open()
            self._loop_thread.start()

    def _on_change(self, key: Pointer, row: dict, time: int, is_addition: bool):
        self._ensure_loop()
        with self._lock:
            gen = self._gen.get(key, 0) + 1
            self._gen[key] = gen
        if not is_addition:
            task = self._tasks.pop(key, None)
            if task is not None:
                self._loop.call_soon_threadsafe(task.cancel)
            self._reader.push(("remove", key, None))
            return
        with self._lock:
            self._pending += 1

        async def run() -> None:
            try:
                async def call():
                    coro = self.invoke(**row)
                    if self._timeout is not None:
                        return await asyncio.wait_for(coro, self._timeout)
                    return await coro

                if self._retry_strategy is not None:
                    result = await self._retry_strategy.invoke(call)
                else:
                    result = await call()
                if not isinstance(result, dict):
                    raise TypeError(
                        f"invoke() must return a dict, got {type(result).__name__}"
                    )
                payload = {**result, _STATUS_COLUMN: SUCCESS}
            except asyncio.CancelledError:
                with self._lock:
                    self._pending -= 1
                    self._maybe_finish()
                raise
            except Exception as e:  # noqa: BLE001 — failure rows carry status
                payload = {
                    **{c: None for c in self.output_schema.column_names()},
                    _STATUS_COLUMN: f"{FAILURE}{e!r}",
                }
            with self._lock:
                if self._gen.get(key) == gen:
                    # only the latest generation may publish: a removal or
                    # replacement that raced this invoke wins
                    self._reader.push(("upsert", key, payload))
                if self._tasks.get(key) is asyncio.current_task():
                    self._tasks.pop(key, None)  # release finished task
                self._pending -= 1
                self._maybe_finish()

        def schedule() -> None:
            self._tasks[key] = self._loop.create_task(run())

        self._loop.call_soon_threadsafe(schedule)

    def _on_end(self) -> None:
        self._ensure_loop()
        with self._lock:
            self._input_done = True
            self._maybe_finish()

    def _maybe_finish(self) -> None:
        if self._input_done and self._pending == 0:
            self._reader.close()
            try:
                self.close()
            except Exception:  # noqa: BLE001
                pass

    # -- results --------------------------------------------------------------

    @property
    def finished(self) -> Table:
        """All invoked rows, with the raw ``_async_status`` column."""
        return self._finished

    @property
    def successful(self) -> Table:
        """Rows whose invoke() completed, in the output schema."""
        t = self._finished
        ok = t.filter(t[_STATUS_COLUMN] == SUCCESS)
        return ok[list(self.output_schema.column_names())]

    @property
    def failed(self) -> Table:
        t = self._finished
        return t.filter(t[_STATUS_COLUMN] != SUCCESS)

    @property
    def result(self) -> Table:
        return self.successful


def input_table_from_reader(
    reader, schema, column_names, upstream_done, upstream_table
) -> Table:
    dtypes = schema.dtypes()

    def make_reader():
        return reader

    def make_parser(_names):
        return _ResultParser(column_names, dtypes)

    return input_table(
        schema,
        make_reader,
        make_parser,
        source_name="async-transformer",
        upstream_done=upstream_done,
        upstream_table=upstream_table,
    )
