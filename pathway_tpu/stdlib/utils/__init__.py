"""Utility transforms (reference: stdlib/utils/ — pandas_transformer, col,
async_transformer, filtering)."""

from __future__ import annotations

from typing import Any, Callable, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table, TableSpec
from pathway_tpu.internals.expression import apply as pw_apply


def pandas_transformer(
    output_schema: Any = None,
) -> Callable:
    """Whole-table pandas UDF (reference: stdlib/utils/pandas_transformer).

    Decorates ``fn(df: pandas.DataFrame) -> pandas.DataFrame``; the result
    table is re-keyed by the output frame's positional index per recompute.
    """

    def wrap(fn: Callable) -> Callable[[Table], Table]:
        def apply_to(table: Table) -> Table:
            import pandas as pd

            from pathway_tpu.engine.value import hash_values

            cols = table.column_names()

            def transform(state: dict) -> dict:
                keys = list(state)
                df = pd.DataFrame(
                    [state[k] for k in keys], columns=cols,
                    index=[int(k) for k in keys],
                )
                out = fn(df)
                result = {}
                for i, (_idx, row) in enumerate(out.iterrows()):
                    key = hash_values((fn.__name__, i), salt=b"pandas")
                    result[key] = tuple(row[c] for c in out.columns)
                return result

            if output_schema is not None:
                out_types = dict(output_schema.dtypes())
            else:
                out_types = {n: dt.ANY for n in cols}
            return table._derived(
                TableSpec("table_transform", [table], {"fn": transform}),
                out_types,
            )

        return apply_to

    return wrap


def unpack_col(column: Any, *names: str) -> Table:
    """Explode a tuple column into named columns
    (reference: stdlib/utils/col.py unpack_col)."""
    table = column.table
    return table.select(
        **{
            name: pw_apply(lambda t, i=i: t[i] if t is not None else None, column)
            for i, name in enumerate(names)
        }
    )


def argmax_rows(table: Table, *on: Any, what: Any) -> Table:
    """Rows holding the per-group maximum of ``what``
    (reference: stdlib/utils/filtering.py argmax_rows)."""
    from pathway_tpu.internals import reducers
    from pathway_tpu.internals.desugaring import resolve_this

    what_ref = resolve_this(what, table)
    grouped = table.groupby(*[resolve_this(o, table) for o in on])
    best = grouped.reduce(_pw_best=reducers.argmax(what_ref))
    return table.ix(best["_pw_best"])


def argmin_rows(table: Table, *on: Any, what: Any) -> Table:
    from pathway_tpu.internals import reducers
    from pathway_tpu.internals.desugaring import resolve_this

    what_ref = resolve_this(what, table)
    grouped = table.groupby(*[resolve_this(o, table) for o in on])
    best = grouped.reduce(_pw_best=reducers.argmin(what_ref))
    return table.ix(best["_pw_best"])


from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer  # noqa: E402
