"""Ordered-table helpers (reference: stdlib/ordered/diff.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table, TableSpec


def diff(
    table: Table, timestamp: Any, *value_columns: Any, instance: Any = None
) -> Table:
    """Per-row difference vs the previous row in ``timestamp`` order
    (reference: pw.ordered.diff — built on prev/next pointers). Output has
    ``diff_<col>`` columns; the first row per instance gets None."""
    from pathway_tpu.internals.desugaring import resolve_this

    cols = table.column_names()
    t_idx = cols.index(resolve_this(timestamp, table).name)
    names = [resolve_this(v, table).name for v in value_columns]
    v_idx = [cols.index(n) for n in names]
    i_idx = (
        cols.index(resolve_this(instance, table).name)
        if instance is not None
        else None
    )

    def transform(state: dict) -> dict:
        groups: dict[Any, list] = {}
        for key, row in state.items():
            inst = row[i_idx] if i_idx is not None else None
            groups.setdefault(inst, []).append((key, row))
        out = {}
        for rows in groups.values():
            rows.sort(key=lambda kv: (kv[1][t_idx], int(kv[0])))
            prev = None
            for key, row in rows:
                diffs = tuple(
                    (row[vi] - prev[vi]) if prev is not None else None
                    for vi in v_idx
                )
                out[key] = tuple(row) + diffs
                prev = row
        return out

    dtypes = dict(table._dtypes)
    out_types = {n: dtypes[n] for n in cols}
    for n in names:
        out_types[f"diff_{n}"] = dt.ANY
    return table._derived(
        TableSpec("table_transform", [table], {"fn": transform}),
        out_types,
        universe=table._universe,
    )
