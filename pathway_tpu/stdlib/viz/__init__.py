"""Live table visualization (reference: stdlib/viz — plotting.py /
table_viz.py render streaming tables as live Bokeh/Panel dashboards in
notebooks).

This environment has no notebook stack, so the native surface is a rich
live console table that re-renders as commits land (the same mechanism as
the monitoring dashboard); ``plot`` keeps the reference signature and uses
Bokeh when importable.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.table import Table


class _LiveTableViz:
    def __init__(self, table: Table, title: str, console: Any, max_rows: int):
        from pathway_tpu.internals.viz_model import RowSnapshot

        # shared snapshot model with the notebook LiveTable
        # (internals/interactive.py): one owner for add/retract semantics
        self._snapshot = RowSnapshot(table.column_names(), max_rows)
        self.title = title
        self._live = None
        self._console = console

    @property
    def rows(self) -> dict:
        return self._snapshot.rows

    def _render(self):
        from rich.table import Table as RichTable

        rt = RichTable(title=self.title)
        for name in self._snapshot.column_names:
            rt.add_column(name)
        for row in self._snapshot.visible():
            rt.add_row(*[str(v) for v in row])
        if self._snapshot.overflow:
            rt.caption = f"... {self._snapshot.overflow} more rows"
        return rt

    def on_change(self, key, row, time, is_addition):
        self._snapshot.apply(key, row, is_addition)

    def on_time_end(self, time):
        if self._live is None:
            from rich.live import Live

            self._live = Live(self._render(), console=self._console)
            self._live.start()
        self._live.update(self._render())

    def on_end(self):
        if self._live is not None:
            self._live.update(self._render())
            self._live.stop()


def table_viz(
    table: Table,
    *,
    title: str = "pathway table",
    console: Any = None,
    max_rows: int = 20,
) -> None:
    """Subscribe a live console rendering of ``table`` to the run
    (reference table_viz.py; renders per commit)."""
    viz = _LiveTableViz(table, title, console, max_rows)

    from pathway_tpu.engine.value import Pointer
    from pathway_tpu.internals.parse_graph import G

    column_names = table.column_names()

    def attach(scope, node):
        def on_change(key: Pointer, values: tuple, time: int, diff: int):
            viz.on_change(
                key, dict(zip(column_names, values)), time, diff > 0
            )

        scope.subscribe_table(
            node,
            on_change=on_change,
            on_time_end=viz.on_time_end,
            on_end=viz.on_end,
        )
        return None

    G.add_sink(table, attach)


def plot(
    table: Table,
    plotting_function: Callable,
    *,
    sorting_col: Any = None,
) -> Any:
    """Live Bokeh plot of a streaming table (reference plotting.py:plot).
    Needs bokeh, which this image does not ship."""
    try:
        import bokeh  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "pw.stdlib.viz.plot needs bokeh; use table_viz for the console "
            "rendering, or install bokeh for notebook dashboards"
        ) from e
    raise NotImplementedError(
        "bokeh plotting requires a notebook event loop; use table_viz here"
    )


def show(table: Table, **kwargs: Any) -> None:
    """Reference ``Table.show()`` (interactive.py): live view of the table."""
    table_viz(table, **kwargs)


Table.show = show  # reference surface: t.show()
