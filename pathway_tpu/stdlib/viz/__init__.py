"""Live table visualization (reference: stdlib/viz — plotting.py /
table_viz.py render streaming tables as live Bokeh/Panel dashboards in
notebooks).

This environment has no notebook stack, so the native surface is a rich
live console table that re-renders as commits land (the same mechanism as
the monitoring dashboard); ``plot`` keeps the reference signature and uses
Bokeh when importable.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.table import Table


class _LiveTableViz:
    def __init__(self, table: Table, title: str, console: Any, max_rows: int):
        from pathway_tpu.internals.viz_model import RowSnapshot

        # shared snapshot model with the notebook LiveTable
        # (internals/interactive.py): one owner for add/retract semantics
        self._snapshot = RowSnapshot(table.column_names(), max_rows)
        self.title = title
        self._live = None
        self._console = console

    @property
    def rows(self) -> dict:
        return self._snapshot.rows

    def _render(self):
        from rich.table import Table as RichTable

        rt = RichTable(title=self.title)
        for name in self._snapshot.column_names:
            rt.add_column(name)
        for row in self._snapshot.visible():
            rt.add_row(*[str(v) for v in row])
        if self._snapshot.overflow:
            rt.caption = f"... {self._snapshot.overflow} more rows"
        return rt

    def on_change(self, key, row, time, is_addition):
        self._snapshot.apply(key, row, is_addition)

    def on_time_end(self, time):
        if self._live is None:
            from rich.live import Live

            self._live = Live(self._render(), console=self._console)
            self._live.start()
        self._live.update(self._render())

    def on_end(self):
        if self._live is not None:
            self._live.update(self._render())
            self._live.stop()


def table_viz(
    table: Table,
    *,
    title: str = "pathway table",
    console: Any = None,
    max_rows: int = 20,
) -> None:
    """Subscribe a live console rendering of ``table`` to the run
    (reference table_viz.py; renders per commit)."""
    viz = _LiveTableViz(table, title, console, max_rows)

    from pathway_tpu.engine.value import Pointer
    from pathway_tpu.internals.parse_graph import G

    column_names = table.column_names()

    def attach(scope, node):
        def on_change(key: Pointer, values: tuple, time: int, diff: int):
            viz.on_change(
                key, dict(zip(column_names, values)), time, diff > 0
            )

        scope.subscribe_table(
            node,
            on_change=on_change,
            on_time_end=viz.on_time_end,
            on_end=viz.on_end,
        )
        return None

    G.add_sink(table, attach)


class LiveDashboard:
    """Live streaming web dashboard — the TPU-repo equivalent of the
    reference's Bokeh/Panel notebook dashboards (stdlib/viz/plotting.py):
    no notebook stack ships in this image, so the dashboard is a
    dependency-free web page served by the framework itself. Subscribed
    tables stream into row snapshots; the page polls ``/data`` and
    re-renders tables plus an SVG row-count sparkline per table.

    Usage::

        dash = pw.stdlib.viz.LiveDashboard(port=8099)
        dash.add(my_table, title="events")
        ...
        pw.run()   # dashboard live at http://127.0.0.1:8099/
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8099,
        max_rows: int = 50,
        history: int = 600,
    ) -> None:
        self.host = host
        self.port = port
        self.max_rows = max_rows
        self.history = history
        self._tables: dict[str, dict] = {}
        self._server = None
        self._started = False
        import threading

        self._lock = threading.Lock()

    def add(self, table: Table, title: str | None = None) -> None:
        from pathway_tpu.internals.parse_graph import G
        from pathway_tpu.internals.viz_model import RowSnapshot

        name = title or f"table_{len(self._tables)}"
        column_names = table.column_names()
        snap = RowSnapshot(column_names, self.max_rows)
        entry = {"snapshot": snap, "counts": [], "commits": 0}
        self._tables[name] = entry

        def attach(scope, node):
            def on_change(key, values, time, diff):
                with self._lock:
                    snap.apply(
                        key, dict(zip(column_names, values)), diff > 0
                    )

            def on_time_end(time):
                with self._lock:
                    entry["commits"] += 1
                    entry["counts"].append(len(snap.rows))
                    del entry["counts"][: -self.history]
                self._ensure_server()

            scope.subscribe_table(
                node, on_change=on_change, on_time_end=on_time_end
            )
            return None

        G.add_sink(table, attach)

    # -- serving ------------------------------------------------------------

    def snapshot_json(self) -> dict:
        with self._lock:
            out = {}
            for name, entry in self._tables.items():
                snap = entry["snapshot"]
                out[name] = {
                    "columns": list(snap.column_names),
                    "rows": [
                        [str(v) for v in row] for row in snap.visible()
                    ],
                    "n_rows": len(snap.rows),
                    "overflow": snap.overflow,
                    "commits": entry["commits"],
                    "count_history": list(entry["counts"]),
                }
            return out

    _PAGE = """<!doctype html><html><head><title>pathway dashboard</title>
<style>
body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa}
h2{margin:.8rem 0 .3rem}
table{border-collapse:collapse;background:#fff;box-shadow:0 1px 3px #0002}
td,th{border:1px solid #ddd;padding:.25rem .6rem;font-size:.85rem}
th{background:#f0f0f0}.meta{color:#666;font-size:.8rem}
svg{background:#fff;box-shadow:0 1px 3px #0002;margin:.3rem 0}
</style></head><body><h1>pathway live dashboard</h1>
<div id="root"></div><script>
function esc(s){return String(s).replace(/[&<>"']/g,
 c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
function spark(h){if(!h.length)return "";const W=420,H=60,m=Math.max(...h,1);
const pts=h.map((v,i)=>`${(i/(Math.max(h.length-1,1)))*W},${H-(v/m)*(H-6)-3}`).join(" ");
return `<svg width="${W}" height="${H}"><polyline fill="none" stroke="#2a6" stroke-width="2" points="${pts}"/></svg>`}
async function tick(){try{
const d=await (await fetch('data')).json();let html='';
for(const [name,t] of Object.entries(d)){
html+=`<h2>${esc(name)}</h2><div class="meta">${t.n_rows} rows · ${t.commits} commits</div>`;
html+=spark(t.count_history);
html+='<table><tr>'+t.columns.map(c=>`<th>${esc(c)}</th>`).join('')+'</tr>';
for(const r of t.rows){html+='<tr>'+r.map(v=>`<td>${esc(v)}</td>`).join('')+'</tr>'}
html+='</table>';if(t.overflow){html+=`<div class="meta">… ${t.overflow} more rows</div>`}}
document.getElementById('root').innerHTML=html}catch(e){}}
setInterval(tick,500);tick();
</script></body></html>"""

    def _ensure_server(self) -> None:
        if self._started:
            return
        self._started = True
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: A003
                pass

            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") in ("", "/index.html"):
                    body = dash._PAGE.encode()
                    ctype = "text/html"
                elif self.path.lstrip("/").startswith("data"):
                    body = _json.dumps(dash.snapshot_json()).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        import sys
        import threading

        try:
            self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        except OSError as exc:
            # this runs inside subscribe callbacks: a port collision must
            # not kill the streaming run — disable the dashboard loudly
            self.error = exc
            print(
                f"pw.viz.LiveDashboard: cannot bind "
                f"{self.host}:{self.port} ({exc}); dashboard disabled",
                file=sys.stderr,
            )
            return
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever,
            name="pw-dashboard",
            daemon=True,
        ).start()

    def start(self) -> None:
        """Open the port immediately (otherwise it opens lazily at the
        first commit)."""
        self._ensure_server()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()


def plot(
    table: Table,
    plotting_function: Callable | None = None,
    *,
    sorting_col: Any = None,
    host: str = "127.0.0.1",
    port: int = 0,
) -> LiveDashboard:
    """Live streaming plot of a table (reference plotting.py:plot).

    With bokeh installed and a ``plotting_function``, the reference's
    notebook path would apply; this environment has neither, so the call
    serves the table on a :class:`LiveDashboard` (row table + row-count
    sparkline) and returns it."""
    dash = LiveDashboard(host=host, port=port)
    dash.add(table, title="plot")
    dash.start()
    return dash


def show(table: Table, **kwargs: Any) -> None:
    """Reference ``Table.show()`` (interactive.py): live view of the table."""
    table_viz(table, **kwargs)


Table.show = show  # reference surface: t.show()
