"""pw.demo — artificial streams for examples and tests.

(reference: python/pathway/demo/__init__.py, 339 LoC —
generate_custom_stream :28, noisy_linear_stream, range_stream,
replay_csv :212, replay_csv_with_time :258.)
"""

from __future__ import annotations

import csv as _csv
import random
from typing import Any, Callable, Mapping

from pathway_tpu.engine.connectors import (
    INSERT,
    BatchScheduleDriver,
    DsvParser,
    FsReader,
    InputDriver,
    ParsedEvent,
    Parser,
    QueueReader,
    Reader,
)
from pathway_tpu.engine.graph import Scope
from pathway_tpu.engine.value import ref_scalar
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table, TableSpec
from pathway_tpu.io._utils import converter_for, input_table


class _GeneratorReader(Reader):
    """Emits up to ``batch_size`` generated rows per poll."""

    def __init__(self, nb_rows: int | None, batch_size: int = 1) -> None:
        self.nb_rows = nb_rows
        self.batch_size = batch_size
        self.emitted = 0

    def poll(self):
        if self.nb_rows is not None and self.emitted >= self.nb_rows:
            return [], True
        count = self.batch_size
        if self.nb_rows is not None:
            count = min(count, self.nb_rows - self.emitted)
        entries = [(self.emitted + i, f"gen:{self.emitted + i}", {}) for i in range(count)]
        self.emitted += count
        return entries, self.nb_rows is not None and self.emitted >= self.nb_rows


class _GeneratorParser(Parser):
    def __init__(self, column_names, value_generators) -> None:
        super().__init__(column_names)
        self.value_generators = value_generators

    def parse(self, payload: int) -> list[ParsedEvent]:
        values = tuple(self.value_generators[name](payload) for name in self.column_names)
        return [ParsedEvent(INSERT, values)]


def generate_custom_stream(
    value_generators: Mapping[str, Callable[[int], Any]],
    *,
    schema: schema_mod.SchemaMetaclass,
    nb_rows: int | None = None,
    autocommit_duration_ms: int = 1000,
    input_rate: float = 1.0,
    batch_size: int = 1,
    **kwargs: Any,
) -> Table:
    return input_table(
        schema,
        lambda: _GeneratorReader(nb_rows, batch_size),
        lambda names: _GeneratorParser(names, dict(value_generators)),
        source_name="demo-stream",
    )


def range_stream(
    nb_rows: int | None = 30,
    offset: int = 0,
    input_rate: float = 1.0,
    **kwargs: Any,
) -> Table:
    schema = schema_mod.schema_from_types(value=int)
    return generate_custom_stream(
        {"value": lambda i: i + offset},
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
    )


def noisy_linear_stream(
    nb_rows: int = 10, input_rate: float = 1.0, **kwargs: Any
) -> Table:
    schema = schema_mod.schema_from_types(x=float, y=float)
    rng = random.Random(0)
    return generate_custom_stream(
        {
            "x": lambda i: float(i),
            "y": lambda i: float(i) + rng.uniform(-1, 1),
        },
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
    )


def replay_csv(
    path: str,
    *,
    schema: schema_mod.SchemaMetaclass,
    input_rate: float = 1.0,
    **kwargs: Any,
) -> Table:
    """Replay a CSV file as a bounded stream (one commit batch per poll)."""
    dtypes = schema.dtypes()

    def make_reader():
        return FsReader(path, mode="static")

    def make_parser(names):
        return DsvParser(names, converters=[converter_for(dtypes[n]) for n in names])

    return input_table(schema, make_reader, make_parser, source_name=f"replay:{path}")


def replay_csv_with_time(
    path: str,
    *,
    schema: schema_mod.SchemaMetaclass,
    time_column: str,
    unit: str = "s",
    autocommit_ms: int = 100,
    speedup: float = 1,
    **kwargs: Any,
) -> Table:
    """Replay a CSV using its time column to group commit batches: rows with
    the same (scaled) time value arrive in the same commit."""
    names = schema.column_names()
    dtypes = schema.dtypes()
    convs = [converter_for(dtypes[n]) for n in names]
    tpos = names.index(time_column)

    with open(path, newline="", encoding="utf-8") as f:
        reader = _csv.reader(f)
        header = next(reader)
        positions = [header.index(n) for n in names]
        rows = []
        for row in reader:
            values = tuple(
                conv(row[p]) for conv, p in zip(convs, positions)
            )
            rows.append(values)
    rows.sort(key=lambda r: r[tpos])

    batches: list[list] = []
    current_time = None
    for i, values in enumerate(rows):
        t = values[tpos]
        if t != current_time:
            batches.append([])
            current_time = t
        batches[-1].append((INSERT, ref_scalar(i), values))

    def attach(scope: Scope, make_driver: bool = True):
        session = scope.input_session(len(names))
        if not make_driver:
            return session, None
        driver = BatchScheduleDriver(session, batches)
        return session, driver

    return Table(
        TableSpec("input", [], {"attach": attach}),
        names,
        dtypes,
        name=f"replay:{path}",
    )
