"""Persistence API: pw.persistence.Backend / Config.

Reference: python/pathway/persistence/__init__.py (Backend.filesystem/mock
:13, Config :88) over the Rust persistence subsystem (src/persistence/ —
metadata store, input snapshots, rewind on startup; SURVEY.md §5.4).

Model: every persistent input source journals its (key, row, diff) events
with commit markers plus its reader/driver state. On restart the journal is
replayed into the input session up to the last complete commit, the reader
seeks past consumed input, and processing continues — at-least-once
end-to-end, exactly-once for the replayed prefix.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

from pathway_tpu.engine.persistence import (
    FileBackend,
    MemoryBackend,
    PersistenceBackend,
)


class PersistenceMode(enum.Enum):
    PERSISTING = "persisting"  # input-event journal replay (default)
    UDF_CACHING = "udf_caching"  # only wire the UDF disk cache
    OPERATOR_PERSISTING = "operator_persisting"  # reserved (operator snapshots)


class Backend:
    """Factory namespace (reference: persistence/__init__.py:13)."""

    @staticmethod
    def filesystem(path: Any) -> PersistenceBackend:
        return FileBackend(str(path))

    @staticmethod
    def mock(events: Any = None) -> PersistenceBackend:
        return MemoryBackend()


@dataclasses.dataclass
class Config:
    backend: PersistenceBackend
    snapshot_interval_ms: int = 0
    persistence_mode: PersistenceMode = PersistenceMode.PERSISTING
    continue_after_replay: bool = True

    @staticmethod
    def simple_config(
        backend: PersistenceBackend,
        snapshot_interval_ms: int = 0,
        persistence_mode: PersistenceMode = PersistenceMode.PERSISTING,
    ) -> "Config":
        return Config(backend, snapshot_interval_ms, persistence_mode)
