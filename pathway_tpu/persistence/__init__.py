"""Persistence API: pw.persistence.Backend / Config.

Reference: python/pathway/persistence/__init__.py (Backend.filesystem/mock
:13, Config :88) over the Rust persistence subsystem (src/persistence/ —
metadata store, input snapshots, rewind on startup; SURVEY.md §5.4).

Model: every persistent input source journals its (key, row, diff) events
with commit markers plus its reader/driver state. On restart the journal is
replayed into the input session up to the last complete commit, the reader
seeks past consumed input, and processing continues — at-least-once
end-to-end, exactly-once for the replayed prefix.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

from pathway_tpu.engine.persistence import (
    FileBackend,
    MemoryBackend,
    ObjectStoreBackend,
    PersistenceBackend,
)


class PersistenceMode(enum.Enum):
    PERSISTING = "persisting"  # input-event journal replay (default)
    UDF_CACHING = "udf_caching"  # only wire the UDF disk cache
    #: snapshot operator state at commit boundaries; resume restores state
    #: and seeks readers — O(state) resume instead of O(history) replay
    #: (reference operator_snapshot.rs)
    OPERATOR_PERSISTING = "operator_persisting"


class Backend:
    """Factory namespace (reference: persistence/__init__.py:13)."""

    @staticmethod
    def filesystem(path: Any) -> PersistenceBackend:
        return FileBackend(str(path))

    @staticmethod
    def mock(events: Any = None) -> PersistenceBackend:
        return MemoryBackend()

    @staticmethod
    def s3(root_path: Any = None, bucket_settings: Any = None, *, client: Any = None) -> PersistenceBackend:
        """S3-shaped object-store backend (reference backends/s3.rs). Pass
        ``client`` (get/put/list seam — boto3 adapter from pw.io.s3, or an
        in-memory store) or AwsS3Settings as ``bucket_settings``."""
        from pathway_tpu.engine.persistence import ObjectStoreBackend

        if client is None:
            if bucket_settings is None:
                raise ValueError("pass client= or bucket_settings=")
            client = bucket_settings.create_client()
        return ObjectStoreBackend(client, str(root_path or "pathway-persistence"))

    @staticmethod
    def azure(root_path: Any = None, account: Any = None, *, client: Any = None) -> PersistenceBackend:
        """Azure blob backend through the same object-store seam."""
        from pathway_tpu.engine.persistence import ObjectStoreBackend

        if client is None:
            raise ImportError(
                "pw.persistence.Backend.azure needs an injected blob client "
                "(get_object/put_object/list_objects seam)"
            )
        return ObjectStoreBackend(client, str(root_path or "pathway-persistence"))


@dataclasses.dataclass
class Config:
    backend: PersistenceBackend
    snapshot_interval_ms: int = 0
    persistence_mode: PersistenceMode = PersistenceMode.PERSISTING
    continue_after_replay: bool = True

    @staticmethod
    def simple_config(
        backend: PersistenceBackend,
        snapshot_interval_ms: int = 0,
        persistence_mode: PersistenceMode = PersistenceMode.PERSISTING,
    ) -> "Config":
        return Config(backend, snapshot_interval_ms, persistence_mode)
