"""User-code trace attribution (reference: python/pathway/internals/trace.py).

Each operator/table records the first user-code frame that created it, so
engine errors point at user code, not framework internals.
"""

from __future__ import annotations

import os
import traceback
from dataclasses import dataclass

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass(frozen=True)
class Trace:
    file: str
    line: int
    function: str
    line_text: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line} in {self.function}"


def current_trace() -> Trace | None:
    """First stack frame outside the pathway_tpu package."""
    for frame in reversed(traceback.extract_stack()[:-1]):
        filename = os.path.abspath(frame.filename)
        if not filename.startswith(_PKG_ROOT):
            return Trace(
                file=frame.filename,
                line=frame.lineno or 0,
                function=frame.name,
                line_text=frame.line or "",
            )
    return None
