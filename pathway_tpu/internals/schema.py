"""`pw.Schema` — declarative table schemas.

New implementation of the reference's schema metaclass
(reference: python/pathway/internals/schema.py, 955 LoC): schemas are classes
whose annotations declare column dtypes; `column_definition` adds
primary-key/default metadata; helpers build schemas from dicts/types and
combine them.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping

from pathway_tpu.internals import dtype as dt

_no_default = object()


@dataclass(frozen=True)
class ColumnDefinition:
    dtype: dt.DType = dt.ANY
    primary_key: bool = False
    default_value: Any = _no_default
    name: str | None = None
    append_only: bool | None = None

    def has_default(self) -> bool:
        return self.default_value is not _no_default


def column_definition(
    *,
    primary_key: bool = False,
    default_value: Any = _no_default,
    dtype: Any = None,
    name: str | None = None,
    append_only: bool | None = None,
) -> Any:
    """Column metadata marker used as a class attribute in a Schema."""
    return ColumnDefinition(
        dtype=dt.wrap(dtype) if dtype is not None else dt.ANY,
        primary_key=primary_key,
        default_value=default_value,
        name=name,
        append_only=append_only,
    )


class SchemaProperties:
    def __init__(self, append_only: bool = False) -> None:
        self.append_only = append_only


class SchemaMetaclass(type):
    __columns__: dict[str, ColumnDefinition]
    __properties__: SchemaProperties

    def __init__(cls, name: str, bases: tuple, namespace: dict, /, **kwargs: Any) -> None:
        super().__init__(name, bases, namespace)
        append_only = bool(kwargs.get("append_only", False))
        columns: dict[str, ColumnDefinition] = {}
        for base in reversed(bases):
            columns.update(getattr(base, "__columns__", {}))
        annotations = namespace.get("__annotations__", {})
        for col_name, annotation in annotations.items():
            if col_name.startswith("__"):
                continue
            dtype = dt.wrap(annotation)
            definition = namespace.get(col_name)
            if isinstance(definition, ColumnDefinition):
                definition = ColumnDefinition(
                    dtype=dtype if definition.dtype == dt.ANY else definition.dtype,
                    primary_key=definition.primary_key,
                    default_value=definition.default_value,
                    name=definition.name or col_name,
                    append_only=definition.append_only,
                )
            else:
                definition = ColumnDefinition(dtype=dtype, name=col_name)
            columns[definition.name or col_name] = definition
        cls.__columns__ = columns
        cls.__properties__ = SchemaProperties(append_only=append_only)

    def column_names(cls) -> list[str]:
        return list(cls.__columns__.keys())

    def columns(cls) -> Mapping[str, ColumnDefinition]:
        return dict(cls.__columns__)

    def primary_key_columns(cls) -> list[str] | None:
        pkeys = [n for n, c in cls.__columns__.items() if c.primary_key]
        return pkeys or None

    def typehints(cls) -> dict[str, Any]:
        return {n: c.dtype.typehint for n, c in cls.__columns__.items()}

    def dtypes(cls) -> dict[str, dt.DType]:
        return {n: c.dtype for n, c in cls.__columns__.items()}

    def keys(cls) -> Iterable[str]:
        return cls.__columns__.keys()

    def __getitem__(cls, name: str) -> ColumnDefinition:
        return cls.__columns__[name]

    def __or__(cls, other: "SchemaMetaclass") -> "SchemaMetaclass":
        columns = dict(cls.__columns__)
        for name, col in other.__columns__.items():
            if name in columns and columns[name].dtype != col.dtype:
                raise ValueError(f"column {name!r} has conflicting dtypes in schema union")
            columns[name] = col
        return schema_from_column_definitions(columns)

    def with_types(cls, **kwargs: Any) -> "SchemaMetaclass":
        columns = dict(cls.__columns__)
        for name, dtype in kwargs.items():
            if name not in columns:
                raise ValueError(f"column {name!r} not present in schema")
            old = columns[name]
            columns[name] = ColumnDefinition(
                dtype=dt.wrap(dtype),
                primary_key=old.primary_key,
                default_value=old.default_value,
                name=old.name,
                append_only=old.append_only,
            )
        return schema_from_column_definitions(columns)

    def without(cls, *names: str) -> "SchemaMetaclass":
        columns = {n: c for n, c in cls.__columns__.items() if n not in names}
        return schema_from_column_definitions(columns)

    def update_properties(cls, **kwargs: Any) -> "SchemaMetaclass":
        new = schema_from_column_definitions(dict(cls.__columns__))
        new.__properties__ = SchemaProperties(**kwargs)
        return new

    def __repr__(cls) -> str:
        cols = ", ".join(f"{n}: {c.dtype!r}" for n, c in cls.__columns__.items())
        return f"<pw.Schema {cls.__name__}({cols})>"


class Schema(metaclass=SchemaMetaclass):
    """Base class for user-defined schemas:

    >>> class InputSchema(pw.Schema):
    ...     name: str
    ...     age: int
    """


_schema_counter = itertools.count()


def schema_from_column_definitions(
    columns: dict[str, ColumnDefinition], name: str | None = None
) -> SchemaMetaclass:
    if name is None:
        name = f"Schema_{next(_schema_counter)}"
    cls = SchemaMetaclass(name, (Schema,), {})
    cls.__columns__ = dict(columns)
    cls.__properties__ = SchemaProperties()
    return cls


def schema_from_types(_name: str | None = None, **kwargs: Any) -> SchemaMetaclass:
    """`pw.schema_from_types(x=int, y=str)`"""
    columns = {n: ColumnDefinition(dtype=dt.wrap(t), name=n) for n, t in kwargs.items()}
    return schema_from_column_definitions(columns, name=_name)


def schema_from_dict(
    columns: Mapping[str, Any], *, name: str | None = None
) -> SchemaMetaclass:
    defs: dict[str, ColumnDefinition] = {}
    for col_name, spec in columns.items():
        if isinstance(spec, ColumnDefinition):
            defs[col_name] = spec
        elif isinstance(spec, Mapping):
            defs[col_name] = ColumnDefinition(
                dtype=dt.wrap(spec.get("dtype", Any)),
                primary_key=spec.get("primary_key", False),
                default_value=spec.get("default_value", _no_default),
                name=col_name,
            )
        else:
            defs[col_name] = ColumnDefinition(dtype=dt.wrap(spec), name=col_name)
    return schema_from_column_definitions(defs, name=name)


def schema_builder(
    columns: Mapping[str, ColumnDefinition],
    *,
    name: str | None = None,
    properties: SchemaProperties | None = None,
) -> SchemaMetaclass:
    cls = schema_from_column_definitions(dict(columns), name=name)
    if properties is not None:
        cls.__properties__ = properties
    return cls


def schema_from_csv(
    path: str,
    *,
    name: str | None = None,
    num_parsed_rows: int | None = 30,
    delimiter: str = ",",
    quote: str = '"',
    double_quote_escapes: bool = True,
) -> SchemaMetaclass:
    """Infer a schema from a CSV file's header + a sample of rows
    (reference schema_from_csv): int ⊂ float ⊂ str by widening."""
    import csv as _csv

    def classify(text: str) -> type:
        try:
            int(text)
            return int
        except ValueError:
            pass
        try:
            float(text)
            return float
        except ValueError:
            return str

    with open(path, newline="", encoding="utf-8") as f:
        reader = _csv.reader(
            f,
            delimiter=delimiter,
            quotechar=quote,
            doublequote=double_quote_escapes,
        )
        header = next(reader, None)
        if header is None:
            raise ValueError(f"schema_from_csv: {path!r} is empty (no header)")
        if len(set(header)) != len(header):
            dupes = sorted({h for h in header if header.count(h) > 1})
            raise ValueError(
                f"schema_from_csv: duplicate column names {dupes}"
            )
        kinds: dict[str, type | None] = {h: None for h in header}
        for i, row in enumerate(reader):
            if num_parsed_rows is not None and i >= num_parsed_rows:
                break
            for h, cell in zip(header, row):
                k = classify(cell)
                prev = kinds[h]
                if prev is None or prev is k:
                    kinds[h] = k
                elif {prev, k} == {int, float}:
                    kinds[h] = float
                else:
                    kinds[h] = str
    return schema_from_types(
        name, **{h: (k or str) for h, k in kinds.items()}
    )


def assert_table_has_schema(
    table: Any,
    schema: SchemaMetaclass,
    *,
    allow_superset: bool = False,
    ignore_primary_keys: bool = True,
) -> None:
    """Raise AssertionError unless the table's columns (and dtypes) match
    the schema (reference pw.assert_table_has_schema)."""
    table_types = {n: table._dtypes[n] for n in table.column_names()}
    wanted = dict(schema.dtypes())
    if not ignore_primary_keys:
        table_pk = set(table.schema.primary_key_columns() or [])
        schema_pk = set(schema.primary_key_columns() or [])
        if table_pk != schema_pk:
            raise AssertionError(
                f"primary keys differ: table {sorted(table_pk)} vs schema "
                f"{sorted(schema_pk)}"
            )
    if allow_superset:
        missing = [n for n in wanted if n not in table_types]
        if missing:
            raise AssertionError(
                f"table lacks columns required by the schema: {missing}"
            )
        compare = {n: table_types[n] for n in wanted}
    else:
        if set(table_types) != set(wanted):
            raise AssertionError(
                f"column sets differ: table {sorted(table_types)} vs "
                f"schema {sorted(wanted)}"
            )
        compare = table_types
    for n, dtype in compare.items():
        if dtype != wanted[n] and wanted[n] != dt.ANY and dtype != dt.ANY:
            raise AssertionError(
                f"column {n!r}: table dtype {dtype!r} != schema {wanted[n]!r}"
            )
