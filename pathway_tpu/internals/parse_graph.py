"""Global capture graph ``G`` and ``pw.run``.

(reference: python/pathway/internals/parse_graph.py:244 + run.py:12).
Sinks (io.write / subscribe / debug captures) register here; ``pw.run``
lowers everything reachable and pumps the scheduler — static sources run in
one commit; connector-backed sources run the streaming loop.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Callable

from pathway_tpu.engine.graph import Node, Scheduler, Scope

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


@dataclass
class SinkSpec:
    table: "Table"
    attach: Callable[[Scope, Node], Any]  # returns optional driver
    #: internal sinks (AsyncTransformer loopback subscriptions) are part of
    #: the dataflow itself: debug captures must attach them to make their
    #: loopback sources progress, while user output sinks stay registered
    #: for the eventual pw.run()
    internal: bool = False


class ParseGraph:
    def __init__(self) -> None:
        self.sinks: list[SinkSpec] = []
        self.error_log_tables: list[Table] = []

    def add_sink(
        self,
        table: "Table",
        attach: Callable[[Scope, Node], Any],
        internal: bool = False,
    ) -> None:
        self.sinks.append(SinkSpec(table, attach, internal))

    def clear(self) -> None:
        self.sinks = []
        self.error_log_tables = []


G = ParseGraph()


def run(
    *,
    monitoring_level: Any = None,
    with_http_server: bool = False,
    monitoring_server_port: int | None = None,
    debug: bool = False,
    persistence_config: Any = None,
    strict: bool = False,
    **kwargs: Any,
) -> None:
    """Execute the captured graph (reference: pw.run, internals/run.py:12).

    ``monitoring_level``: pw.MonitoringLevel (NONE/IN_OUT/ALL) — IN_OUT and
    ALL render a live rich dashboard; ``with_http_server`` additionally
    serves Prometheus metrics on port 20000 + PATHWAY_PROCESS_ID
    (reference monitoring.py:56-228, http_server.rs:22).

    ``strict=True`` runs the pre-execution static analyzer over the built
    graph and raises ``pathway_tpu.analysis.AnalysisError`` on any
    error-severity finding before any data flows."""
    from pathway_tpu.analysis import runtime as _analysis_runtime
    from pathway_tpu.internals.config import get_pathway_config
    from pathway_tpu.internals.runner import (
        DistributedGraphRunner,
        GraphRunner,
        ShardedGraphRunner,
    )

    config = get_pathway_config()
    if persistence_config is None:
        # env-driven persistence (PATHWAY_PERSISTENT_STORAGE etc.,
        # reference PathwayConfig.replay_config)
        persistence_config = config.replay_config
    threads = kwargs.get("threads") or config.threads
    processes = kwargs.get("processes") or config.processes
    if _analysis_runtime.enabled():
        # graph-only mode (cli analyze): one local worker, no connector
        # drivers, no exchange sockets, no dashboards — the scheduler
        # intercepts before any data flows, whatever the topology asks for
        runner = GraphRunner(persistence_config=None, attach_drivers=False)
        processes = threads = 1
        monitoring_level = None
        with_http_server = False
    elif processes > 1:
        # multi-process: identical program per process, key-sharded TCP
        # exchange (engine/distributed.py; reference `pathway spawn`
        # cluster topology, config.rs:72-86)
        runner: Any = DistributedGraphRunner(
            threads,
            processes,
            int(config.process_id),
            first_port=config.first_port,
            persistence_config=persistence_config,
        )
        if int(config.process_id) != 0:
            # live dashboards belong to process 0 only (the Prometheus
            # endpoint stays per-process: port 20000 + process_id, as in
            # the reference http_server.rs:22)
            from pathway_tpu.internals.monitoring import MonitoringLevel

            monitoring_level = MonitoringLevel.NONE
    elif threads > 1:
        # multi-worker: identical graph per worker, key-sharded exchange
        # (engine/sharded.py; reference PATHWAY_THREADS)
        runner: Any = ShardedGraphRunner(
            threads, persistence_config=persistence_config
        )
    else:
        runner = GraphRunner(persistence_config=persistence_config)

    monitor = None
    http_server = None
    level = monitoring_level
    if level is not None or with_http_server:
        import sys

        from pathway_tpu.internals.monitoring import (
            MonitoringHttpServer,
            MonitoringLevel,
            StatsMonitor,
        )

        if level is None or level == MonitoringLevel.AUTO:
            level = (
                MonitoringLevel.IN_OUT
                if sys.stderr.isatty()
                else MonitoringLevel.NONE
            )
        if level != MonitoringLevel.NONE or with_http_server:
            monitor = StatsMonitor(
                level if level != MonitoringLevel.NONE else MonitoringLevel.IN_OUT
            )
            runner.monitor = monitor
            if level != MonitoringLevel.NONE:
                monitor.start_live()
            if with_http_server:
                http_server = MonitoringHttpServer(
                    monitor, port=monitoring_server_port
                )

    from pathway_tpu import serving as _serving
    from pathway_tpu.internals import profiling as _profiling
    from pathway_tpu.internals import timeseries as _timeseries
    from pathway_tpu.internals.metrics import FLIGHT
    from pathway_tpu.internals.telemetry import run_span, telemetry_enabled

    query_server = None
    if _serving.enabled() and not _analysis_runtime.enabled():
        # the serving plane is per-process: every mesh member answers
        # queries from its own shard's snapshots on 21000 + process_id
        query_server = _serving.start_server()

    profiler_started = False
    telemetry_loop_started = False
    if not _analysis_runtime.enabled():
        # sampling profiler: strictly opt-in (PATHWAY_TPU_PROFILE=1) —
        # when unset this is a boolean test, no thread, no cost
        profiler_started = _profiling.PROFILER.maybe_start()
        # metrics history ring: feed it whenever something can read it
        # (an HTTP endpoint serving /timeseries) or the user asked for
        # it explicitly (PATHWAY_TPU_TIMESERIES=1 / PATHWAY_TPU_SLO)
        if with_http_server or _timeseries.loop_enabled():
            if monitor is None and _timeseries.loop_enabled():
                # SLO evaluation without a dashboard: a quiet monitor
                # gives the loop its scheduler/mesh_snapshots views
                from pathway_tpu.internals.monitoring import (
                    MonitoringLevel,
                    StatsMonitor,
                )

                monitor = StatsMonitor(MonitoringLevel.IN_OUT)
                runner.monitor = monitor
            if monitor is not None:
                _timeseries.start_loop(monitor)
                telemetry_loop_started = True

    if telemetry_enabled():
        # per-operator stats feed the metrics sampler + operator spans
        runner.probe_stats = True
    FLIGHT.record(
        "run_start", threads=threads, processes=processes,
        process_id=int(config.process_id),
    )
    try:
        with run_span(lambda: getattr(runner, "scheduler", None)):
            if isinstance(runner, (ShardedGraphRunner, DistributedGraphRunner)):
                runner.attach_sinks()
                if strict:
                    from pathway_tpu.analysis import check_strict

                    # workers are identical replicas; worker 0 carries the
                    # superset (sinks attach there only)
                    check_strict(runner.workers[0].scope)
                runner.run()
            else:
                for sink in G.sinks:
                    node = runner.build(sink.table)
                    driver = sink.attach(runner.scope, node)
                    if driver is not None:
                        runner.drivers.append(driver)
                if strict:
                    from pathway_tpu.analysis import check_strict

                    check_strict(runner.scope)
                runner.run()
        FLIGHT.record("run_end")
    except BaseException as exc:
        # crash forensics from ANY worker: the last commits/exchanges/
        # errors of this process land on disk before the raise surfaces
        # (PATHWAY_TPU_FLIGHT_DIR picks where)
        FLIGHT.record("run_error", error=repr(exc))
        FLIGHT.dump(f"pw.run raised: {exc!r}")
        raise
    finally:
        if telemetry_loop_started:
            # final tick inside stop_loop captures the run's last state
            _timeseries.stop_loop()
        if profiler_started:
            _profiling.PROFILER.stop()
            # best-effort forensics: export() swallows write failures
            _profiling.PROFILER.export()
        if monitor is not None:
            monitor.stop()
        if http_server is not None and not kwargs.get("_keep_http_server"):
            http_server.stop()
        if query_server is not None and not kwargs.get("_keep_http_server"):
            _serving.stop_server()
        # reap the device completion worker: a raising run must not
        # leave the daemon behind (it respawns on next use)
        from pathway_tpu.engine import device_pipeline as _device_pipeline

        _device_pipeline.stop_worker()
        G.clear()


def run_all(**kwargs: Any) -> None:
    run(**kwargs)
