"""License / entitlements (reference: src/engine/license.rs — ed25519-
signed keys gate >8 workers, monitoring, SharePoint/DeltaLake extras;
MAX_WORKERS free cap src/engine/dataflow/config.rs:7-11).

No license server is reachable here, so keys are self-describing:
``pathway-tpu:<entitlement>[,<entitlement>...]`` (e.g.
``pathway-tpu:unlimited-workers,xpack-sharepoint``). An absent key is the
free tier: everything runs, capped at MAX_WORKERS logical workers.
"""

from __future__ import annotations

MAX_WORKERS = 8  # free-tier cap (reference config.rs:7)

ENTITLEMENT_UNLIMITED_WORKERS = "unlimited-workers"
ENTITLEMENT_XPACK_SHAREPOINT = "xpack-sharepoint"


class LicenseError(RuntimeError):
    pass


def _entitlements() -> set[str]:
    from pathway_tpu.internals.config import get_pathway_config

    key = get_pathway_config().license_key
    if not key:
        return set()
    if not key.startswith("pathway-tpu:"):
        raise LicenseError(
            f"unrecognized license key format {key[:16]!r}..."
        )
    return {e.strip() for e in key.split(":", 1)[1].split(",") if e.strip()}


def check_entitlements(*entitlements: str) -> None:
    """Raise LicenseError unless the active license grants every requested
    entitlement (reference check_entitlements python_api.rs:5538)."""
    have = _entitlements()
    missing = [e for e in entitlements if e not in have]
    if missing:
        raise LicenseError(
            f"the active license does not grant: {', '.join(missing)}; set a "
            f"key with pw.set_license_key('pathway-tpu:<entitlements>')"
        )


def check_worker_count(n_workers: int) -> None:
    """Free tier caps logical workers at MAX_WORKERS (reference
    config.rs:7-11)."""
    if n_workers <= MAX_WORKERS:
        return
    if ENTITLEMENT_UNLIMITED_WORKERS in _entitlements():
        return
    raise LicenseError(
        f"{n_workers} workers exceeds the free tier's {MAX_WORKERS}; license "
        f"with the {ENTITLEMENT_UNLIMITED_WORKERS!r} entitlement to raise it"
    )
