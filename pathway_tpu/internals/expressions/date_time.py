"""``expr.dt.*`` datetime method namespace (reference: expressions/date_time.py)."""

from __future__ import annotations

import datetime
from typing import Any

from pathway_tpu.internals.expression import (
    ApplyExpression,
    ColumnExpression,
    wrap_expression,
)


def _method(fn, ret, *args):
    return ApplyExpression(fn, ret, args, {}, propagate_none=True)


class DateTimeNamespace:
    def __init__(self, expression: ColumnExpression) -> None:
        self._e = expression

    def year(self) -> ColumnExpression:
        return _method(lambda d: d.year, int, self._e)

    def month(self) -> ColumnExpression:
        return _method(lambda d: d.month, int, self._e)

    def day(self) -> ColumnExpression:
        return _method(lambda d: d.day, int, self._e)

    def hour(self) -> ColumnExpression:
        return _method(lambda d: d.hour, int, self._e)

    def minute(self) -> ColumnExpression:
        return _method(lambda d: d.minute, int, self._e)

    def second(self) -> ColumnExpression:
        return _method(lambda d: d.second, int, self._e)

    def microsecond(self) -> ColumnExpression:
        return _method(lambda d: d.microsecond, int, self._e)

    def millisecond(self) -> ColumnExpression:
        return _method(lambda d: d.microsecond // 1000, int, self._e)

    def nanosecond(self) -> ColumnExpression:
        return _method(lambda d: d.microsecond * 1000, int, self._e)

    def weekday(self) -> ColumnExpression:
        return _method(lambda d: d.weekday(), int, self._e)

    def timestamp(self, unit: str = "s") -> ColumnExpression:
        scale = {"ns": 1e9, "us": 1e6, "ms": 1e3, "s": 1.0}[unit]

        def ts(d: datetime.datetime) -> float:
            if d.tzinfo is None:
                d = d.replace(tzinfo=datetime.timezone.utc)
            return d.timestamp() * scale

        return _method(ts, float, self._e)

    def strftime(self, fmt: Any) -> ColumnExpression:
        return _method(lambda d, f: d.strftime(f), str, self._e, wrap_expression(fmt))

    def strptime(self, fmt: Any) -> ColumnExpression:
        return _method(
            lambda s, f: datetime.datetime.strptime(s, f),
            datetime.datetime,
            self._e,
            wrap_expression(fmt),
        )

    def to_utc(self, from_timezone: str) -> ColumnExpression:
        import zoneinfo

        def conv(d: datetime.datetime) -> datetime.datetime:
            tz = zoneinfo.ZoneInfo(from_timezone)
            return d.replace(tzinfo=tz).astimezone(datetime.timezone.utc)

        return _method(conv, datetime.datetime, self._e)

    def to_naive_in_timezone(self, timezone: str) -> ColumnExpression:
        import zoneinfo

        def conv(d: datetime.datetime) -> datetime.datetime:
            tz = zoneinfo.ZoneInfo(timezone)
            return d.astimezone(tz).replace(tzinfo=None)

        return _method(conv, datetime.datetime, self._e)

    def round(self, duration: Any) -> ColumnExpression:
        return _method(_round_dt, datetime.datetime, self._e, wrap_expression(duration))

    def floor(self, duration: Any) -> ColumnExpression:
        return _method(_floor_dt, datetime.datetime, self._e, wrap_expression(duration))

    # duration accessors
    def days(self) -> ColumnExpression:
        return _method(lambda d: d.days, int, self._e)

    def hours(self) -> ColumnExpression:
        return _method(lambda d: int(d.total_seconds() // 3600), int, self._e)

    def minutes(self) -> ColumnExpression:
        return _method(lambda d: int(d.total_seconds() // 60), int, self._e)

    def seconds(self) -> ColumnExpression:
        return _method(lambda d: int(d.total_seconds()), int, self._e)

    def milliseconds(self) -> ColumnExpression:
        return _method(lambda d: int(d.total_seconds() * 1e3), int, self._e)

    def microseconds(self) -> ColumnExpression:
        return _method(lambda d: int(d.total_seconds() * 1e6), int, self._e)

    def nanoseconds(self) -> ColumnExpression:
        return _method(lambda d: int(d.total_seconds() * 1e9), int, self._e)


def _floor_dt(d: datetime.datetime, dur: datetime.timedelta) -> datetime.datetime:
    epoch = datetime.datetime(1970, 1, 1, tzinfo=d.tzinfo)
    delta = (d - epoch).total_seconds()
    step = dur.total_seconds()
    return epoch + datetime.timedelta(seconds=(delta // step) * step)


def _round_dt(d: datetime.datetime, dur: datetime.timedelta) -> datetime.datetime:
    floor = _floor_dt(d, dur)
    if (d - floor) * 2 >= dur:
        return floor + dur
    return floor
