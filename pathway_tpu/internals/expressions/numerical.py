"""``expr.num.*`` numeric method namespace (reference: expressions/numerical.py)."""

from __future__ import annotations

import math
from typing import Any

from pathway_tpu.internals.expression import (
    ApplyExpression,
    ColumnExpression,
    wrap_expression,
)


def _method(fn, ret, *args):
    return ApplyExpression(fn, ret, args, {}, propagate_none=True)


class NumericalNamespace:
    def __init__(self, expression: ColumnExpression) -> None:
        self._e = expression

    def abs(self) -> ColumnExpression:
        return _method(abs, float, self._e)

    def round(self, decimals: Any = 0) -> ColumnExpression:
        return _method(lambda x, d: round(x, d), float, self._e, wrap_expression(decimals))

    def fill_na(self, default_value: Any) -> ColumnExpression:
        def fill(x: Any, d: Any) -> Any:
            if x is None:
                return d
            if isinstance(x, float) and math.isnan(x):
                return d
            return x

        return ApplyExpression(
            fill, None, (self._e, wrap_expression(default_value)), {}, propagate_none=False
        )
