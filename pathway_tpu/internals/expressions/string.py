"""``expr.str.*`` string method namespace (reference: expressions/string.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ApplyExpression,
    ColumnExpression,
    wrap_expression,
)


def _method(fn, ret, *args, propagate_none=True):
    return ApplyExpression(fn, ret, args, {}, propagate_none=propagate_none)


class StringNamespace:
    def __init__(self, expression: ColumnExpression) -> None:
        self._e = expression

    def lower(self) -> ColumnExpression:
        return _method(lambda s: s.lower(), str, self._e)

    def upper(self) -> ColumnExpression:
        return _method(lambda s: s.upper(), str, self._e)

    def reversed(self) -> ColumnExpression:
        return _method(lambda s: s[::-1], str, self._e)

    def len(self) -> ColumnExpression:
        return _method(len, int, self._e)

    def strip(self, chars: Any = None) -> ColumnExpression:
        # a literal-None optional arg must not ride through None-propagating
        # apply (it would blank the result row) — omit it instead
        if chars is None:
            return _method(lambda s: s.strip(), str, self._e)
        return _method(lambda s, c: s.strip(c), str, self._e, wrap_expression(chars))

    def lstrip(self, chars: Any = None) -> ColumnExpression:
        if chars is None:
            return _method(lambda s: s.lstrip(), str, self._e)
        return _method(lambda s, c: s.lstrip(c), str, self._e, wrap_expression(chars))

    def rstrip(self, chars: Any = None) -> ColumnExpression:
        if chars is None:
            return _method(lambda s: s.rstrip(), str, self._e)
        return _method(lambda s, c: s.rstrip(c), str, self._e, wrap_expression(chars))

    def startswith(self, prefix: Any) -> ColumnExpression:
        return _method(lambda s, p: s.startswith(p), bool, self._e, wrap_expression(prefix))

    def endswith(self, suffix: Any) -> ColumnExpression:
        return _method(lambda s, p: s.endswith(p), bool, self._e, wrap_expression(suffix))

    def swapcase(self) -> ColumnExpression:
        return _method(lambda s: s.swapcase(), str, self._e)

    def title(self) -> ColumnExpression:
        return _method(lambda s: s.title(), str, self._e)

    def count(self, sub: Any, start: Any = None, end: Any = None) -> ColumnExpression:
        return self._bounded(lambda s: s.count, int, sub, start, end)

    def _bounded(self, method_of, ret, sub: Any, start: Any, end: Any) -> ColumnExpression:
        # omitted bounds must not ride through None-propagating apply (a
        # None operand would blank the whole result): pass only given args
        args = [self._e, wrap_expression(sub)]
        if start is not None or end is not None:
            args.append(wrap_expression(0 if start is None else start))
        if end is not None:
            args.append(wrap_expression(end))
        fns = {
            2: lambda s, x: method_of(s)(x),
            3: lambda s, x, b: method_of(s)(x, b),
            4: lambda s, x, b, e: method_of(s)(x, b, e),
        }
        return _method(fns[len(args)], ret, *args)

    def find(self, sub: Any, start: Any = None, end: Any = None) -> ColumnExpression:
        return self._bounded(lambda s: s.find, int, sub, start, end)

    def rfind(self, sub: Any, start: Any = None, end: Any = None) -> ColumnExpression:
        return self._bounded(lambda s: s.rfind, int, sub, start, end)

    def replace(self, old: Any, new: Any, count: Any = -1) -> ColumnExpression:
        return _method(
            lambda s, o, n, c: s.replace(o, n, c),
            str,
            self._e,
            wrap_expression(old),
            wrap_expression(new),
            wrap_expression(count),
        )

    def split(self, sep: Any = None, maxsplit: Any = -1) -> ColumnExpression:
        if sep is None:  # whitespace split; None must not blank the row
            return _method(
                lambda s, m: tuple(s.split(None, m)),
                tuple[str, ...],
                self._e,
                wrap_expression(maxsplit),
            )
        return ApplyExpression(
            lambda s, sp, m: tuple(s.split(sp, m)),
            tuple[str, ...],
            (self._e, wrap_expression(sep), wrap_expression(maxsplit)),
            {},
            propagate_none=True,
        )

    def slice(self, start: Any, end: Any) -> ColumnExpression:
        return _method(
            lambda s, b, e: s[b:e], str, self._e, wrap_expression(start), wrap_expression(end)
        )

    def parse_int(self, optional: bool = False) -> ColumnExpression:
        def parse(s: str) -> int | None:
            try:
                return int(s)
            except (ValueError, TypeError):
                if optional:
                    return None
                raise

        return _method(parse, int | None if optional else int, self._e)

    def parse_float(self, optional: bool = False) -> ColumnExpression:
        def parse(s: str) -> float | None:
            try:
                return float(s)
            except (ValueError, TypeError):
                if optional:
                    return None
                raise

        return _method(parse, float | None if optional else float, self._e)

    def parse_bool(self, optional: bool = False) -> ColumnExpression:
        def parse(s: str) -> bool | None:
            low = s.strip().lower()
            if low in ("true", "1", "yes", "on"):
                return True
            if low in ("false", "0", "no", "off"):
                return False
            if optional:
                return None
            raise ValueError(f"cannot parse {s!r} as bool")

        return _method(parse, bool | None if optional else bool, self._e)

    def to_datetime(self, fmt: Any = None) -> ColumnExpression:
        import datetime

        def parse(s: str, f: str | None = None) -> datetime.datetime:
            if f is not None:
                return datetime.datetime.strptime(s, f)
            return datetime.datetime.fromisoformat(s)

        if fmt is None:
            return _method(parse, datetime.datetime, self._e)
        return _method(parse, datetime.datetime, self._e, wrap_expression(fmt))