"""``expr.str.*`` string method namespace (reference: expressions/string.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ApplyExpression,
    ColumnExpression,
    wrap_expression,
)


def _method(fn, ret, *args, propagate_none=True):
    return ApplyExpression(fn, ret, args, {}, propagate_none=propagate_none)


class StringNamespace:
    def __init__(self, expression: ColumnExpression) -> None:
        self._e = expression

    def lower(self) -> ColumnExpression:
        return _method(lambda s: s.lower(), str, self._e)

    def upper(self) -> ColumnExpression:
        return _method(lambda s: s.upper(), str, self._e)

    def reversed(self) -> ColumnExpression:
        return _method(lambda s: s[::-1], str, self._e)

    def len(self) -> ColumnExpression:
        return _method(len, int, self._e)

    def strip(self, chars: Any = None) -> ColumnExpression:
        return _method(lambda s, c: s.strip(c), str, self._e, wrap_expression(chars))

    def lstrip(self, chars: Any = None) -> ColumnExpression:
        return _method(lambda s, c: s.lstrip(c), str, self._e, wrap_expression(chars))

    def rstrip(self, chars: Any = None) -> ColumnExpression:
        return _method(lambda s, c: s.rstrip(c), str, self._e, wrap_expression(chars))

    def startswith(self, prefix: Any) -> ColumnExpression:
        return _method(lambda s, p: s.startswith(p), bool, self._e, wrap_expression(prefix))

    def endswith(self, suffix: Any) -> ColumnExpression:
        return _method(lambda s, p: s.endswith(p), bool, self._e, wrap_expression(suffix))

    def swapcase(self) -> ColumnExpression:
        return _method(lambda s: s.swapcase(), str, self._e)

    def title(self) -> ColumnExpression:
        return _method(lambda s: s.title(), str, self._e)

    def count(self, sub: Any, start: Any = None, end: Any = None) -> ColumnExpression:
        return _method(
            lambda s, x, b, e: s.count(x, b, e),
            int,
            self._e,
            wrap_expression(sub),
            wrap_expression(start),
            wrap_expression(end),
        )

    def find(self, sub: Any, start: Any = None, end: Any = None) -> ColumnExpression:
        return _method(
            lambda s, x, b, e: s.find(x, b, e),
            int,
            self._e,
            wrap_expression(sub),
            wrap_expression(start),
            wrap_expression(end),
        )

    def rfind(self, sub: Any, start: Any = None, end: Any = None) -> ColumnExpression:
        return _method(
            lambda s, x, b, e: s.rfind(x, b, e),
            int,
            self._e,
            wrap_expression(sub),
            wrap_expression(start),
            wrap_expression(end),
        )

    def replace(self, old: Any, new: Any, count: Any = -1) -> ColumnExpression:
        return _method(
            lambda s, o, n, c: s.replace(o, n, c),
            str,
            self._e,
            wrap_expression(old),
            wrap_expression(new),
            wrap_expression(count),
        )

    def split(self, sep: Any = None, maxsplit: Any = -1) -> ColumnExpression:
        return ApplyExpression(
            lambda s, sp, m: tuple(s.split(sp, m)),
            tuple[str, ...],
            (self._e, wrap_expression(sep), wrap_expression(maxsplit)),
            {},
            propagate_none=True,
        )

    def slice(self, start: Any, end: Any) -> ColumnExpression:
        return _method(
            lambda s, b, e: s[b:e], str, self._e, wrap_expression(start), wrap_expression(end)
        )

    def parse_int(self, optional: bool = False) -> ColumnExpression:
        def parse(s: str) -> int | None:
            try:
                return int(s)
            except (ValueError, TypeError):
                if optional:
                    return None
                raise

        return _method(parse, int | None if optional else int, self._e)

    def parse_float(self, optional: bool = False) -> ColumnExpression:
        def parse(s: str) -> float | None:
            try:
                return float(s)
            except (ValueError, TypeError):
                if optional:
                    return None
                raise

        return _method(parse, float | None if optional else float, self._e)

    def parse_bool(self, optional: bool = False) -> ColumnExpression:
        def parse(s: str) -> bool | None:
            low = s.strip().lower()
            if low in ("true", "1", "yes", "on"):
                return True
            if low in ("false", "0", "no", "off"):
                return False
            if optional:
                return None
            raise ValueError(f"cannot parse {s!r} as bool")

        return _method(parse, bool | None if optional else bool, self._e)

    def to_datetime(self, fmt: Any = None) -> ColumnExpression:
        import datetime

        def parse(s: str, f: str | None) -> datetime.datetime:
            if f is not None:
                return datetime.datetime.strptime(s, f)
            return datetime.datetime.fromisoformat(s)

        return _method(parse, datetime.datetime, self._e, wrap_expression(fmt))