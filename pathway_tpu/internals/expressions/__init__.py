"""Method namespaces for expressions: ``.dt``, ``.str``, ``.num``.

(reference: python/pathway/internals/expressions/ — date_time.py 1,613 LoC,
string.py 931 LoC, numerical.py). Implemented as Apply-lowered library
functions; the vectorized NumPy fast path applies batch-wise in the engine.
"""
