"""pw.iterate — fixed-point iteration over the Table API.

Reference: pw.iterate / IterateOperator (internals/operator.py:316) lowering
to Graph::iterate (SURVEY.md §3.6). The body function is called once with
*parameter tables* to capture the inner spec graph; execution is the
host-driven loop of engine/iterate.py: bind parameters to the current
state, run the captured subgraph statically, feed results back, repeat
until convergence or ``iteration_limit``.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table, TableSpec


class _IterationEngine:
    def __init__(
        self,
        func: Callable,
        outer: dict[str, Table],
        iteration_limit: int | None,
    ) -> None:
        self.outer_names = list(outer)
        self.outer_tables = list(outer.values())
        self.limit = iteration_limit
        # parameter tables: stand-ins bound per iteration
        self.params: dict[str, Table] = {}
        for slot, (name, t) in enumerate(outer.items()):
            self.params[name] = Table(
                TableSpec("iterate_param", [], {"slot": slot}),
                t.column_names(),
                {n: t._dtypes[n] for n in t.column_names()},
                name=f"iterate_param_{name}",
            )
        result = func(**self.params)
        if isinstance(result, Table):
            result = {"result": result}
        elif not isinstance(result, dict):
            result = dict(result._asdict()) if hasattr(result, "_asdict") else dict(result)
        self.results: dict[str, Table] = result
        # names fed back into the next iteration
        self.feedback = [n for n in self.results if n in self.params]
        self._cache_inputs: list[dict] | None = None
        self._cache_out: dict[str, dict] | None = None

    def compute_all(self, input_states: list[dict]) -> dict[str, dict]:
        if self._cache_inputs is not None and all(
            a == b for a, b in zip(self._cache_inputs, input_states)
        ):
            assert self._cache_out is not None
            return self._cache_out
        from pathway_tpu.internals.runner import GraphRunner

        state = {
            name: dict(input_states[i])
            for i, name in enumerate(self.outer_names)
        }
        steps = 0
        while True:
            runner = GraphRunner()
            runner.iterate_params = [
                list(state[name].items()) for name in self.outer_names
            ]
            nodes = {n: runner.build(t) for n, t in self.results.items()}
            runner.run_static()
            out = {n: dict(node.current) for n, node in nodes.items()}
            steps += 1
            converged = all(out[n] == state[n] for n in self.feedback)
            for n in self.feedback:
                state[n] = out[n]
            if converged or (self.limit is not None and steps >= self.limit):
                break
        self._cache_inputs = [dict(s) for s in input_states]
        self._cache_out = out
        return out


class IterationResult:
    """Holds the iterated tables; attribute access mirrors the reference."""

    def __init__(self, tables: dict[str, Table]) -> None:
        self._tables = tables
        for name, t in tables.items():
            setattr(self, name, t)

    def __getitem__(self, name: str) -> Table:
        return self._tables[name]


def iterate(
    func: Callable,
    iteration_limit: int | None = None,
    **kwargs: Table,
) -> IterationResult:
    """Iterate ``func`` to fixed point (reference: pw.iterate).

    ``func(**tables) -> dict[str, Table] | Table`` — returned names that
    match parameter names are fed back each round; all returned tables are
    exposed on the result.
    """
    if not kwargs:
        raise ValueError("pw.iterate needs at least one input table")
    engine = _IterationEngine(func, kwargs, iteration_limit)
    out_tables: dict[str, Table] = {}
    for name, spec_table in engine.results.items():
        out_tables[name] = Table(
            TableSpec(
                "iterate_result",
                list(kwargs.values()),
                {"engine": engine, "name": name},
            ),
            spec_table.column_names(),
            {c: spec_table._dtypes[c] for c in spec_table.column_names()},
            name=f"iterate_{name}",
        )
    return IterationResult(out_tables)
