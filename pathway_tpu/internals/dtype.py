"""Column dtype lattice for the Python layer.

New implementation of the reference's dtype system
(reference: python/pathway/internals/dtype.py, 979 LoC): a small set of
singleton dtypes plus parametric Optional/Tuple/List/Array/Callable/Pointer
wrappers, conversion from Python type annotations, and lattice operations
(is_subclass / lca) used by the type interpreter.
"""

from __future__ import annotations

import datetime
import types as _types
import typing
from typing import Any, Optional, Union, get_args, get_origin

import numpy as np

from pathway_tpu.engine import value as engine_value
from pathway_tpu.engine.value import Json as _Json
from pathway_tpu.engine.value import Pointer as _Pointer
from pathway_tpu.engine.value import PyObjectWrapper as _PyObjectWrapper
from pathway_tpu.engine.value import Type as EngineType


class DType:
    """Base class for column dtypes."""

    _name: str = "DType"

    def to_engine(self) -> EngineType:
        raise NotImplementedError

    @property
    def typehint(self) -> Any:
        return Any

    def is_optional(self) -> bool:
        return False

    def strip_optional(self) -> "DType":
        return self

    def __repr__(self) -> str:
        return self._name

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))


class _SimpleDType(DType):
    def __init__(self, name: str, engine_type: EngineType, typehint: Any) -> None:
        self._name = name
        self._engine_type = engine_type
        self._typehint = typehint

    def to_engine(self) -> EngineType:
        return self._engine_type

    @property
    def typehint(self) -> Any:
        return self._typehint

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, _SimpleDType) and other._name == self._name

    def __hash__(self) -> int:
        return hash(self._name)


ANY = _SimpleDType("ANY", EngineType.ANY, Any)
NONE = _SimpleDType("NONE", EngineType.NONE, type(None))
BOOL = _SimpleDType("BOOL", EngineType.BOOL, bool)
INT = _SimpleDType("INT", EngineType.INT, int)
FLOAT = _SimpleDType("FLOAT", EngineType.FLOAT, float)
STR = _SimpleDType("STR", EngineType.STRING, str)
BYTES = _SimpleDType("BYTES", EngineType.BYTES, bytes)
DATE_TIME_NAIVE = _SimpleDType(
    "DATE_TIME_NAIVE", EngineType.DATE_TIME_NAIVE, datetime.datetime
)
DATE_TIME_UTC = _SimpleDType("DATE_TIME_UTC", EngineType.DATE_TIME_UTC, datetime.datetime)
DURATION = _SimpleDType("DURATION", EngineType.DURATION, datetime.timedelta)
JSON = _SimpleDType("JSON", EngineType.JSON, _Json)
PY_OBJECT_WRAPPER = _SimpleDType(
    "PY_OBJECT_WRAPPER", EngineType.PY_OBJECT_WRAPPER, _PyObjectWrapper
)


class Optional_(DType):
    def __init__(self, wrapped: DType) -> None:
        if isinstance(wrapped, Optional_):
            wrapped = wrapped.wrapped
        self.wrapped = wrapped
        self._name = f"Optional({wrapped!r})"

    def to_engine(self) -> EngineType:
        return self.wrapped.to_engine()

    @property
    def typehint(self) -> Any:
        return Optional[self.wrapped.typehint]

    def is_optional(self) -> bool:
        return True

    def strip_optional(self) -> DType:
        return self.wrapped

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Optional_) and other.wrapped == self.wrapped

    def __hash__(self) -> int:
        return hash(("Optional", self.wrapped))


class Pointer(DType):
    """Pointer dtype, optionally carrying the target schema."""

    def __init__(self, target_schema: Any = None) -> None:
        self.target_schema = target_schema
        self._name = "POINTER" if target_schema is None else f"Pointer({target_schema})"

    def to_engine(self) -> EngineType:
        return EngineType.POINTER

    @property
    def typehint(self) -> Any:
        return _Pointer

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Pointer)

    def __hash__(self) -> int:
        return hash("Pointer")


POINTER = Pointer()


class Tuple(DType):
    def __init__(self, *args: DType) -> None:
        self.args = tuple(args)
        self._name = f"Tuple{self.args!r}"

    def to_engine(self) -> EngineType:
        return EngineType.TUPLE

    @property
    def typehint(self) -> Any:
        return typing.Tuple[tuple(a.typehint for a in self.args)] if self.args else tuple

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Tuple) and other.args == self.args

    def __hash__(self) -> int:
        return hash(("Tuple", self.args))


ANY_TUPLE = Tuple()


class List(DType):
    def __init__(self, wrapped: DType = ANY) -> None:
        self.wrapped = wrapped
        self._name = f"List({wrapped!r})"

    def to_engine(self) -> EngineType:
        return EngineType.LIST

    @property
    def typehint(self) -> Any:
        return typing.List[self.wrapped.typehint]

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, List) and other.wrapped == self.wrapped

    def __hash__(self) -> int:
        return hash(("List", self.wrapped))


class Array(DType):
    """N-dimensional numeric array dtype (ndarray on host, jax.Array on device)."""

    def __init__(self, n_dim: int | None = None, wrapped: DType = ANY) -> None:
        self.n_dim = n_dim
        self.wrapped = wrapped
        self._name = f"Array({n_dim}, {wrapped!r})"

    def to_engine(self) -> EngineType:
        return EngineType.ARRAY

    @property
    def typehint(self) -> Any:
        return np.ndarray

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, Array)
            and other.n_dim == self.n_dim
            and other.wrapped == self.wrapped
        )

    def __hash__(self) -> int:
        return hash(("Array", self.n_dim, self.wrapped))


ANY_ARRAY = Array()


class Callable(DType):
    def __init__(self, arg_types: Any = ..., return_type: DType = ANY) -> None:
        self.arg_types = arg_types
        self.return_type = return_type
        self._name = f"Callable(..., {return_type!r})"

    def to_engine(self) -> EngineType:
        return EngineType.ANY

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Callable) and other.return_type == self.return_type

    def __hash__(self) -> int:
        return hash(("Callable", self.return_type))


class Future(DType):
    """Result of an async UDF not yet awaited (reference dtype.Future)."""

    def __init__(self, wrapped: DType) -> None:
        self.wrapped = wrapped
        self._name = f"Future({wrapped!r})"

    def to_engine(self) -> EngineType:
        return EngineType.FUTURE

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Future) and other.wrapped == self.wrapped

    def __hash__(self) -> int:
        return hash(("Future", self.wrapped))


_SIMPLE_FROM_HINT: dict[Any, DType] = {
    Any: ANY,
    type(None): NONE,
    bool: BOOL,
    int: INT,
    float: FLOAT,
    str: STR,
    bytes: BYTES,
    datetime.datetime: DATE_TIME_NAIVE,
    datetime.timedelta: DURATION,
    np.ndarray: ANY_ARRAY,
    _Json: JSON,
    dict: JSON,
    _Pointer: POINTER,
    _PyObjectWrapper: PY_OBJECT_WRAPPER,
    np.int64: INT,
    np.float64: FLOAT,
    np.bool_: BOOL,
}


def wrap(input_type: Any) -> DType:
    """Convert a Python type annotation (or DType) to a DType."""
    if isinstance(input_type, DType):
        return input_type
    if input_type in _SIMPLE_FROM_HINT:
        return _SIMPLE_FROM_HINT[input_type]
    origin = get_origin(input_type)
    if origin is Union or origin is _types.UnionType:
        args = get_args(input_type)
        non_none = [a for a in args if a is not type(None)]
        has_none = len(non_none) != len(args)
        if len(non_none) == 1:
            inner = wrap(non_none[0])
        else:
            inner = ANY
        return Optional_(inner) if has_none else inner
    if origin in (tuple, typing.Tuple):
        args = get_args(input_type)
        if not args or args[-1] is Ellipsis:
            if args:
                return List(wrap(args[0]))
            return ANY_TUPLE
        return Tuple(*[wrap(a) for a in args])
    if origin in (list, typing.List):
        args = get_args(input_type)
        return List(wrap(args[0]) if args else ANY)
    if origin is np.ndarray:
        return ANY_ARRAY
    if origin is _Pointer:
        return POINTER
    if isinstance(input_type, type):
        # Schema classes become typed pointers; other classes opaque objects
        from pathway_tpu.internals import schema as schema_mod

        if issubclass(input_type, schema_mod.Schema):
            return Pointer(input_type)
        if issubclass(input_type, _Pointer):
            return POINTER
        return PY_OBJECT_WRAPPER
    return ANY


def dtype_of_value(value: Any) -> DType:
    """Runtime dtype of a concrete value."""
    et = engine_value.value_type_of(value)
    mapping = {
        engine_value.Type.NONE: NONE,
        engine_value.Type.BOOL: BOOL,
        engine_value.Type.INT: INT,
        engine_value.Type.FLOAT: FLOAT,
        engine_value.Type.POINTER: POINTER,
        engine_value.Type.STRING: STR,
        engine_value.Type.BYTES: BYTES,
        engine_value.Type.DATE_TIME_NAIVE: DATE_TIME_NAIVE,
        engine_value.Type.DATE_TIME_UTC: DATE_TIME_UTC,
        engine_value.Type.DURATION: DURATION,
        engine_value.Type.ARRAY: ANY_ARRAY,
        engine_value.Type.JSON: JSON,
        engine_value.Type.TUPLE: ANY_TUPLE,
        engine_value.Type.LIST: List(ANY),
        engine_value.Type.PY_OBJECT_WRAPPER: PY_OBJECT_WRAPPER,
    }
    return mapping.get(et, ANY)


_NUMERIC_ORDER = {BOOL: 0, INT: 1, FLOAT: 2}


def is_subclass(sub: DType, sup: DType) -> bool:
    """dtype lattice partial order."""
    if sup == ANY or sub == sup:
        return True
    if isinstance(sub, Optional_):
        return isinstance(sup, Optional_) and is_subclass(sub.wrapped, sup.wrapped)
    if isinstance(sup, Optional_):
        return sub == NONE or is_subclass(sub, sup.wrapped)
    if sub in _NUMERIC_ORDER and sup in _NUMERIC_ORDER:
        return _NUMERIC_ORDER[sub] <= _NUMERIC_ORDER[sup]
    if isinstance(sub, Tuple) and isinstance(sup, Tuple):
        if not sup.args:
            return True
        return len(sub.args) == len(sup.args) and all(
            is_subclass(a, b) for a, b in zip(sub.args, sup.args)
        )
    if isinstance(sub, Array) and isinstance(sup, Array):
        return sup.n_dim is None or sub.n_dim == sup.n_dim
    if isinstance(sub, Pointer) and isinstance(sup, Pointer):
        return True
    return False


def lca(a: DType, b: DType) -> DType:
    """Least common ancestor of two dtypes (used for if_else/concat typing)."""
    if a == b:
        return a
    if is_subclass(a, b):
        return b
    if is_subclass(b, a):
        return a
    a_opt, b_opt = a.is_optional() or a == NONE, b.is_optional() or b == NONE
    sa, sb = a.strip_optional(), b.strip_optional()
    if a == NONE:
        return Optional_(sb)
    if b == NONE:
        return Optional_(sa)
    inner: DType
    if sa in _NUMERIC_ORDER and sb in _NUMERIC_ORDER:
        inner = max(sa, sb, key=lambda d: _NUMERIC_ORDER[d])
    elif sa == sb:
        inner = sa
    else:
        inner = ANY
    if a_opt or b_opt:
        return Optional_(inner) if inner != ANY else ANY
    return inner


def normalize_value(value: Any, dtype: DType | None = None) -> Any:
    """Coerce a raw Python value to engine representation (e.g. dict→Json)."""
    if dtype is not None:
        target = dtype.strip_optional()
        if value is None:
            return None
        if target == JSON and not isinstance(value, _Json):
            return _Json(value)
        if target == FLOAT and isinstance(value, (int, np.integer)) and not isinstance(value, bool):
            return float(value)
        if target == INT and isinstance(value, np.integer):
            return int(value)
        if target == BOOL and isinstance(value, np.bool_):
            return bool(value)
        if target == STR and isinstance(value, str):
            return value
    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.floating):
        return float(value)
    if isinstance(value, np.bool_):
        return bool(value)
    if isinstance(value, dict):
        return _Json(value)
    return value
