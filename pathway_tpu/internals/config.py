"""Env-driven runtime configuration.

Reference: python/pathway/internals/config.py:58 PathwayConfig — the env
flags a deployment sets instead of code: persistence location/mode,
replay, license key, monitoring endpoint, worker topology, assertion and
typechecking switches. ``pw.run`` consults the active config for anything
not passed explicitly.
"""

from __future__ import annotations

import os
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any


def _env_field(name: str, default: str | None = None):
    return field(default_factory=lambda: os.environ.get(name, default))


def _env_bool_field(name: str, default: str = "false"):
    return field(
        default_factory=lambda: os.environ.get(name, default).lower()
        in ("1", "true", "yes", "on")
    )


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


@dataclass
class PathwayConfig:
    continue_after_replay: bool = _env_bool_field(
        "PATHWAY_CONTINUE_AFTER_REPLAY", "true"
    )
    ignore_asserts: bool = _env_bool_field("PATHWAY_IGNORE_ASSERTS")
    runtime_typechecking: bool = _env_bool_field("PATHWAY_RUNTIME_TYPECHECKING")
    persistence_mode: str = _env_field("PATHWAY_PERSISTENCE_MODE", "persisting")
    persistent_storage: str | None = _env_field("PATHWAY_PERSISTENT_STORAGE")
    replay_storage: str | None = _env_field("PATHWAY_REPLAY_STORAGE")
    snapshot_access: str | None = _env_field("PATHWAY_SNAPSHOT_ACCESS")
    license_key: str | None = _env_field("PATHWAY_LICENSE_KEY")
    monitoring_server: str | None = _env_field("PATHWAY_MONITORING_SERVER")
    terminate_on_error: bool = _env_bool_field(
        "PATHWAY_TERMINATE_ON_ERROR", "true"
    )
    process_id: str = _env_field("PATHWAY_PROCESS_ID", "0")
    threads: int = field(default_factory=lambda: _env_int("PATHWAY_THREADS", 1))
    processes: int = field(
        default_factory=lambda: _env_int("PATHWAY_PROCESSES", 1)
    )
    first_port: int = field(
        default_factory=lambda: _env_int("PATHWAY_FIRST_PORT", 10000)
    )

    @property
    def replay_config(self) -> Any:
        """Persistence Config implied by the env, or None (reference
        config.py:76 replay_config)."""
        storage = self.persistent_storage or self.replay_storage
        if not storage:
            return None
        from pathway_tpu.persistence import Backend, Config, PersistenceMode

        mode = {
            "persisting": PersistenceMode.PERSISTING,
            "operator_persisting": PersistenceMode.OPERATOR_PERSISTING,
            "udf_caching": PersistenceMode.UDF_CACHING,
        }.get(self.persistence_mode.lower(), PersistenceMode.PERSISTING)
        return Config(
            Backend.filesystem(storage),
            persistence_mode=mode,
            continue_after_replay=self.continue_after_replay,
        )


_pathway_config: ContextVar[PathwayConfig | None] = ContextVar(
    "pathway_config", default=None
)


def get_pathway_config() -> PathwayConfig:
    """Explicitly-set config if any, else a FRESH read of the environment —
    env changes between runs must take effect (the reference re-reads env
    per run too)."""
    config = _pathway_config.get()
    if config is None:
        return PathwayConfig()
    return config


def set_pathway_config(config: PathwayConfig | None) -> None:
    _pathway_config.set(config)


def set_license_key(key: str | None) -> None:
    config = get_pathway_config()
    config.license_key = key
    set_pathway_config(config)
