"""Error-log tables: ``pw.global_error_log()`` / ``pw.local_error_log()``.

Reference: internals/errors.py + engine error logs (dataflow.rs:3980,
set_error_log python_api.rs:3168): rows that fail evaluation poison to
ERROR and the message lands in an error-log table — the global one by
default, or a local one for operators built inside a
``with pw.local_error_log() as log:`` block.
"""

from __future__ import annotations

import contextlib
import itertools
from typing import Any, Iterator

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table, TableSpec

_log_ids = itertools.count(1)
_active_log_ids: list[int] = []


def current_log_id() -> int | None:
    """The local error log in scope at Table-construction time (None =
    global). Consulted by Table.__init__."""
    return _active_log_ids[-1] if _active_log_ids else None


def _log_table(log_id: int | None) -> Table:
    return Table(
        TableSpec("error_log", [], {"log_id": log_id}),
        ["message"],
        {"message": dt.STR},
    )


def global_error_log() -> Table:
    """All error messages of the run (reference pw.global_error_log)."""
    return _log_table(None)


@contextlib.contextmanager
def local_error_log() -> Iterator[Table]:
    """Errors of operators built inside the block route to the yielded
    table instead of the global log."""
    log_id = next(_log_ids)
    _active_log_ids.append(log_id)
    try:
        yield _log_table(log_id)
    finally:
        _active_log_ids.pop()
