"""Notebook interactive layer (reference: python/pathway/internals/
interactive.py + stdlib/viz/table_viz.py — live-updating tables in
IPython, with the run pumping in the background so cells return).

Surface:

- :class:`LiveTable` — subscribes to a table and re-renders a snapshot on
  every commit. In an IPython kernel it renders through a display handle
  (``display(display_id=True)`` + ``handle.update``) as an HTML table;
  outside IPython it falls back to the rich console renderer the viz
  module already provides.
- :func:`enable_interactive_mode` — starts ``pw.run`` on a background
  thread so a notebook cell returns immediately while LiveTables keep
  updating; :func:`stop_interactive_mode` joins it.
- ``Table._repr_html_`` (installed by this module's import through
  pathway_tpu/__init__) — schema-shaped HTML so bare table expressions
  render usefully in notebooks without running the graph.
"""

from __future__ import annotations

import html as _html
import threading
from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.internals.viz_model import RowSnapshot

_interactive: dict[str, Any] = {"thread": None, "error": None}


def _in_ipython() -> bool:
    try:
        from IPython import get_ipython

        return get_ipython() is not None
    except ImportError:
        return False


class LiveTable:
    """A live-updating view of ``table``: one row per key, revised as
    commits land.

    ``display_handle``: anything with ``.update(obj)`` — defaults to an
    IPython display handle in a kernel; injectable for tests/headless."""

    def __init__(
        self,
        table: Table,
        *,
        max_rows: int = 20,
        display_handle: Any = None,
    ) -> None:
        from pathway_tpu.io import subscribe as _subscribe

        self._snapshot = RowSnapshot(table.column_names(), max_rows)
        self.n_commits = 0
        self._handle = display_handle
        _subscribe(
            table,
            on_change=self._on_change,
            on_time_end=self._on_time_end,
            on_end=self._on_end,
        )

    # -- engine callbacks -----------------------------------------------------

    def _on_change(self, key, row, time, is_addition):
        self._snapshot.apply(key, row, is_addition)

    def _on_time_end(self, time):
        self.n_commits += 1
        self._render()

    def _on_end(self):
        self._render()

    @property
    def rows(self) -> dict:
        return self._snapshot.rows

    @property
    def column_names(self) -> list:
        return self._snapshot.column_names

    # -- rendering ------------------------------------------------------------

    def _repr_html_(self) -> str:
        snap = self._snapshot
        head = "".join(
            f"<th>{_html.escape(str(n))}</th>" for n in snap.column_names
        )
        body = []
        for row in snap.visible():
            cells = "".join(
                f"<td>{_html.escape(str(v))}</td>" for v in row
            )
            body.append(f"<tr>{cells}</tr>")
        extra = (
            f"<caption>... {snap.overflow} more rows</caption>"
            if snap.overflow
            else ""
        )
        return (
            f"<table>{extra}<thead><tr>{head}</tr></thead>"
            f"<tbody>{''.join(body)}</tbody></table>"
            f"<small>{len(snap.rows)} rows · commit {self.n_commits}"
            f"</small>"
        )

    def _render(self) -> None:
        if self._handle is None and _in_ipython():
            from IPython.display import HTML, display

            self._handle = display(
                HTML(self._repr_html_()), display_id=True
            )
            return
        if self._handle is not None:
            try:
                from IPython.display import HTML

                self._handle.update(HTML(self._repr_html_()))
            except ImportError:
                self._handle.update(self._repr_html_())


def show(table: Table, **kwargs: Any) -> LiveTable | None:
    """Notebook: a LiveTable; console: the rich live renderer
    (stdlib/viz, which accepts the same kwargs it documents)."""
    if _in_ipython() or kwargs.get("display_handle") is not None:
        return LiveTable(table, **kwargs)
    from pathway_tpu.stdlib.viz import show as console_show

    console_show(table, **kwargs)
    return None


def enable_interactive_mode(**run_kwargs: Any) -> threading.Thread:
    """Start ``pw.run`` on a background thread (reference interactive
    mode: cells return while the dataflow keeps streaming)."""
    if _interactive["thread"] is not None and _interactive["thread"].is_alive():
        raise RuntimeError("interactive mode already running")
    if _interactive["error"] is not None:
        # a previous background run died and was never joined — surface
        # its failure instead of silently discarding it
        error = _interactive["error"]
        _interactive["error"] = None
        raise RuntimeError(
            "previous interactive run failed; fix and retry"
        ) from error
    from pathway_tpu.internals import parse_graph

    def runner():
        try:
            parse_graph.run(**run_kwargs)
        except Exception as exc:  # noqa: BLE001 — surfaced on stop/join
            _interactive["error"] = exc

    thread = threading.Thread(
        target=runner, name="pw-interactive", daemon=True
    )
    thread.start()
    _interactive["thread"] = thread
    return thread


def stop_interactive_mode(timeout: float | None = 30.0) -> None:
    """Join the background run (it ends when every connector finishes);
    re-raises any error the run hit."""
    thread = _interactive["thread"]
    if thread is None:
        return
    thread.join(timeout=timeout)
    if thread.is_alive():
        # the run is still going (endless connector?) — keep the handle
        # so a retry can join it; starting a second run stays blocked
        raise TimeoutError(
            f"interactive run still alive after {timeout}s; its "
            "connectors have not finished"
        )
    _interactive["thread"] = None
    if _interactive["error"] is not None:
        error = _interactive["error"]
        _interactive["error"] = None
        raise error


def _table_repr_html(table: Table) -> str:
    """Schema-shaped notebook repr (no graph execution)."""
    dtypes = table._dtypes
    rows = "".join(
        f"<tr><td>{_html.escape(str(n))}</td>"
        f"<td><code>{_html.escape(str(dtypes.get(n)))}</code></td></tr>"
        for n in table.column_names()
    )
    return (
        f"<b>pw.Table</b> <code>{_html.escape(getattr(table, '_name', ''))}</code>"
        f"<table><thead><tr><th>column</th><th>dtype</th></tr></thead>"
        f"<tbody>{rows}</tbody></table>"
    )


# bare table expressions render their schema in notebooks
Table._repr_html_ = _table_repr_html  # type: ignore[attr-defined]
