"""Expression desugaring: resolve pw.this/pw.left/pw.right placeholders.

(reference: python/pathway/internals/desugaring.py, 353 LoC — here a compact
structural substitution over the expression tree.)
"""

from __future__ import annotations

import copy
from typing import TYPE_CHECKING, Any, Callable

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.thisclass import ThisColumnReference, left, right, this

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table

_CHILD_ATTRS = (
    "_left",
    "_right",
    "_arg",
    "_cond",
    "_then",
    "_otherwise",
    "_value",
    "_fallback",
    "_index",
    "_default",
    "_instance",
)
_CHILD_LIST_ATTRS = ("_args", "_deps")
_CHILD_DICT_ATTRS = ("_kwargs",)


def substitute(
    expression: ColumnExpression,
    replace: Callable[[ColumnExpression], ColumnExpression | None],
) -> ColumnExpression:
    """Rebuild an expression tree, replacing nodes where ``replace`` returns
    a non-None substitute."""
    replaced = replace(expression)
    if replaced is not None:
        return replaced
    clone: ColumnExpression | None = None

    def ensure_clone() -> ColumnExpression:
        nonlocal clone
        if clone is None:
            clone = copy.copy(expression)
        return clone

    for attr in _CHILD_ATTRS:
        child = getattr(expression, attr, None)
        if isinstance(child, ColumnExpression):
            new_child = substitute(child, replace)
            if new_child is not child:
                setattr(ensure_clone(), attr, new_child)
    for attr in _CHILD_LIST_ATTRS:
        children = getattr(expression, attr, None)
        if isinstance(children, list):
            new_children = [
                substitute(c, replace) if isinstance(c, ColumnExpression) else c
                for c in children
            ]
            if any(a is not b for a, b in zip(children, new_children)):
                setattr(ensure_clone(), attr, new_children)
    for attr in _CHILD_DICT_ATTRS:
        children = getattr(expression, attr, None)
        if isinstance(children, dict):
            new_dict = {
                k: substitute(c, replace) if isinstance(c, ColumnExpression) else c
                for k, c in children.items()
            }
            if any(new_dict[k] is not children[k] for k in children):
                setattr(ensure_clone(), attr, new_dict)
    return clone if clone is not None else expression


def resolve_this(expression: Any, table: "Table") -> ColumnExpression:
    """Bind ``pw.this`` placeholders (and bare column names) to ``table``."""
    from pathway_tpu.internals.thisclass import DelayedIxRefColumn

    if isinstance(expression, str):
        return ColumnReference(table, expression)
    expression = expr_mod.wrap_expression(expression)

    def replace(node: ColumnExpression) -> ColumnExpression | None:
        if isinstance(node, DelayedIxRefColumn):
            if node._owner is not this:
                raise ValueError(f"{node!r} cannot be used here; use pw.this")
            return ColumnReference(
                _delayed_ix_table(node, table), node.name
            )
        if isinstance(node, ThisColumnReference):
            if node._owner is not this:
                raise ValueError(f"{node!r} cannot be used here; use pw.this")
            return ColumnReference(table, node.name)
        return None

    return substitute(expression, replace)


def _delayed_ix_table(node: "ColumnExpression", table: "Table") -> "Table":
    """The bound table indexes ITSELF by the key expressions, with
    itself as the keys context (reference delayed ix_ref). Identical
    (args, kwargs) chains reuse ONE ix table per bound table, so
    selecting several columns from the same pw.this.ix_ref(keys) runs a
    single index lookup."""
    cache = table.__dict__.setdefault("_pw_ix_ref_cache", {})
    key = repr((node._ix_args, node._ix_kwargs))
    ix_table = cache.get(key)
    if ix_table is None:
        ix_table = table.ix_ref(
            *node._ix_args, context=table, **node._ix_kwargs
        )
        cache[key] = ix_table
    return ix_table


def resolve_join_sides(
    expression: Any, left_table: "Table", right_table: "Table"
) -> ColumnExpression:
    """Bind pw.left/pw.right (and pw.this → left) in a join context."""
    from pathway_tpu.internals.thisclass import DelayedIxRefColumn

    expression = expr_mod.wrap_expression(expression)

    def replace(node: ColumnExpression) -> ColumnExpression | None:
        if isinstance(node, DelayedIxRefColumn):
            # pw.this binds the left side in a join context, matching
            # the ThisColumnReference rule below
            side = (
                right_table if node._owner is right else left_table
            )
            return ColumnReference(
                _delayed_ix_table(node, side), node.name
            )
        if isinstance(node, ThisColumnReference):
            if node._owner is left or node._owner is this:
                return ColumnReference(left_table, node.name)
            if node._owner is right:
                return ColumnReference(right_table, node.name)
        return None

    return substitute(expression, replace)


def resolve_side(expression: Any, table: "Table", side: str) -> ColumnExpression:
    """Bind ``pw.this`` AND the matching side sentinel (``pw.left`` when
    side='left', ``pw.right`` when side='right') to ``table`` — temporal
    joins take per-side time expressions where the reference accepts either
    spelling (interval/asof/window join signatures)."""
    if isinstance(expression, str):
        return ColumnReference(table, expression)
    expression = expr_mod.wrap_expression(expression)
    sided = left if side == "left" else right

    def replace(node: ColumnExpression) -> ColumnExpression | None:
        if isinstance(node, ThisColumnReference):
            if node._owner is this or node._owner is sided:
                return ColumnReference(table, node.name)
            raise ValueError(
                f"{node!r} cannot be used for the {side} side of this join"
            )
        return None

    return substitute(expression, replace)
