"""``pw.this`` / ``pw.left`` / ``pw.right`` placeholders.

New implementation of the reference's thisclass
(reference: python/pathway/internals/thisclass.py, 313 LoC). Placeholders are
resolved eagerly by the consuming method (``select``/``filter``/``join``...)
via :mod:`pathway_tpu.internals.desugaring`.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression


class ThisColumnReference(ColumnExpression):
    """``pw.this.colname`` — bound to a concrete table at call time."""

    def __init__(self, owner: "ThisMetaclass", name: str) -> None:
        self._owner = owner
        self._name = name
        self._dtype = dt.ANY

    @property
    def name(self) -> str:
        return self._name

    def _dependencies(self):
        raise RuntimeError(
            f"pw.{self._owner._side}.{self._name} used outside of a table context"
        )

    def __repr__(self) -> str:
        return f"pw.{self._owner._side}.{self._name}"


class DelayedIxRefColumn(ColumnExpression):
    """``pw.this.ix_ref(*keys).column`` — the whole chain resolves when
    the consuming select/reduce binds pw.this to a concrete table: the
    table indexes ITSELF by the key expressions (reference delayed
    ix_ref, thisclass.py ix handling)."""

    def __init__(
        self, owner: "ThisMetaclass", args: tuple, kwargs: dict, name: str
    ) -> None:
        self._owner = owner
        self._ix_args = args
        self._ix_kwargs = kwargs
        self._name = name
        self._dtype = dt.ANY

    @property
    def name(self) -> str:
        return self._name

    def _dependencies(self):
        raise RuntimeError(
            f"pw.{self._owner._side}.ix_ref(...) used outside of a table "
            f"context"
        )

    def __repr__(self) -> str:
        return f"pw.{self._owner._side}.ix_ref(...).{self._name}"


class DelayedIxRef:
    """Result of ``pw.this.ix_ref(...)`` — column access yields the
    delayed expression."""

    def __init__(
        self, owner: "ThisMetaclass", args: tuple, kwargs: dict
    ) -> None:
        self._owner = owner
        self._args = args
        self._kwargs = kwargs

    def __getattr__(self, name: str) -> DelayedIxRefColumn:
        if name.startswith("_"):
            raise AttributeError(name)
        return DelayedIxRefColumn(self._owner, self._args, self._kwargs, name)

    def __getitem__(self, name: str) -> DelayedIxRefColumn:
        return DelayedIxRefColumn(self._owner, self._args, self._kwargs, name)


class ThisStar:
    """``*pw.this`` marker: select expands it to every column of the
    bound table (reference thisclass __iter__ mock, thisclass.py:103)."""

    def __init__(self, owner: "ThisMetaclass") -> None:
        self._owner = owner

    def __repr__(self) -> str:
        return f"*pw.{self._owner._side}"


class ThisMetaclass:
    def __init__(self, side: str) -> None:
        self._side = side

    def ix_ref(self, *args: Any, **kwargs: Any) -> DelayedIxRef:
        return DelayedIxRef(self, args, kwargs)

    def __getattr__(self, name: str) -> ThisColumnReference:
        # engine-provided columns (_pw_window_start, _pw_instance, ...) are
        # addressable by attribute, like the reference (_window.py usage);
        # other underscore names stay AttributeError so copy/pickle probes
        # of the sentinel don't manufacture ghost columns
        if name.startswith("_") and not name.startswith("_pw_"):
            raise AttributeError(name)
        return ThisColumnReference(self, name)

    def __getitem__(self, name: str) -> ThisColumnReference:
        if not isinstance(name, str):
            # guards the implicit-iteration protocol: without this,
            # ``*pw.this`` would loop forever on integer indices
            raise TypeError(f"pw.{self._side}[...] needs a column name")
        return ThisColumnReference(self, name)

    def __iter__(self):
        return iter([ThisStar(self)])

    def __repr__(self) -> str:
        return f"pw.{self._side}"


this = ThisMetaclass("this")
left = ThisMetaclass("left")
right = ThisMetaclass("right")


def is_this_ref(value: Any) -> bool:
    return isinstance(value, ThisColumnReference)
