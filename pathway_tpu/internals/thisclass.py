"""``pw.this`` / ``pw.left`` / ``pw.right`` placeholders.

New implementation of the reference's thisclass
(reference: python/pathway/internals/thisclass.py, 313 LoC). Placeholders are
resolved eagerly by the consuming method (``select``/``filter``/``join``...)
via :mod:`pathway_tpu.internals.desugaring`.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression


class ThisColumnReference(ColumnExpression):
    """``pw.this.colname`` — bound to a concrete table at call time."""

    def __init__(self, owner: "ThisMetaclass", name: str) -> None:
        self._owner = owner
        self._name = name
        self._dtype = dt.ANY

    @property
    def name(self) -> str:
        return self._name

    def _dependencies(self):
        raise RuntimeError(
            f"pw.{self._owner._side}.{self._name} used outside of a table context"
        )

    def __repr__(self) -> str:
        return f"pw.{self._owner._side}.{self._name}"


class ThisMetaclass:
    def __init__(self, side: str) -> None:
        self._side = side

    def __getattr__(self, name: str) -> ThisColumnReference:
        # engine-provided columns (_pw_window_start, _pw_instance, ...) are
        # addressable by attribute, like the reference (_window.py usage);
        # other underscore names stay AttributeError so copy/pickle probes
        # of the sentinel don't manufacture ghost columns
        if name.startswith("_") and not name.startswith("_pw_"):
            raise AttributeError(name)
        return ThisColumnReference(self, name)

    def __getitem__(self, name: str) -> ThisColumnReference:
        return ThisColumnReference(self, name)

    def __repr__(self) -> str:
        return f"pw.{self._side}"


this = ThisMetaclass("this")
left = ThisMetaclass("left")
right = ThisMetaclass("right")


def is_this_ref(value: Any) -> bool:
    return isinstance(value, ThisColumnReference)
