"""Declarative app templates: YAML with ``!pw...`` object tags + ``$var``
variables.

Reference: python/pathway/internals/yaml_loader.py:214 load_yaml — the RAG
app templates instantiate embedders/stores/servers straight from YAML:

    $llm: !pw.xpacks.llm.llms.TpuPipelineChat
      model: tiny
    question_answerer: !pw.xpacks.llm.question_answering.BaseRAGQuestionAnswerer
      llm: $llm
      indexer: $document_store

Tags resolve against this package (``pw.`` →  ``pathway_tpu.``) or any
importable dotted path; ``$name`` keys declare variables, ``$name`` values
reference them (each constructed exactly once).
"""

from __future__ import annotations

import importlib
import io
from dataclasses import dataclass, field
from typing import Any

import yaml


@dataclass(frozen=True)
class Variable:
    name: str

    def __str__(self) -> str:
        return f"${self.name}"


@dataclass(eq=False)
class Value:
    constructor: Any
    kwargs: Any
    constructed: bool = False
    value: Any = None


def import_object(path: str) -> Any:
    """``pw.x.y.Z`` / ``pathway_tpu.x.y.Z`` / any importable dotted path."""
    if path.startswith("pw.") or path.startswith("pw:"):
        path = "pathway_tpu." + path[3:]
    path = path.replace(":", ".")
    parts = path.split(".")
    for split in range(len(parts), 0, -1):
        module_name = ".".join(parts[:split])
        try:
            obj: Any = importlib.import_module(module_name)
        except ImportError:
            continue
        try:
            for attr in parts[split:]:
                obj = getattr(obj, attr)
        except AttributeError:
            break
        return obj
    raise ValueError(f"cannot import {path!r}")


class PathwayYamlLoader(yaml.SafeLoader):
    pass


def _construct_variable(loader: PathwayYamlLoader, node: yaml.Node) -> Variable:
    name = loader.construct_yaml_str(node)
    if not name.startswith("$"):
        raise yaml.YAMLError(f"variable {name!r} must start with '$'")
    return Variable(name[1:])


def _construct_value(
    loader: PathwayYamlLoader, tag_suffix: str, node: yaml.Node
) -> Value:
    constructor = import_object(tag_suffix)
    if isinstance(node, yaml.MappingNode):
        kwargs = loader.construct_mapping(node, deep=True)
    elif isinstance(node, yaml.ScalarNode) and not node.value:
        kwargs = {}
    else:
        raise yaml.YAMLError(
            f"!{tag_suffix} expects a mapping of keyword arguments"
        )
    if not callable(constructor):
        if kwargs:
            raise yaml.YAMLError(
                f"{tag_suffix!r} is not callable but was given arguments"
            )
        return Value(None, {}, constructed=True, value=constructor)
    return Value(constructor, kwargs)


PathwayYamlLoader.add_implicit_resolver(
    "!pw_variable", __import__("re").compile(r"^\$[A-Za-z_][A-Za-z0-9_]*$"), "$"
)
PathwayYamlLoader.add_constructor("!pw_variable", _construct_variable)
# any "!dotted.path" tag constructs the imported object (reference
# import_object yaml_loader.py:46 — pw.* plus arbitrary importable paths)
PathwayYamlLoader.add_multi_constructor(
    "!", lambda loader, suffix, node: _construct_value(loader, suffix, node)
)


@dataclass
class _Resolver:
    variables: dict[Variable, Any] = field(default_factory=dict)
    used: set = field(default_factory=set)

    def resolve(self, obj: Any) -> Any:
        if isinstance(obj, Variable):
            if obj not in self.variables:
                raise ValueError(f"undefined variable {obj}")
            self.used.add(obj)
            return self.resolve(self.variables[obj])
        if isinstance(obj, Value):
            if not obj.constructed:
                kwargs = {
                    k: self.resolve(v) for k, v in obj.kwargs.items()
                }
                obj.value = obj.constructor(**kwargs)
                obj.constructed = True
            return obj.value
        if isinstance(obj, dict):
            declared = [k for k in obj if isinstance(k, Variable)]
            for var in declared:
                self.variables[var] = obj.pop(var)
            return {k: self.resolve(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [self.resolve(v) for v in obj]
        return obj


def load_yaml(stream: "str | bytes | io.IOBase") -> Any:
    parsed = yaml.load(stream, PathwayYamlLoader)
    return _Resolver().resolve(parsed)
