"""JoinResult — `t1.join(t2, t1.a == t2.b).select(...)`.

(reference: python/pathway/internals/joins.py, 1,422 LoC)
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.desugaring import resolve_join_sides
from pathway_tpu.internals.expression import (
    BinaryOpExpression,
    ColumnExpression,
    ColumnReference,
)

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class JoinResult:
    """Lazy join; materialized by ``.select`` (or ``.reduce`` after groupby)."""

    def __init__(
        self,
        left: "Table",
        right: "Table",
        on: tuple,
        how: str,
        id: Any = None,  # noqa: A002
    ) -> None:
        self._left = left
        self._right = right
        self._how = how
        self._id = id
        self._on: list[tuple[ColumnExpression, ColumnExpression]] = []
        for cond in on:
            resolved = resolve_join_sides(cond, left, right)
            if not (
                isinstance(resolved, BinaryOpExpression) and resolved._op == "=="
            ):
                raise ValueError(
                    f"join conditions must be equalities (left_col == right_col), got {cond!r}"
                )
            lexpr, rexpr = resolved._left, resolved._right
            if self._side_of(lexpr) == "right" or self._side_of(rexpr) == "left":
                lexpr, rexpr = rexpr, lexpr
            self._on.append((lexpr, rexpr))

    def _side_of(self, expression: ColumnExpression) -> str | None:
        tables = {ref.table._id for ref in expression._dependencies()}
        if tables <= self._reachable_ids(self._left):
            return "left"
        if tables <= self._reachable_ids(self._right):
            return "right"
        return None

    @staticmethod
    def _reachable_ids(table: "Table") -> set[int]:
        return {table._id}

    def select(self, *args: Any, **kwargs: Any) -> "Table":
        from pathway_tpu.internals.table import Table, TableSpec

        from pathway_tpu.internals.thisclass import ThisStar, left, right

        exprs: dict[str, ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, ThisStar):
                # *pw.left / *pw.right expand that side; *pw.this takes
                # both (left first; duplicate names keep the left column)
                sides = (
                    [self._left]
                    if arg._owner is left
                    else [self._right]
                    if arg._owner is right
                    else [self._left, self._right]
                )
                for side in sides:
                    for n in side.column_names():
                        exprs.setdefault(n, ColumnReference(side, n))
                continue
            resolved = resolve_join_sides(arg, self._left, self._right)
            if not isinstance(resolved, ColumnReference):
                raise ValueError("positional join-select arguments must be column refs")
            exprs[resolved.name] = resolved
        for name, value in kwargs.items():
            exprs[name] = resolve_join_sides(value, self._left, self._right)
        dtypes = {n: e._dtype for n, e in exprs.items()}
        id_spec = None
        if self._id is not None:
            resolved_id = resolve_join_sides(self._id, self._left, self._right)
            if not isinstance(resolved_id, ColumnReference):
                raise ValueError(
                    "join id= must be a column reference (a side's .id or "
                    "a pointer column)"
                )
            if resolved_id.table is self._left:
                side, side_table = "left", self._left
            elif resolved_id.table is self._right:
                side, side_table = "right", self._right
            else:
                raise ValueError(
                    "join id= must reference one of the joined tables"
                )
            if resolved_id.name == "id":
                id_spec = (side, None)
            else:
                col_dtype = side_table._dtypes.get(resolved_id.name)
                base = (
                    col_dtype.strip_optional()
                    if col_dtype is not None
                    else None
                )
                if not (
                    col_dtype is None
                    or col_dtype == dt.ANY
                    or isinstance(base, dt.Pointer)
                ):
                    raise ValueError(
                        f"join id= column {resolved_id.name!r} must be "
                        f"pointer-typed, got {col_dtype}"
                    )
                id_spec = (side, resolved_id.name)
        return Table(
            TableSpec(
                "join_select",
                [self._left, self._right],
                {
                    "on": self._on,
                    "how": self._how,
                    "exprs": exprs,
                    "id_spec": id_spec,
                },
            ),
            list(exprs.keys()),
            dtypes,
        )
