"""JMESPath-subset evaluator for metadata filters.

The reference filters document metadata with JMESPath plus custom
functions globmatch/to_string (src/external_integration/mod.rs:200-373 and
stdlib/ml/classifiers/_knn_lsh.py:125-133). No jmespath package ships in
this image, so this is a native evaluator of the subset those filters use:

- dotted field paths (``owner``, ``meta.path``), raw ``'strings'``,
  backtick JSON literals, numbers, booleans, null
- comparisons ``== != < <= > >=``, boolean ``&& || !``, parentheses
- functions: ``globmatch(pattern, path)`` (with ``**`` crossing ``/``),
  ``contains(haystack, needle)``, ``starts_with``, ``ends_with``,
  ``to_string``
"""

from __future__ import annotations

import fnmatch
import json
import re
from typing import Any

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<lparen>\()|(?P<rparen>\))|(?P<comma>,)|
        (?P<and>&&)|(?P<or>\|\|)|
        (?P<cmp>==|!=|<=|>=|<|>)|(?P<not>!)|
        (?P<raw>'(?:[^'\\]|\\.)*')|
        (?P<json>`(?:[^`\\]|\\.)*`)|
        (?P<number>-?\d+(?:\.\d+)?)|
        (?P<ident>[A-Za-z_][A-Za-z0-9_]*)|
        (?P<dot>\.)
    )""",
    re.VERBOSE,
)

_FUNCTIONS = ("globmatch", "contains", "starts_with", "ends_with", "to_string")


class JMESPathError(ValueError):
    pass


def _globmatch_parts(pattern: list, path: list) -> bool:
    if not pattern:
        return not path
    if pattern[0] == "**":
        if _globmatch_parts(pattern[1:], path):
            return True
        return bool(path) and _globmatch_parts(pattern, path[1:])
    if not path:
        return False
    if fnmatch.fnmatch(path[0], pattern[0]):
        return _globmatch_parts(pattern[1:], path[1:])
    return False


def globmatch(pattern: str, path: str) -> bool:
    """fnmatch at every /-level; ``**`` spans levels (reference
    _knn_lsh.py:101-122 _globmatch)."""
    return _globmatch_parts(str(pattern).split("/"), str(path).split("/"))


def _tokenize(text: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise JMESPathError(f"bad filter syntax at {text[pos:]!r}")
        pos = m.end()
        for kind, value in m.groupdict().items():
            if value is not None:
                out.append((kind, value))
                break
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]], doc: Any) -> None:
        self.tokens = tokens
        self.i = 0
        self.doc = doc

    def peek(self) -> tuple[str, str]:
        return self.tokens[self.i]

    def next(self) -> tuple[str, str]:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect(self, kind: str) -> str:
        k, v = self.next()
        if k != kind:
            raise JMESPathError(f"expected {kind}, got {v!r}")
        return v

    def or_expr(self) -> Any:
        left = self.and_expr()
        while self.peek()[0] == "or":
            self.next()
            right = self.and_expr()
            left = _truthy(left) or _truthy(right)
        return left

    def and_expr(self) -> Any:
        left = self.not_expr()
        while self.peek()[0] == "and":
            self.next()
            right = self.not_expr()
            left = _truthy(left) and _truthy(right)
        return left

    def not_expr(self) -> Any:
        if self.peek()[0] == "not":
            self.next()
            return not _truthy(self.not_expr())
        return self.comparison()

    def comparison(self) -> Any:
        left = self.operand()
        if self.peek()[0] == "cmp":
            op = self.next()[1]
            right = self.operand()
            try:
                if op == "==":
                    return left == right
                if op == "!=":
                    return left != right
                if left is None or right is None:
                    return False
                if op == "<":
                    return left < right
                if op == "<=":
                    return left <= right
                if op == ">":
                    return left > right
                if op == ">=":
                    return left >= right
            except TypeError:
                return False
        return left

    def operand(self) -> Any:
        kind, value = self.next()
        if kind == "lparen":
            out = self.or_expr()
            self.expect("rparen")
            return out
        if kind == "raw":
            return value[1:-1].replace("\\'", "'")
        if kind == "json":
            return json.loads(value[1:-1])
        if kind == "number":
            return float(value) if "." in value else int(value)
        if kind == "ident":
            if value in _FUNCTIONS and self.peek()[0] == "lparen":
                return self.call(value)
            if value == "true":
                return True
            if value == "false":
                return False
            if value == "null":
                return None
            return self.path(value)
        raise JMESPathError(f"unexpected token {value!r}")

    def call(self, name: str) -> Any:
        self.expect("lparen")
        args = [self.or_expr()]
        while self.peek()[0] == "comma":
            self.next()
            args.append(self.or_expr())
        self.expect("rparen")
        if name == "globmatch":
            return globmatch(args[0], args[1])
        if name == "contains":
            hay, needle = args
            if hay is None:
                return False
            return needle in hay
        if name == "starts_with":
            return str(args[0]).startswith(str(args[1]))
        if name == "ends_with":
            return str(args[0]).endswith(str(args[1]))
        if name == "to_string":
            v = args[0]
            return v if isinstance(v, str) else json.dumps(v)
        raise JMESPathError(f"unknown function {name}")

    def path(self, first: str) -> Any:
        node = self.doc
        parts = [first]
        while self.peek()[0] == "dot":
            self.next()
            parts.append(self.expect("ident"))
        for part in parts:
            if isinstance(node, dict):
                node = node.get(part)
            else:
                return None
        return node


def _truthy(v: Any) -> bool:
    # JMESPath truthiness: null / false / empty string / empty collection
    if v is None or v is False:
        return False
    if isinstance(v, (str, list, dict, tuple)) and len(v) == 0:
        return False
    return True


def search(expression: str, document: Any) -> Any:
    """Evaluate the filter expression against a (dict-like) document."""
    parser = _Parser(_tokenize(expression), document)
    out = parser.or_expr()
    if parser.peek()[0] != "eof":
        raise JMESPathError(
            f"trailing tokens in filter: {parser.peek()[1]!r}"
        )
    return out
