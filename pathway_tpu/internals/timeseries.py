"""Metrics history ring + SLO sentinel: the trend side of the plane.

The registry (internals/metrics.py) is point-in-time; this module keeps
a bounded, down-sampling history of every registry family so questions
like "how has queue depth trended over the last five minutes" — the
signals the ROADMAP item-4 autoscaler loop consumes — have an answer:

- :class:`TimeSeriesStore` — per-series tiered rings (raw → 1s → 10s),
  each tier a fixed-length deque, plus a global series-count cap, so
  total memory is a hard constant regardless of run length or label
  cardinality.  Histogram families are stored as derived scalar tracks
  (``stat`` label: count / sum / p50 / p95 / p99) so bucket explosion
  never hits the ring.
- :class:`TelemetryLoop` — the daemon recorder: every tick it snapshots
  the local registry (plus, on a mesh leader, every piggybacked
  follower snapshot) into the store under ``worker`` labels and runs
  the sentinel.  Served as ``/timeseries?family=...&window=...`` on the
  existing monitoring port and rendered by ``cli stats --watch``.
- :class:`SloSentinel` — declarative SLOs (latency burn-rate,
  queue-depth ceiling, staleness bound, throughput floor) evaluated
  continuously against the ring; every evaluation sets the
  ``pathway_slo_burn_ratio`` gauge and a breach crossing records a
  structured ``slo_burn`` event in the PR-5 flight recorder — the
  machine-checkable "did we violate SLOs during failover" verdict.

Stale ``worker=`` label sets are pruned on rescale/failover/recovery
via :meth:`TimeSeriesStore.prune_workers` (hooked from the same
``prune_mesh_metrics`` path that prunes the /metrics exposition), so
``cli stats --watch`` never shows dead workers.
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
from collections import deque
from typing import Any, Iterable

from pathway_tpu.internals import metrics as _metrics

__all__ = [
    "SeriesRing",
    "TimeSeriesStore",
    "SloSpec",
    "SloSentinel",
    "TelemetryLoop",
    "STORE",
    "SENTINEL",
    "start_loop",
    "stop_loop",
]

#: down-sampling tier periods, seconds (raw tier records every tick)
MID_PERIOD = 1.0
COARSE_PERIOD = 10.0

#: per-tier point caps — with the series cap these fix the memory
#: ceiling: MAX_SERIES * (RAW + MID + COARSE) points, ~3 floats each
RAW_POINTS = 240
MID_POINTS = 360
COARSE_POINTS = 360

_TRUTHY = ("1", "true", "yes")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class SeriesRing:
    """One scalar series: three fixed-length tiers.  Every append lands
    in the raw tier; a point also promotes to the 1s / 10s tier when
    that tier's period has elapsed — so a window query older than the
    raw span still has (coarser) coverage."""

    __slots__ = ("raw", "mid", "coarse", "_last_mid", "_last_coarse")

    def __init__(
        self,
        raw_points: int = RAW_POINTS,
        mid_points: int = MID_POINTS,
        coarse_points: int = COARSE_POINTS,
    ) -> None:
        self.raw: deque = deque(maxlen=raw_points)
        self.mid: deque = deque(maxlen=mid_points)
        self.coarse: deque = deque(maxlen=coarse_points)
        self._last_mid = float("-inf")
        self._last_coarse = float("-inf")

    def append(self, t: float, v: float) -> None:
        self.raw.append((t, v))
        if t - self._last_mid >= MID_PERIOD:
            self.mid.append((t, v))
            self._last_mid = t
        if t - self._last_coarse >= COARSE_PERIOD:
            self.coarse.append((t, v))
            self._last_coarse = t

    def points(self, since: float) -> list[list[float]]:
        """Ascending ``[t, v]`` points covering ``since``..now: the
        coarse/mid tiers fill the span the raw ring has already
        evicted, deduplicated on timestamp (finest tier wins)."""
        raw = [p for p in self.raw if p[0] >= since]
        floor = raw[0][0] if raw else float("inf")
        merged = [p for p in self.coarse if since <= p[0] < floor]
        merged += [
            p
            for p in self.mid
            if since <= p[0] < floor
            and not any(abs(p[0] - q[0]) < 1e-9 for q in merged)
        ]
        merged.sort()
        return [[t, v] for t, v in merged + raw]

    def n_points(self) -> int:
        return len(self.raw) + len(self.mid) + len(self.coarse)

    def last(self) -> tuple[float, float] | None:
        return self.raw[-1] if self.raw else None


#: histogram-derived scalar tracks recorded per histogram series
_HIST_STATS = ("count", "sum", "p50", "p95", "p99")


def _hist_quantile_from_snapshot(
    bounds: list, counts: list, count: int, q: float
) -> float:
    """Bucket-interpolated quantile from a snapshot's per-bucket counts
    (same estimate as ``Histogram.quantile``, which operates on live
    instruments rather than snapshots)."""
    if count <= 0:
        return 0.0
    target = q * count
    seen = 0
    for i, c in enumerate(counts):
        if seen + c >= target and c > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else (bounds[-1] if bounds else 0.0)
            frac = (target - seen) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += c
    return bounds[-1] if bounds else 0.0


class TimeSeriesStore:
    """Bounded in-process time-series store over registry snapshots.

    Series are keyed by ``(family, sorted-label-items)`` — labels
    always include ``worker`` — and capped globally: once
    ``max_series`` distinct series exist, new ones are dropped (and
    counted) rather than grown, so the memory budget holds under label
    churn."""

    def __init__(self, max_series: int | None = None) -> None:
        if max_series is None:
            max_series = _env_int("PATHWAY_TPU_TS_MAX_SERIES", 1024)
        self.max_series = max(1, max_series)
        self._lock = threading.Lock()
        self._series: dict[tuple, SeriesRing] = {}  # guarded-by: self._lock
        self._kinds: dict[str, str] = {}  # guarded-by: self._lock
        self._dropped_series = 0  # guarded-by: self._lock

    # -- write side ----------------------------------------------------------

    def observe(
        self, family: str, labels: dict, value: float, t: float | None = None
    ) -> None:
        if t is None:
            t = _time.time()
        key = (family, tuple(sorted(labels.items())))
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                if len(self._series) >= self.max_series:
                    self._dropped_series += 1
                    return
                ring = self._series[key] = SeriesRing()
            ring.append(float(t), float(value))

    def ingest_snapshot(
        self, snap: dict, worker: str, t: float | None = None
    ) -> None:
        """Record one registry snapshot (``Registry.snapshot`` shape)
        under a ``worker`` label.  Scalars record as-is; histograms
        record their derived count/sum/quantile tracks."""
        if t is None:
            t = _time.time()
        for family, fam in snap.items():
            if family.startswith("__") or not isinstance(fam, dict):
                continue  # reserved piggyback keys are not families
            kind = fam.get("kind")
            series = fam.get("series")
            if kind is None or not isinstance(series, list):
                continue
            with self._lock:
                self._kinds.setdefault(family, kind)
            bounds = list(fam.get("buckets") or [])
            for entry in series:
                labels = dict(entry.get("labels") or {})
                labels["worker"] = worker
                if kind == "histogram":
                    counts = entry.get("counts") or []
                    count = int(entry.get("count", 0))
                    derived = {
                        "count": float(count),
                        "sum": float(entry.get("sum", 0.0)),
                        "p50": _hist_quantile_from_snapshot(
                            bounds, counts, count, 0.50
                        ),
                        "p95": _hist_quantile_from_snapshot(
                            bounds, counts, count, 0.95
                        ),
                        "p99": _hist_quantile_from_snapshot(
                            bounds, counts, count, 0.99
                        ),
                    }
                    for stat in _HIST_STATS:
                        self.observe(
                            family,
                            dict(labels, stat=stat),
                            derived[stat],
                            t,
                        )
                else:
                    self.observe(family, labels, entry.get("value", 0.0), t)

    def ingest_read_tier(
        self, snap: dict, worker: str, t: float | None = None
    ) -> None:
        """Derive the read-tier health families from one registry
        snapshot and record them under ``worker`` — the PR-19 read-path
        metrics reduced to the three numbers an operator watches:

        - ``pathway_read_cache_hit_rate`` — hits / (hits + misses) of
          the result cache (skipped until the first lookup);
        - ``pathway_read_federation_fanout_mean`` — mean backend
          requests per federated query (sum/count of the fan-out
          histogram);
        - ``pathway_read_replica_lag_seconds`` — freshest-cut age per
          replica, re-labelled so replica series prune with their
          ``r<id>`` worker label on disconnect.
        """
        if t is None:
            t = _time.time()
        derived: list[tuple[str, dict, float]] = []
        cache = snap.get("pathway_serving_cache_events_total")
        if isinstance(cache, dict):
            counts = {
                (entry.get("labels") or {}).get("kind"): float(
                    entry.get("value", 0.0)
                )
                for entry in cache.get("series") or []
            }
            total = counts.get("hit", 0.0) + counts.get("miss", 0.0)
            if total > 0:
                derived.append(
                    (
                        "pathway_read_cache_hit_rate",
                        {},
                        counts.get("hit", 0.0) / total,
                    )
                )
        fanout = snap.get("pathway_serving_federation_fanout")
        if isinstance(fanout, dict):
            for entry in fanout.get("series") or []:
                count = float(entry.get("count", 0.0))
                if count > 0:
                    derived.append(
                        (
                            "pathway_read_federation_fanout_mean",
                            dict(entry.get("labels") or {}),
                            float(entry.get("sum", 0.0)) / count,
                        )
                    )
        lag = snap.get("pathway_serving_replica_lag_seconds")
        if isinstance(lag, dict):
            for entry in lag.get("series") or []:
                derived.append(
                    (
                        "pathway_read_replica_lag_seconds",
                        dict(entry.get("labels") or {}),
                        float(entry.get("value", 0.0)),
                    )
                )
        if not derived:
            return
        with self._lock:
            for family, _labels, _value in derived:
                self._kinds.setdefault(family, "gauge")
        for family, labels, value in derived:
            labels["worker"] = worker
            self.observe(family, labels, value, t)

    def prune_workers(
        self, dead: Iterable[str] = (), width: int | None = None
    ) -> None:
        """Drop every series labelled with a dead ``worker`` — the
        timeseries twin of ``prune_mesh_metrics``, hooked from the
        same rescale/failover/recovery paths.  ``width`` additionally
        drops numeric worker ids beyond the current mesh width (a
        rescale that shrank the mesh leaves them as dead
        incarnations)."""
        gone = {str(w) for w in dead}
        if not gone and width is None:
            return
        with self._lock:
            for key in list(self._series):
                worker = dict(key[1]).get("worker")
                if worker in gone or (
                    width is not None
                    and isinstance(worker, str)
                    and worker.isdigit()
                    and int(worker) >= width
                ):
                    self._series.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._kinds.clear()
            self._dropped_series = 0

    # -- read side -----------------------------------------------------------

    def families(self) -> list[dict]:
        with self._lock:
            counts: dict[str, int] = {}
            for family, _labels in self._series:
                counts[family] = counts.get(family, 0) + 1
            return [
                {
                    "family": family,
                    "kind": self._kinds.get(family, "gauge"),
                    "series": n,
                }
                for family, n in sorted(counts.items())
            ]

    def query(
        self,
        family: str,
        window_s: float = 60.0,
        labels: dict | None = None,
        now: float | None = None,
    ) -> dict:
        """Windowed read: every series of ``family`` whose labels are a
        superset of ``labels``, each with its ascending ``[t, v]``
        points over the last ``window_s`` seconds — the shape
        ``/timeseries`` serves and the autoscaler loop will read."""
        if now is None:
            now = _time.time()
        since = now - max(0.0, float(window_s))
        want = {str(k): str(v) for k, v in (labels or {}).items()}
        out = []
        with self._lock:
            matches = [
                (key, ring)
                for key, ring in self._series.items()
                if key[0] == family
            ]
            kind = self._kinds.get(family, "gauge")
        for (fam, label_items), ring in sorted(matches):
            label_dict = dict(label_items)
            if any(str(label_dict.get(k)) != v for k, v in want.items()):
                continue
            pts = ring.points(since)
            out.append({"labels": label_dict, "points": pts})
        return {
            "family": family,
            "kind": kind,
            "window_s": float(window_s),
            "now": now,
            "series": out,
        }

    def stats(self) -> dict:
        """Bound accounting for tests and the ``/timeseries`` index:
        series/point totals plus the hard caps they stay under."""
        with self._lock:
            n_series = len(self._series)
            n_points = sum(r.n_points() for r in self._series.values())
            dropped = self._dropped_series
        return {
            "series": n_series,
            "points": n_points,
            "dropped_series": dropped,
            "max_series": self.max_series,
            "max_points": self.max_series
            * (RAW_POINTS + MID_POINTS + COARSE_POINTS),
        }


# -- SLO sentinel -------------------------------------------------------------

_SLO_KINDS = ("latency", "queue_depth", "staleness", "throughput")


class SloSpec:
    """One declarative SLO:

    - ``latency``: burn rate — the fraction of windowed quantile points
      above ``bound`` seconds, divided by the error ``budget`` fraction
      (burn > 1 means the budget is being spent too fast);
    - ``queue_depth``: ceiling — max windowed value over ``bound``;
    - ``staleness``: bound — last observed value over ``bound`` seconds;
    - ``throughput``: floor — ``bound`` rows/s over the windowed
      counter rate.

    Every kind normalizes to a burn ratio where > 1.0 is a violation.
    """

    __slots__ = (
        "name", "kind", "family", "labels", "bound", "window_s",
        "budget", "quantile",
    )

    def __init__(
        self,
        name: str,
        kind: str,
        family: str,
        bound: float,
        labels: dict | None = None,
        window_s: float = 60.0,
        budget: float = 0.1,
        quantile: str = "p99",
    ) -> None:
        if kind not in _SLO_KINDS:
            raise ValueError(f"slo {name!r}: unknown kind {kind!r}")
        if bound <= 0:
            raise ValueError(f"slo {name!r}: bound must be > 0")
        if quantile not in ("p50", "p95", "p99"):
            raise ValueError(f"slo {name!r}: unknown quantile {quantile!r}")
        self.name = name
        self.kind = kind
        self.family = family
        self.labels = dict(labels or {})
        self.bound = float(bound)
        self.window_s = float(window_s)
        self.budget = min(1.0, max(1e-6, float(budget)))
        self.quantile = quantile

    @classmethod
    def from_dict(cls, d: dict) -> "SloSpec":
        return cls(
            name=d["name"],
            kind=d["kind"],
            family=d["family"],
            bound=float(d["bound"]),
            labels=d.get("labels"),
            window_s=float(d.get("window_s", 60.0)),
            budget=float(d.get("budget", 0.1)),
            quantile=d.get("quantile", "p99"),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "family": self.family,
            "labels": dict(self.labels),
            "bound": self.bound,
            "window_s": self.window_s,
            "budget": self.budget,
            "quantile": self.quantile,
        }


class SloSentinel:
    """Evaluates SLO specs against the history ring; every evaluation
    sets ``pathway_slo_burn_ratio{slo=...}`` and a burn crossing
    (ratio rising through 1.0) records an ``slo_burn`` flight event and
    bumps ``pathway_slo_breaches_total`` — re-armed once the ratio
    falls back under 1.0, so a sustained violation is one event."""

    def __init__(self, specs: Iterable[SloSpec] = ()) -> None:
        self._lock = threading.Lock()
        self._specs: list[SloSpec] = list(specs)  # guarded-by: self._lock
        self._burning: set[str] = set()  # guarded-by: self._lock

    def configure(self, specs: Iterable[SloSpec] | None = None) -> int:
        """Install specs, or (re)load them from ``PATHWAY_TPU_SLO`` —
        inline JSON or a path to a JSON file holding a spec list.
        Returns the number of active specs."""
        if specs is None:
            raw = os.environ.get("PATHWAY_TPU_SLO", "").strip()
            loaded: list[SloSpec] = []
            if raw:
                try:
                    if not raw.lstrip().startswith(("[", "{")):
                        with open(raw, encoding="utf-8") as fh:
                            raw = fh.read()
                    data = json.loads(raw)
                    if isinstance(data, dict):
                        data = [data]
                    loaded = [SloSpec.from_dict(d) for d in data]
                except (OSError, ValueError, KeyError, TypeError) as exc:
                    _metrics.FLIGHT.record("slo_config_error", error=repr(exc))
            specs = loaded
        with self._lock:
            self._specs = list(specs)
            self._burning.clear()
            return len(self._specs)

    def specs(self) -> list[SloSpec]:
        with self._lock:
            return list(self._specs)

    def _measure(
        self, spec: SloSpec, store: TimeSeriesStore, now: float
    ) -> tuple[float, float] | None:
        """Returns ``(burn_ratio, measured)`` or None when the ring has
        no data for the spec yet (no data is not a violation)."""
        labels = dict(spec.labels)
        if spec.kind == "latency":
            labels.setdefault("stat", spec.quantile)
        result = store.query(spec.family, spec.window_s, labels, now=now)
        points = [p for s in result["series"] for p in s["points"]]
        if not points:
            return None
        points.sort()
        if spec.kind == "latency":
            violating = sum(1 for _t, v in points if v > spec.bound)
            frac = violating / len(points)
            return frac / spec.budget, max(v for _t, v in points)
        if spec.kind == "queue_depth":
            peak = max(v for _t, v in points)
            return peak / spec.bound, peak
        if spec.kind == "staleness":
            last = points[-1][1]
            return last / spec.bound, last
        # throughput floor: windowed counter rate (counters are
        # cumulative, so the rate is the endpoint delta over time)
        t0, v0 = points[0]
        t1, v1 = points[-1]
        if t1 - t0 < 1e-6:
            return None
        rate = max(0.0, (v1 - v0) / (t1 - t0))
        return spec.bound / max(rate, 1e-9), rate

    def evaluate(
        self, store: TimeSeriesStore, now: float | None = None
    ) -> list[dict]:
        """One evaluation pass; returns per-spec reports (for tests and
        the ``/timeseries`` index page)."""
        if now is None:
            now = _time.time()
        reports = []
        for spec in self.specs():
            measured = self._measure(spec, store, now)
            if measured is None:
                reports.append(
                    {"slo": spec.name, "burn": None, "measured": None}
                )
                continue
            burn, value = measured
            _metrics.REGISTRY.gauge(
                "pathway_slo_burn_ratio",
                "SLO burn ratio (> 1.0 = violating)",
                slo=spec.name,
            ).set(round(burn, 6))
            with self._lock:
                burning = spec.name in self._burning
                if burn > 1.0 and not burning:
                    self._burning.add(spec.name)
                    crossed = True
                elif burn <= 1.0 and burning:
                    self._burning.discard(spec.name)
                    crossed = False
                else:
                    crossed = False
            if crossed:
                _metrics.REGISTRY.counter(
                    "pathway_slo_breaches_total",
                    "SLO burn events recorded by the sentinel",
                    slo=spec.name,
                ).inc(1)
                _metrics.FLIGHT.record(
                    "slo_burn",
                    slo=spec.name,
                    slo_kind=spec.kind,
                    family=spec.family,
                    burn=round(burn, 6),
                    measured=round(value, 6),
                    bound=spec.bound,
                    window_s=spec.window_s,
                )
            reports.append(
                {
                    "slo": spec.name,
                    "kind": spec.kind,
                    "burn": round(burn, 6),
                    "measured": round(value, 6),
                    "bound": spec.bound,
                }
            )
        return reports


# -- the recorder loop --------------------------------------------------------


class TelemetryLoop:
    """Daemon thread recording registry snapshots into the store and
    running the sentinel — one per process, started by ``pw.run``
    alongside the monitoring HTTP server (or whenever
    ``PATHWAY_TPU_TIMESERIES=1`` / an SLO spec is configured)."""

    def __init__(
        self,
        store: TimeSeriesStore,
        sentinel: SloSentinel,
        monitor: Any = None,
        period_s: float | None = None,
    ) -> None:
        if period_s is None:
            period_s = _env_float("PATHWAY_TPU_TS_INTERVAL", 0.5)
        self.store = store
        self.sentinel = sentinel
        self.monitor = monitor
        self.period_s = max(0.05, period_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        try:
            self.worker_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        except ValueError:
            self.worker_id = 0

    def start(self) -> "TelemetryLoop":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pathway-timeseries", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)
        # one final pass so a run shorter than the period still lands
        # its last state in the ring (and the sentinel sees it)
        try:
            self.tick()
        except Exception:
            pass

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def tick(self, now: float | None = None) -> None:
        """One recording pass (the loop body; tests call it directly):
        local registry (plus scheduler operator series) under this
        worker's label, then every piggybacked mesh snapshot under its
        peer's label, then the sentinel."""
        if now is None:
            now = _time.time()
        scheduler = getattr(self.monitor, "scheduler", None)
        snap = _metrics.full_snapshot(scheduler)
        self.store.ingest_snapshot(snap, str(self.worker_id), t=now)
        self.store.ingest_read_tier(snap, str(self.worker_id), t=now)
        mesh = getattr(self.monitor, "mesh_snapshots", None) or {}
        width = getattr(scheduler, "n_processes", None)
        for peer, peer_snap in sorted(mesh.items()):
            if width is not None and int(peer) >= width:
                continue  # dead-incarnation filter, as prometheus_text
            if isinstance(peer_snap, dict):
                self.store.ingest_snapshot(peer_snap, str(peer), t=now)
        # read-tier replicas ride the snapshot stream under "r<id>"
        # labels; SnapshotStreamServer._drop_subscriber prunes their
        # ring series on disconnect (string ids bypass the width filter)
        try:
            from pathway_tpu import serving as _serving

            stream = _serving.stream_server()
        except Exception:
            stream = None
        if stream is not None:
            for rid, rsnap in sorted(
                stream.replica_metrics_snapshot().items()
            ):
                if isinstance(rsnap, dict):
                    self.store.ingest_snapshot(rsnap, f"r{rid}", t=now)
                    self.store.ingest_read_tier(rsnap, f"r{rid}", t=now)
        self.sentinel.evaluate(self.store, now=now)

    def _run(self) -> None:
        tick_hist = _metrics.REGISTRY.histogram(
            "pathway_timeseries_tick_seconds",
            "wall cost of one timeseries recording pass",
            buckets=(1e-4, 1e-3, 1e-2, 0.1, 1.0),
        )
        while not self._stop.wait(self.period_s):
            t0 = _time.perf_counter()
            try:
                self.tick()
            except Exception:
                # the recorder must never take the run down; the next
                # tick retries from fresh snapshots
                pass
            tick_hist.observe(_time.perf_counter() - t0)


#: process-wide store + sentinel (the /timeseries endpoint reads these)
STORE = TimeSeriesStore()
SENTINEL = SloSentinel()

_LOOP: TelemetryLoop | None = None
_LOOP_LOCK = threading.Lock()


def loop_enabled() -> bool:
    """True when the recorder should run even without a monitoring
    HTTP server: an explicit opt-in or a configured SLO spec."""
    return (
        os.environ.get("PATHWAY_TPU_TIMESERIES", "").lower() in _TRUTHY
        or bool(os.environ.get("PATHWAY_TPU_SLO", "").strip())
    )


def start_loop(monitor: Any = None) -> TelemetryLoop:
    """Start (or rebind) the process-wide recorder loop; idempotent."""
    global _LOOP
    if not SENTINEL.specs():
        SENTINEL.configure()  # pick up PATHWAY_TPU_SLO if set
    with _LOOP_LOCK:
        if _LOOP is None:
            _LOOP = TelemetryLoop(STORE, SENTINEL, monitor=monitor)
        else:
            _LOOP.monitor = monitor if monitor is not None else _LOOP.monitor
        return _LOOP.start()


def stop_loop() -> None:
    global _LOOP
    with _LOOP_LOCK:
        loop = _LOOP
        _LOOP = None
    if loop is not None:
        loop.stop()
