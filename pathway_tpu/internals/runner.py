"""GraphRunner — lowers the lazy Table graph onto the engine scope.

New implementation of the reference's graph_runner
(reference: python/pathway/internals/graph_runner/__init__.py:36 +
expression_evaluator.py + path_evaluator.py): tree-shakes reachable specs,
flattens columns into engine tuple positions, compiles the expression DSL to
engine expressions, and pumps the scheduler.
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING, Any, Callable, Sequence

from pathway_tpu.engine import expression as eex
from pathway_tpu.engine.graph import Node, Scheduler, Scope
from pathway_tpu.engine.reducers import make_reducer
from pathway_tpu.engine.value import Pointer
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as pex
from pathway_tpu.internals.desugaring import substitute
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.universe import solver

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class Layout:
    """Maps (table_id, column_name) → tuple position in a storage node."""

    def __init__(self) -> None:
        self.columns: dict[tuple[int, str], int] = {}
        self.key_tables: set[int] = set()  # tables whose id == storage key
        self.id_columns: dict[int, int] = {}  # table_id -> position of its id col

    def position(self, ref: ColumnReference) -> int | None:
        if ref.name == "id":
            return self.id_columns.get(ref.table._id)
        return self.columns.get((ref.table._id, ref.name))


_CAST_NAMES = {
    dt.INT: "Int",
    dt.FLOAT: "Float",
    dt.BOOL: "Bool",
    dt.STR: "String",
}


from pathway_tpu.engine import device_pipeline as _device_pipeline
from pathway_tpu import serving as _serving
from pathway_tpu.internals.udfs.executors import make_kw_fn as _make_kw_fn
from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import profiling as _profiling
from pathway_tpu.internals import timeseries as _timeseries
from pathway_tpu.internals import tracing as _tracing

#: ingest->sink latency, observed once per delta batch weighted by the
#: rows the commit delivered to subscribe sinks
_INGEST_LATENCY = _metrics.REGISTRY.histogram(
    "pathway_ingest_to_sink_latency_seconds",
    "end-to-end ingest->sink latency stamped per delta batch",
)
#: same series the sink nodes bump (engine/graph.py SubscribeNode)
_OUT_ROWS = _metrics.REGISTRY.counter("pathway_output_rows_total")


def _take_ingest_stamp(
    drivers: list,
) -> tuple[float | None, list[str]]:
    """Pop the oldest pending-row wall stamp across connector drivers
    (InputDriver.poll sets it when rows enter a session); the commit that
    follows delivers those rows, closing the latency window.  Also
    returns the source names whose stamps were popped — the tracing
    ingest-wait span labels itself with them."""
    best = None
    sources: list[str] = []
    for d in drivers:
        inner = getattr(d, "driver", d)
        stamp = getattr(inner, "first_pending_wall", None)
        if stamp is not None:
            inner.first_pending_wall = None
            name = getattr(inner, "source_name", None)
            if name:
                sources.append(str(name))
            if best is None or stamp < best:
                best = stamp
    return best, sources


def _observe_commit_latency(
    stamp: float | None, commit_started: float, rows_before: float
) -> None:
    """Stamp the latency histogram with this commit's sink-row delta.
    Rows without an ingest stamp (static data, replays) fall back to the
    commit start so the histogram ``_count`` always equals the rows the
    sinks produced."""
    import time as _time

    rows = int(_OUT_ROWS.value - rows_before)
    if rows <= 0:
        return
    origin = stamp if stamp is not None else commit_started
    _INGEST_LATENCY.observe_n(max(0.0, _time.monotonic() - origin), rows)


def _pump_drivers(w0: "GraphRunner", drivers: list, on_data, on_idle=None) -> None:
    """The one streaming poll loop (GraphRunner / ShardedGraphRunner /
    DistributedGraphRunner all drive it): poll every connector driver,
    accumulate rows into input sessions, and call ``on_data()`` (which
    commits) when a driver's autocommit deadline expires or a driver
    finishes. Also drains passive loopback sources (AsyncTransformer) once
    no live driver can still feed them, and backs off exponentially when
    idle (``on_idle`` hooks extra idle work, e.g. coordinator pings).

    The autocommit window (``autocommit_duration_ms`` on each connector,
    reference python/pathway/io/python/__init__.py read kwarg) is what
    keeps commit granularity healthy: committing on every poll turns a
    fast feed into thousands of tiny commits whose per-commit overhead
    (scheduler sweep + device dispatch + decay barrier) dwarfs the row
    work — measured 163 vs ~8000 docs/s on the RAG ingest bench. Data
    waits at most the window; a 0-window connector (queries) pulls the
    commit forward immediately."""
    import time as _time

    live = list(drivers)
    idle_spins = 0
    pending = False  # rows sit in input sessions awaiting a commit
    deadline = 0.0
    while live:
        produced = False
        flush_now = False
        for d in list(live):
            status = d.poll()
            if status == "done":
                live.remove(d)
                produced = True
                flush_now = True  # stream end surfaces immediately
                # a driver's last poll can drain rows AND report EOF in
                # one call — those rows are in the session now, so a
                # commit must follow even if nothing else was pending
                pending = True
            elif status == "data":
                produced = True
                eff = getattr(d, "effective_autocommit_s", None)
                ac_deadline = _time.monotonic() + (
                    eff() if eff is not None else getattr(d, "autocommit_s", 0.0)
                )
                deadline = min(deadline, ac_deadline) if pending else ac_deadline
                pending = True
        if pending and (flush_now or _time.monotonic() >= deadline):
            on_data()
            pending = False
            idle_spins = 0
            continue
        if produced:
            idle_spins = 0
            continue  # keep draining the feed until the window closes
        if pending:
            # nothing new this sweep: sleep out (a slice of) the window
            _time.sleep(
                min(max(deadline - _time.monotonic(), 0.0), 0.001)
            )
            continue
        notified = False
        if live and all(
            getattr(d, "upstream_done", None) is not None for d in live
        ):
            for d in live:
                if getattr(d, "_upstream_notified", False):
                    continue
                if w0._loopback_upstream_live(d, live):
                    continue
                d._upstream_notified = True
                d.upstream_done()
                notified = True
                break
        if not notified:
            idle_spins += 1
            _time.sleep(min(0.001 * idle_spins, 0.05))
            if on_idle is not None:
                on_idle()


class GraphRunner:
    def __init__(
        self,
        scope: Scope | None = None,
        persistence_config: Any = None,
        attach_drivers: bool = True,
    ) -> None:
        self.scope = scope if scope is not None else Scope()
        self.nodes: dict[int, Node] = {}
        self.attach_drivers = attach_drivers  # False on sharded replicas >0
        self.drivers: list[Any] = []  # connector drivers (streaming mode)
        self.monitors: list[Any] = []
        self.monitor: Any = None  # StatsMonitor (internals/monitoring.py)
        self._local_logs: dict[int, Node] = {}  # local error logs by id
        self.persistence = persistence_config
        if persistence_config is not None:
            self._wire_udf_cache(persistence_config)

    def _sync_monitor_connectors(self) -> None:
        if self.monitor is None:
            return
        seen: dict[str, int] = {}
        for d in self.drivers:
            inner = getattr(d, "driver", d)
            name = getattr(inner, "source_name", None)
            if name is None:
                continue
            # two drivers may share a source_name (e.g. default
            # 'python-connector'); suffix duplicates so counters don't fight
            n = seen.get(name, 0)
            seen[name] = n + 1
            if n:
                name = f"{name}#{n}"
            st = self.monitor.connector(name)
            st.entries = getattr(inner, "entries_total", 0)
            st.batches = getattr(inner, "batches_total", 0)
            wall = getattr(inner, "last_entry_wall", None)
            if wall is not None:
                st.last_entry_at = wall
            st.finished = getattr(inner, "done", False)

    @staticmethod
    def _wire_udf_cache(config: Any) -> None:
        """Route default DiskCaches at the persistence backend (reference:
        PersistenceMode::UdfCaching, servers.py:62-81 with_cache)."""
        import os as _os

        from pathway_tpu.engine.persistence import FileBackend
        from pathway_tpu.internals.udfs.caches import set_udf_cache_root

        backend = getattr(config, "backend", None)
        if isinstance(backend, FileBackend):
            set_udf_cache_root(_os.path.join(backend.root, "udf-cache"))

    # -- expression compilation --------------------------------------------

    def compile(self, expression: ColumnExpression, layout: Layout) -> eex.EngineExpression:
        override = getattr(expression, "_engine_override", None)
        if override is not None:
            return override
        c = lambda e: self.compile(e, layout)  # noqa: E731
        if isinstance(expression, ColumnReference):
            if expression.name == "id":
                pos = layout.id_columns.get(expression.table._id)
                if pos is not None:
                    return eex.ColumnRef(pos)
                if expression.table._id in layout.key_tables:
                    return eex.KeyRef()
                raise ValueError(
                    f"cannot reference {expression!r} in this context"
                )
            pos = layout.position(expression)
            if pos is None:
                raise ValueError(
                    f"column {expression!r} is not available in this context"
                )
            return eex.ColumnRef(pos)
        if isinstance(expression, pex.ColumnConstExpression):
            return eex.Const(expression._value)
        if isinstance(expression, pex.BinaryOpExpression):
            return eex.Binary(expression._op, c(expression._left), c(expression._right))
        if isinstance(expression, pex.UnaryOpExpression):
            return eex.Unary(expression._op, c(expression._arg))
        if isinstance(expression, pex.BooleanExpression):
            return eex.BooleanChain(expression._op, [c(a) for a in expression._args])
        if isinstance(expression, pex.IsNoneExpression):
            return eex.IsNone(c(expression._arg), expression._negated)
        if isinstance(expression, pex.IfElseExpression):
            return eex.IfElse(
                c(expression._cond), c(expression._then), c(expression._otherwise)
            )
        if isinstance(expression, pex.CoalesceExpression):
            return eex.Coalesce([c(a) for a in expression._args])
        if isinstance(expression, pex.RequireExpression):
            return eex.Require(c(expression._value), [c(d) for d in expression._deps])
        if isinstance(expression, pex.ApplyExpression):
            args = [c(a) for a in expression._args]
            kw_names = list(expression._kwargs.keys())
            args += [c(expression._kwargs[k]) for k in kw_names]
            fn = _make_kw_fn(expression._fn, len(expression._args), kw_names)
            return eex.Apply(
                fn,
                args,
                propagate_none=expression._propagate_none,
                deterministic=expression._deterministic,
            )
        if isinstance(expression, pex.CastExpression):
            target = _CAST_NAMES.get(expression._dtype.strip_optional())
            if target is None:
                return c(expression._arg)
            return eex.Cast(c(expression._arg), target)
        if isinstance(expression, pex.DeclareTypeExpression):
            return c(expression._arg)
        if isinstance(expression, pex.ConvertExpression):
            return eex.Convert(c(expression._arg), expression._target, expression._unwrap)
        if isinstance(expression, pex.UnwrapExpression):
            return eex.Unwrap(c(expression._arg))
        if isinstance(expression, pex.FillErrorExpression):
            return eex.FillError(c(expression._arg), c(expression._fallback))
        if isinstance(expression, pex.MakeTupleExpression):
            return eex.MakeTuple([c(a) for a in expression._args])
        if isinstance(expression, pex.GetExpression):
            return eex.SequenceGet(
                c(expression._arg),
                c(expression._index),
                c(expression._default) if expression._default is not None else None,
                expression._checked,
            )
        if isinstance(expression, pex.PointerExpression):
            return eex.PointerFrom(
                [c(a) for a in expression._args],
                c(expression._instance) if expression._instance is not None else None,
            )
        if isinstance(expression, pex.BatchApplyExpression):
            raise NotImplementedError(
                "async/batched UDF calls are only supported as top-level "
                "select columns"
            )
        if isinstance(expression, pex.ReducerExpression):
            raise ValueError("reducers are only allowed inside .reduce(...)")
        raise NotImplementedError(f"cannot compile expression {expression!r}")

    # -- storage ------------------------------------------------------------

    def storage_for(
        self, base: "Table", expressions: Sequence[ColumnExpression]
    ) -> tuple[Node, Layout]:
        """Build a storage node exposing ``base``'s columns plus any columns
        of other (universe-related) tables referenced by ``expressions``."""
        tables: dict[int, "Table"] = {base._id: base}
        for e in expressions:
            for ref in e._dependencies():
                t = ref.table
                if t._id not in tables:
                    if not solver.query_related(base._universe, t._universe):
                        raise ValueError(
                            f"column {ref!r} belongs to a table with an unrelated "
                            f"universe; join or use with_universe_of first"
                        )
                    tables[t._id] = t
        ordered = [base] + [t for tid, t in sorted(tables.items()) if tid != base._id]
        nodes = [self.build(t) for t in ordered]
        storage = self.scope.zip_tables(nodes)
        layout = Layout()
        offset = 0
        for t in ordered:
            for i, name in enumerate(t._column_names):
                layout.columns[(t._id, name)] = offset + i
            layout.key_tables.add(t._id)
            offset += len(t._column_names)
        return storage, layout

    def base_layout(self, table: "Table") -> Layout:
        layout = Layout()
        for i, name in enumerate(table._column_names):
            layout.columns[(table._id, name)] = i
        layout.key_tables.add(table._id)
        return layout

    # -- lowering -----------------------------------------------------------

    def _error_log_node(self, log_id):
        if log_id is None:
            return self.scope.error_log_default
        node = self._local_logs.get(log_id)
        if node is None:
            node = self._local_logs[log_id] = self.scope.error_log()
        return node

    def build(self, table: "Table") -> Node:
        if table._id in self.nodes:
            return self.nodes[table._id]
        node = self._build(table)
        log_id = getattr(table, "_error_log_id", None)
        if log_id is not None:
            node.error_log = self._error_log_node(log_id)
        node.name = f"{table._spec.kind}<{table._name}>"
        node.trace = table._trace
        self._annotate_schema(node, table)
        self.nodes[table._id] = node
        return node

    @staticmethod
    def _annotate_schema(node: Node, table: "Table") -> None:
        """Attach the framework-level dtypes as engine-type hints for the
        static analyzer (pathway_tpu/analysis): ``node.schema_types`` is a
        list of per-column ``frozenset[engine Type]`` possible-type sets.
        Only attached when the built node's tuple layout matches the table
        columns 1:1 (the base_layout invariant); the analyzer uses the
        hint for source-like and opaque nodes and infers the rest."""
        if node.arity != len(table._column_names):
            return
        hints = []
        for name in table._column_names:
            d = table._dtypes.get(name)
            if d is None:
                hints.append(frozenset({dt.EngineType.ANY}))
                continue
            try:
                members = {d.strip_optional().to_engine()}
                if d.is_optional():
                    members.add(dt.EngineType.NONE)
            except Exception:  # noqa: BLE001 — exotic dtype: stay opaque
                members = {dt.EngineType.ANY}
            hints.append(frozenset(members))
        node.schema_types = hints

    def _project(self, node: Node, positions: Sequence[int]) -> Node:
        return self.scope.expression_table(node, [eex.ColumnRef(i) for i in positions])

    def _build(self, table: "Table") -> Node:
        spec = table._spec
        kind = spec.kind
        scope = self.scope

        if kind == "error_log":
            return self._error_log_node(spec.params.get("log_id"))

        if kind == "static":
            return scope.static_table(spec.params["rows"], len(table._column_names))

        if kind == "input":
            # connector-backed table: the io layer supplies an attach function
            attach = spec.params["attach"]
            import inspect

            if "make_driver" in inspect.signature(attach).parameters:
                node, driver = attach(scope, make_driver=self.attach_drivers)
            else:  # custom attach without the kwarg: discard after the fact
                node, driver = attach(scope)
            if driver is not None and not self.attach_drivers:
                driver = None  # replica scopes never poll; worker 0 reads
            if driver is not None:
                sync_group = spec.params.get("sync_group")
                if sync_group is not None:
                    sync_group.ensure_run(id(self))
                    driver.sync_group = sync_group
                    driver.sync_col = table._column_names.index(
                        spec.params["sync_column"]
                    )
                    sync_group.register(driver)
                persistent_id = spec.params.get("persistent_id")
                if persistent_id is not None and self.persistence is not None:
                    from pathway_tpu.engine.persistence import PersistentDriver
                    from pathway_tpu.persistence import PersistenceMode

                    if (
                        self.persistence.persistence_mode
                        == PersistenceMode.PERSISTING
                    ):
                        driver = PersistentDriver(
                            driver, self.persistence.backend, persistent_id
                        )
                self.drivers.append(driver)
            return node

        if kind == "select":
            exprs = spec.params["exprs"]
            expr_list = list(exprs.values())
            storage, layout = self.storage_for(spec.inputs[0], expr_list)
            if not any(isinstance(e, pex.BatchApplyExpression) for e in expr_list):
                return scope.expression_table(
                    storage, [self.compile(e, layout) for e in expr_list]
                )
            return self._build_select_with_udfs(expr_list, storage, layout)

        if kind == "filter":
            base = spec.inputs[0]
            cond = spec.params["condition"]
            storage, layout = self.storage_for(base, [cond])
            n = len(base._column_names)
            pre = scope.expression_table(
                storage,
                [
                    self.compile(ColumnReference(base, name), layout)
                    for name in base._column_names
                ]
                + [self.compile(cond, layout)],
            )
            filtered = scope.filter_table(pre, n)
            return self._project(filtered, range(n))

        if kind == "remove_errors":
            return scope.remove_errors_from_table(self.build(spec.inputs[0]))

        if kind == "groupby_reduce":
            return self._build_groupby(table)

        if kind == "join_select":
            return self._build_join(table)

        if kind == "concat":
            aligned = []
            for t in spec.inputs:
                node = self.build(t)
                layout = self.base_layout(t)
                aligned.append(
                    scope.expression_table(
                        node,
                        [
                            self.compile(ColumnReference(t, name), layout)
                            for name in table._column_names
                        ],
                    )
                )
            return scope.concat_tables(aligned)

        if kind == "update_rows":
            orig, updates = spec.inputs
            orig_node = self.build(orig)
            upd_node = self.build(updates)
            upd_layout = self.base_layout(updates)
            upd_aligned = scope.expression_table(
                upd_node,
                [
                    self.compile(ColumnReference(updates, name), upd_layout)
                    for name in table._column_names
                ],
            )
            return scope.update_rows_table(orig_node, upd_aligned)

        if kind == "update_cells":
            orig, updates = spec.inputs
            orig_node = self.build(orig)
            upd_node = self.build(updates)
            update_cols = [
                updates._column_names.index(name) if name in updates._column_names else -1
                for name in table._column_names
            ]
            return scope.update_cells_table(orig_node, upd_node, update_cols)

        if kind == "reindex":
            base = spec.inputs[0]
            new_id = spec.params["new_id"]
            storage, layout = self.storage_for(base, [new_id])
            n = len(base._column_names)
            pre = scope.expression_table(
                storage,
                [
                    self.compile(ColumnReference(base, name), layout)
                    for name in base._column_names
                ]
                + [self.compile(new_id, layout)],
            )
            reindexed = scope.reindex_table(pre, n)
            return self._project(reindexed, range(n))

        if kind == "intersect":
            base, *others = spec.inputs
            return scope.intersect_tables(
                self.build(base), [self.build(o) for o in others]
            )

        if kind == "subtract":
            base, other = spec.inputs
            return scope.subtract_table(self.build(base), self.build(other))

        if kind == "restrict":
            base, other = spec.inputs
            return scope.restrict_table(self.build(base), self.build(other))

        if kind == "override_universe":
            base, other = spec.inputs
            return scope.override_table_universe(self.build(base), self.build(other))

        if kind == "flatten":
            base = spec.inputs[0]
            col_idx = base._column_names.index(spec.params["column"])
            return scope.flatten_table(
                self.build(base),
                col_idx,
                with_origin=spec.params.get("origin_id") is not None,
            )

        if kind == "sort":
            base = spec.inputs[0]
            key_expr = spec.params["key"]
            inst_expr = spec.params["instance"]
            exprs = [key_expr] + ([inst_expr] if inst_expr is not None else [])
            storage, layout = self.storage_for(base, exprs)
            pre = scope.expression_table(storage, [self.compile(e, layout) for e in exprs])
            return scope.sort_table(pre, 0, 1 if inst_expr is not None else None)

        if kind == "ix":
            keys_table, source = spec.inputs
            keys_node = self.build(keys_table)
            source_node = self.build(source)
            key_col = keys_table._column_names.index("_pw_ix_key")
            return scope.ix_table(
                keys_node,
                source_node,
                key_col,
                optional=spec.params.get("optional", False),
            )

        if kind == "deduplicate":
            base = spec.inputs[0]
            value = spec.params["value"]
            instance = spec.params["instance"]
            storage, layout = self.storage_for(base, [value, *instance])
            n = len(base._column_names)
            pre_exprs = [
                self.compile(ColumnReference(base, name), layout)
                for name in base._column_names
            ]
            pre_exprs.append(self.compile(value, layout))
            for inst in instance:
                pre_exprs.append(self.compile(inst, layout))
            pre = scope.expression_table(storage, pre_exprs)
            dedup = scope.deduplicate(
                pre,
                value_col=n,
                instance_cols=list(range(n + 1, n + 1 + len(instance))),
                acceptor=spec.params["acceptor"],
            )
            return self._project(dedup, range(n))

        if kind == "external_index":
            from pathway_tpu.engine.external_index import ExternalIndexNode

            data_t, query_t = spec.inputs
            data_node = self.build(data_t)
            query_node = self.build(query_t)
            data_prep = scope.expression_table(
                data_node,
                [self.compile(spec.params["index_expr"], self.base_layout(data_t))],
            )
            query_layout = self.base_layout(query_t)
            q_exprs = [self.compile(spec.params["query_expr"], query_layout)]
            limit_col = None
            if spec.params["limit_expr"] is not None:
                q_exprs.append(self.compile(spec.params["limit_expr"], query_layout))
                limit_col = 1
            query_prep = scope.expression_table(query_node, q_exprs)
            return ExternalIndexNode(
                scope,
                data_prep,
                query_prep,
                spec.params["factory"](),
                index_col=0,
                query_col=0,
                k=spec.params["k"],
                limit_col=limit_col,
            )

        if kind in ("buffer", "forget", "freeze"):
            from pathway_tpu.engine import temporal as tmp

            base_node = self.build(spec.inputs[0])
            cls = {
                "buffer": tmp.BufferNode,
                "forget": tmp.ForgetNode,
                "freeze": tmp.FreezeNode,
            }[kind]
            return cls(
                scope,
                base_node,
                spec.params["threshold_col"],
                spec.params["time_col"],
            )

        if kind == "row_transformer":
            sources = [self.build(t) for t in spec.inputs]
            return scope.recompute_table(
                sources, spec.params["compute"], spec.params["arity"]
            )

        if kind == "gradual_broadcast":
            from pathway_tpu.engine.temporal import GradualBroadcastNode

            base_node = self.build(spec.inputs[0])
            # threshold table lowered to a 3-column (lower, value, upper)
            # storage by Table._gradual_broadcast
            thr_node = self.build(spec.inputs[1])
            return GradualBroadcastNode(scope, base_node, thr_node)

        if kind == "session_assign":
            from pathway_tpu.engine.temporal import SessionAssignNode

            return SessionAssignNode(
                scope,
                self.build(spec.inputs[0]),
                spec.params["time_col"],
                spec.params["instance_col"],
                spec.params["max_gap"],
            )

        if kind in ("interval_join", "asof_join", "asof_now_join"):
            return self._build_temporal_join(table)

        if kind == "iterate_param":
            rows = getattr(self, "iterate_params", None)
            if rows is None:
                raise ValueError(
                    "iterate parameter table used outside pw.iterate"
                )
            return scope.static_table(
                rows[spec.params["slot"]], len(table._column_names)
            )

        if kind == "table_transform":
            from pathway_tpu.engine.iterate import IterateNode

            fn = spec.params["fn"]
            node = self.build(spec.inputs[0])
            return IterateNode(
                scope,
                [node],
                len(table._column_names),
                lambda states, _fn=fn: _fn(states[0]),
            )

        if kind == "iterate_result":
            from pathway_tpu.engine.iterate import IterateNode

            engine = spec.params["engine"]
            name = spec.params["name"]
            input_nodes = [self.build(t) for t in spec.inputs]

            def compute(states: list[dict], _engine=engine, _name=name) -> dict:
                return _engine.compute_all(states)[_name]

            return IterateNode(
                scope, input_nodes, len(table._column_names), compute
            )

        raise NotImplementedError(f"unknown table spec kind {kind!r}")

    def _build_temporal_join(self, table: "Table") -> Node:
        from pathway_tpu.engine import temporal as tmp

        spec = table._spec
        kind = spec.kind
        left, right = spec.inputs
        on = spec.params["on"]
        how = spec.params["how"]
        exprs: dict[str, ColumnExpression] = spec.params["exprs"]
        scope = self.scope

        left_node = self.build(left)
        right_node = self.build(right)
        llayout = self.base_layout(left)
        rlayout = self.base_layout(right)
        nl = len(left._column_names)
        nr = len(right._column_names)
        k = len(on)

        has_time = kind in ("interval_join", "asof_join")
        # interval/asof nodes key on ONE instance value: several equality
        # conditions fold into a single tuple-valued column (exactly the
        # reference's `*on` -> join key tuple, _interval_join.py:583)
        fold = has_time and k > 1

        def prep(node, side, layout, n, time_expr):
            extras: list[eex.EngineExpression] = [eex.KeyRef()]
            if time_expr is not None:
                extras.append(self.compile(time_expr, layout))
            # explicit side index: `base is left` would misfire on
            # self-joins where left and right are the same table
            compiled = [self.compile(pair[side], layout) for pair in on]
            if fold:
                extras.append(eex.MakeTuple(compiled))
            else:
                extras.extend(compiled)
            return scope.expression_table(
                node, [eex.ColumnRef(i) for i in range(n)] + extras
            )

        lt_expr = spec.params.get("left_time")
        rt_expr = spec.params.get("right_time")
        left_prep = prep(left_node, 0, llayout, nl, lt_expr if has_time else None)
        right_prep = prep(right_node, 1, rlayout, nr, rt_expr if has_time else None)

        t_off = 1 if has_time else 0
        k_extras = 1 if fold else k
        l_inst = list(range(nl + 1 + t_off, nl + 1 + t_off + k_extras))
        r_inst = list(range(nr + 1 + t_off, nr + 1 + t_off + k_extras))

        if kind == "interval_join":
            node = tmp.IntervalJoinNode(
                scope,
                left_prep,
                right_prep,
                left_time_col=nl + 1,
                right_time_col=nr + 1,
                lower_bound=spec.params["lower_bound"],
                upper_bound=spec.params["upper_bound"],
                left_instance_col=l_inst[0] if k >= 1 else None,
                right_instance_col=r_inst[0] if k >= 1 else None,
                kind=how,
            )
        elif kind == "asof_join":
            node = tmp.AsofJoinNode(
                scope,
                left_prep,
                right_prep,
                left_time_col=nl + 1,
                right_time_col=nr + 1,
                left_instance_col=l_inst[0] if k >= 1 else None,
                right_instance_col=r_inst[0] if k >= 1 else None,
                direction=spec.params["direction"],
                kind=how,
            )
        else:
            node = tmp.AsofNowJoinNode(
                scope, left_prep, right_prep, l_inst, r_inst, kind=how
            )
        combined = Layout()
        for i, name in enumerate(left._column_names):
            combined.columns[(left._id, name)] = i
        combined.id_columns[left._id] = nl
        off = nl + 1 + t_off + k_extras
        for i, name in enumerate(right._column_names):
            combined.columns[(right._id, name)] = off + i
        combined.id_columns[right._id] = off + nr
        return scope.expression_table(
            node, [self.compile(e, combined) for e in exprs.values()]
        )

    def _build_select_with_udfs(
        self,
        expr_list: list[ColumnExpression],
        storage: Node,
        layout: Layout,
    ) -> Node:
        """Select with UDF (BatchApply) columns: plain columns evaluate in one
        expression node; each UDF column becomes a BatchApplyNode over the
        same prep node; results zip back together in output order.

        UDF calls nested inside other expressions are rejected — the engine
        batches them per commit, so they must be whole select columns
        (matching the reference's async_apply_table contract,
        src/engine/dataflow.rs:1757)."""
        scope = self.scope

        def check_no_nested(e: ColumnExpression) -> None:
            for child in e._children():
                if isinstance(child, pex.BatchApplyExpression):
                    raise NotImplementedError(
                        "async/batched UDF calls must be top-level select "
                        "columns, not nested inside other expressions"
                    )
                check_no_nested(child)

        pre_exprs: list[eex.EngineExpression] = []
        plan: list[tuple[str, Any]] = []
        for e in expr_list:
            check_no_nested(e)
            if isinstance(e, pex.BatchApplyExpression):
                arg_positions = []
                for a in (*e._args, *e._kwargs.values()):
                    pre_exprs.append(self.compile(a, layout))
                    arg_positions.append(len(pre_exprs) - 1)
                plan.append(("batch", (e, arg_positions)))
            else:
                pre_exprs.append(self.compile(e, layout))
                plan.append(("plain", len(pre_exprs) - 1))
        pre = scope.expression_table(storage, pre_exprs)
        parts: list[Node] = [pre]
        col_map: list[int] = []
        offset = len(pre_exprs)
        for tag, payload in plan:
            if tag == "plain":
                col_map.append(payload)
            else:
                e, arg_positions = payload
                node = scope.batch_apply_table(
                    pre, e._rows_fn, arg_positions, e._propagate_none
                )
                node.name = f"udf<{e._name}>"
                parts.append(node)
                col_map.append(offset)
                offset += 1
        zipped = scope.zip_tables(parts)
        return self._project(zipped, col_map)

    def _build_groupby(self, table: "Table") -> Node:
        from pathway_tpu.internals.table import Table as TableCls

        spec = table._spec
        base = spec.inputs[0]
        by_refs: list[ColumnReference] = spec.params["by"]
        exprs: dict[str, ColumnExpression] = spec.params["exprs"]
        set_id: bool = spec.params["set_id"]
        scope = self.scope

        # collect distinct reducer nodes over all output expressions
        reducer_nodes: list[pex.ReducerExpression] = []

        def collect(e: ColumnExpression) -> None:
            if isinstance(e, pex.ReducerExpression):
                if not any(e is r for r in reducer_nodes):
                    reducer_nodes.append(e)
                return
            for child in e._children():
                collect(child)

        for e in exprs.values():
            collect(e)

        arg_exprs: list[ColumnExpression] = []
        for r in reducer_nodes:
            arg_exprs.extend(r._args)

        storage, layout = self.storage_for(base, [*by_refs, *arg_exprs])
        pre_exprs: list[eex.EngineExpression] = [
            self.compile(b, layout) for b in by_refs
        ]
        nb = len(by_refs)
        reducer_descr = []
        pos = nb
        for r in reducer_nodes:
            arg_cols = list(range(pos, pos + len(r._args)))
            pre_exprs.extend(self.compile(a, layout) for a in r._args)
            pos += len(r._args)
            # ARG_MIN/ARG_MAX take (value, row-id) pairs
            from pathway_tpu.engine.reducers import ReducerKind

            if r._kind in (ReducerKind.ARG_MIN, ReducerKind.ARG_MAX):
                pre_exprs.append(eex.KeyRef())
                arg_cols = [arg_cols[0], pos]
                pos += 1
            reducer_descr.append((make_reducer(r._kind, **r._options), arg_cols))

        pre = scope.expression_table(storage, pre_exprs)
        grouped = scope.group_by_table(
            pre,
            by_cols=list(range(nb)),
            reducers=reducer_descr,
            set_id=set_id,
            instance_last=spec.params.get("instance_last", False),
        )

        # post-projection: reducer nodes -> group-row positions; by refs too
        by_positions = {(b.table._id, b.name): i for i, b in enumerate(by_refs)}

        post_layout = Layout()
        post_layout.columns.update(by_positions)

        def replace(e: ColumnExpression) -> ColumnExpression | None:
            for i, r in enumerate(reducer_nodes):
                if e is r:
                    marker = pex.ColumnConstExpression(None)
                    marker._engine_override = eex.ColumnRef(nb + i)  # type: ignore[attr-defined]
                    return marker
            return None

        post_exprs = []
        for e in exprs.values():
            substituted = substitute(e, replace)
            post_exprs.append(self.compile(substituted, post_layout))
        return scope.expression_table(grouped, post_exprs)

    def _build_join(self, table: "Table") -> Node:
        spec = table._spec
        left, right = spec.inputs
        on = spec.params["on"]
        how = spec.params["how"]
        exprs: dict[str, ColumnExpression] = spec.params["exprs"]
        scope = self.scope

        left_node = self.build(left)
        right_node = self.build(right)
        llayout = self.base_layout(left)
        rlayout = self.base_layout(right)

        nl = len(left._column_names)
        nr = len(right._column_names)
        k = len(on)

        left_prep = scope.expression_table(
            left_node,
            [eex.ColumnRef(i) for i in range(nl)]
            + [eex.KeyRef()]
            + [self.compile(le, llayout) for le, _re in on],
        )
        right_prep = scope.expression_table(
            right_node,
            [eex.ColumnRef(i) for i in range(nr)]
            + [eex.KeyRef()]
            + [self.compile(re_, rlayout) for _le, re_ in on],
        )
        id_spec = spec.params.get("id_spec")
        if id_spec is not None and id_spec[1] is not None:
            # name -> column index in the side's prep row
            side, name = id_spec
            names = (left if side == "left" else right)._column_names
            id_spec = (side, names.index(name))
        joined = scope.join_tables(
            left_prep,
            right_prep,
            left_on=list(range(nl + 1, nl + 1 + k)),
            right_on=list(range(nr + 1, nr + 1 + k)),
            kind=how,
            id_spec=id_spec,
        )
        combined = Layout()
        for i, name in enumerate(left._column_names):
            combined.columns[(left._id, name)] = i
        combined.id_columns[left._id] = nl
        off = nl + 1 + k
        for i, name in enumerate(right._column_names):
            combined.columns[(right._id, name)] = off + i
        combined.id_columns[right._id] = off + nr
        return scope.expression_table(
            joined, [self.compile(e, combined) for e in exprs.values()]
        )

    # -- execution ----------------------------------------------------------

    def run_static(self) -> Scheduler:
        sched = Scheduler(
            self.scope,
            probe=(
                self.monitor is not None
                and getattr(self.monitor, "wants_operator_stats", True)
            )
            or getattr(self, "probe_stats", False),
        )
        self.scheduler = sched  # telemetry sampler reads stats here
        if self.monitor is not None:
            self.monitor.scheduler = sched
        import time as _time

        t0 = _time.monotonic()
        sched.run_static()
        if _serving.enabled():
            _device_pipeline.drain_until(sched.time)
            _serving.publish_on_commit([self.scope], sched.time)
        if self.monitor is not None:
            self._sync_monitor_connectors()
            self.monitor.on_commit(0, t0)
        return sched

    def run(self) -> Scheduler:
        """Run to completion: static commit if no drivers, else the streaming
        loop (poll drivers, commit, until all report done)."""
        import time as _time

        from pathway_tpu.engine.graph import StaticSource

        if not self.drivers:
            return self.run_static()
        sched = Scheduler(
            self.scope,
            probe=(
                self.monitor is not None
                and getattr(self.monitor, "wants_operator_stats", True)
            )
            or getattr(self, "probe_stats", False),
        )
        self.scheduler = sched  # telemetry sampler reads stats here
        if self.monitor is not None:
            self.monitor.scheduler = sched
        persistent = [d for d in self.drivers if hasattr(d, "replay")]
        for driver in persistent:
            driver.replay()
        if persistent:
            # flush replayed events as the first commit so downstream state
            # is rebuilt even if no new input arrives
            sched.commit()
        snapshot_mgr = self._operator_snapshot_manager()
        if snapshot_mgr is not None:
            # operator persistence: restore state directly, no event replay;
            # resume the clock after the snapshotted commit so sink
            # timestamps / part names stay monotonic across restarts
            restored_time = snapshot_mgr.restore(self.scope, self.drivers)
            if restored_time is not None:
                sched.time = max(sched.time, restored_time + 1)
        for node in self.scope.nodes:
            if isinstance(node, StaticSource):
                batch = node.initial_batch()
                if batch:
                    node.push(0, batch)
        sched.propagate(sched.time)
        sched.time += 1
        def on_data() -> None:
            commit_started = _time.monotonic()
            stamp, sources = _take_ingest_stamp(self.drivers)
            rows_before = _OUT_ROWS.value
            ctx = _tracing.TRACER.begin(
                sched.time, origin_mono=stamp, sources=sources
            )
            time = sched.commit()
            _observe_commit_latency(stamp, commit_started, rows_before)
            _metrics.FLIGHT.record("commit", time=time)
            if ctx is not None:
                _tracing.TRACER.end(time)
            serving = _serving.enabled()
            if persistent or snapshot_mgr is not None or serving:
                # exactly-once seam: a checkpoint/offset for commit N may
                # only be cut once N's staged device work has completed
                # (read snapshots sit on the same seam: a published view
                # must contain all of commit N, none of N+1)
                _device_pipeline.drain_until(time)
            for driver in persistent:
                driver.on_commit(time)
            if snapshot_mgr is not None:
                snapshot_mgr.on_commit(self.scope, self.drivers, time)
            if serving:
                _serving.publish_on_commit([self.scope], time)
            if self.monitor is not None:
                self._sync_monitor_connectors()
                self.monitor.on_commit(time, commit_started)

        _pump_drivers(self, self.drivers, on_data)
        sched.finish()
        _tracing.TRACER.export()
        for driver in persistent:
            driver.on_commit(sched.time)
        if snapshot_mgr is not None:
            snapshot_mgr.snapshot(self.scope, self.drivers, sched.time)
        return sched

    def _loopback_upstream_live(self, driver, remaining) -> bool:
        """True when another still-running driver's input session can reach
        this loopback's subscribed table — its results may yet produce new
        rows for the subscription, so the loopback must stay open."""
        upstream = getattr(driver, "upstream_table", None)
        if upstream is None:
            return False
        node = self.build(upstream)
        ancestors: set[int] = set()
        stack = [node]
        while stack:
            n = stack.pop()
            if id(n) in ancestors:
                continue
            ancestors.add(id(n))
            stack.extend(n.inputs)
        for other in remaining:
            if other is driver:
                continue
            session = getattr(other, "session", None)
            inner = getattr(session, "_session", session)
            if inner is not None and id(inner) in ancestors:
                return True
        return False

    def _operator_snapshot_manager(self):
        if self.persistence is None:
            return None
        from pathway_tpu.engine.persistence import OperatorSnapshotManager
        from pathway_tpu.persistence import PersistenceMode

        if (
            getattr(self.persistence, "persistence_mode", None)
            != PersistenceMode.OPERATOR_PERSISTING
        ):
            return None
        return OperatorSnapshotManager(
            self.persistence.backend,
            getattr(self.persistence, "snapshot_interval_ms", 0),
        )

    def capture(self, *tables: "Table") -> list[dict[Pointer, tuple]]:
        from pathway_tpu.internals import parse_graph

        nodes = [self.build(t) for t in tables]
        for node in nodes:
            # capture reads node state directly, without a SubscribeNode —
            # the graph optimizer must treat these as observed sinks (no
            # fusion-inerting, no arity narrowing)
            node._pw_observed = True
        # attach + consume INTERNAL sinks only (AsyncTransformer loopback
        # subscriptions — a capture without them would deadlock); user
        # output sinks stay registered for the eventual pw.run()
        remaining = []
        for sink in parse_graph.G.sinks:
            if not sink.internal:
                remaining.append(sink)
                continue
            node = self.build(sink.table)
            driver = sink.attach(self.scope, node)
            if driver is not None:
                self.drivers.append(driver)
        parse_graph.G.sinks = remaining
        self.run()
        return [node.snapshot() for node in nodes]


class ShardedGraphRunner:
    """N logical workers, each owning a replica of the graph; batches
    exchange between operator replicas by co-location key
    (engine/sharded.py; reference worker model config.rs:63-120).

    Input connectors poll on worker 0 and reshard (reference
    dataflow.rs:3492 `scope.index() < parallel_readers`); subscribe/output
    sinks attach on worker 0 only (single-threaded sinks,
    data_storage.rs:611).
    """

    def __init__(self, n_workers: int, persistence_config: Any = None) -> None:
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        from pathway_tpu.internals.license import check_worker_count

        check_worker_count(n_workers)
        self.workers = [
            GraphRunner(
                persistence_config=persistence_config,
                attach_drivers=(i == 0),
            )
            for i in range(n_workers)
        ]
        self.n = n_workers
        self.monitor: Any = None

    def build(self, table: "Table") -> list[Node]:
        return [w.build(table) for w in self.workers]

    def _make_scheduler(self):
        from pathway_tpu.engine.sharded import ShardedScheduler

        probe = (
            self.monitor is not None
            and getattr(self.monitor, "wants_operator_stats", True)
        ) or getattr(self, "probe_stats", False)
        sched = ShardedScheduler(
            [w.scope for w in self.workers], probe=probe
        )
        self.scheduler = sched  # telemetry sampler reads stats here
        return sched

    def run(self, sched=None):
        import time as _time

        sched = sched or self._make_scheduler()
        w0 = self.workers[0]
        drivers = list(w0.drivers)  # inputs read on worker 0
        persistent = [d for d in drivers if hasattr(d, "replay")]
        for d in persistent:
            d.replay()
        scopes = [w.scope for w in self.workers]
        snapshot_mgr = w0._operator_snapshot_manager()
        if snapshot_mgr is not None:
            # per-worker operator snapshots: restore every replica's state
            # and resume the clock after the snapshotted commit
            restored_time = snapshot_mgr.restore(scopes, drivers)
            if restored_time is not None:
                sched.time = max(sched.time, restored_time + 1)
        if self.monitor is not None:
            # aggregated cross-worker operator stats (ShardedScheduler.stats)
            self.monitor.scheduler = sched
        sched.commit()

        def on_data() -> None:
            started = _time.monotonic()
            stamp, sources = _take_ingest_stamp(drivers)
            rows_before = _OUT_ROWS.value
            ctx = _tracing.TRACER.begin(
                sched.time, origin_mono=stamp, sources=sources
            )
            time = sched.commit()
            _observe_commit_latency(stamp, started, rows_before)
            _metrics.FLIGHT.record("commit", time=time)
            if ctx is not None:
                _tracing.TRACER.end(time)
            serving = _serving.enabled()
            if persistent or snapshot_mgr is not None or serving:
                # exactly-once seam: checkpoint only fully-completed commits
                _device_pipeline.drain_until(time)
            for d in persistent:
                d.on_commit(time)
            if snapshot_mgr is not None:
                snapshot_mgr.on_commit(scopes, drivers, time)
            if serving:
                # one snapshot spanning every worker replica: reads merge
                # the key-sharded views back into the synchronous answer
                _serving.publish_on_commit(scopes, time)
            if self.monitor is not None:
                w0.monitor = self.monitor
                w0._sync_monitor_connectors()
                self.monitor.on_commit(time, started)

        _pump_drivers(w0, drivers, on_data)
        sched.finish()
        if not drivers and _serving.enabled():
            # static run: the single up-front commit bypassed on_data
            _device_pipeline.drain_until(sched.time)
            _serving.publish_on_commit(scopes, sched.time)
        _tracing.TRACER.export()
        for d in persistent:
            d.on_commit(sched.time)
        if snapshot_mgr is not None:
            snapshot_mgr.snapshot(scopes, drivers, sched.time)
        return sched

    def capture(self, *tables: "Table") -> list[dict[Pointer, tuple]]:
        from pathway_tpu.internals import parse_graph

        replicas = [self.build(t) for t in tables]
        for reps in replicas:
            for node in reps:
                # capture reads replica state without a SubscribeNode; the
                # optimizer must leave these nodes intact on every worker
                node._pw_observed = True
        # internal sinks: worker 0 only; build every sink table first so
        # SubscribeNodes land after all shared nodes (index alignment)
        remaining = [s for s in parse_graph.G.sinks if not s.internal]
        internal = [s for s in parse_graph.G.sinks if s.internal]
        nodes = [self.workers[0].build(s.table) for s in internal]
        for w in self.workers[1:]:
            for s in internal:
                w.build(s.table)
        for sink, node in zip(internal, nodes):
            driver = sink.attach(self.workers[0].scope, node)
            if driver is not None:
                self.workers[0].drivers.append(driver)
        parse_graph.G.sinks = remaining
        sched = self.run()
        return [
            sched.merged_state(reps[0].index) for reps in replicas
        ]

    def attach_sinks(self) -> None:
        """Attach ALL registered sinks on worker 0 (pw.run path). All sink
        tables build FIRST so SubscribeNodes land after every shared node
        and worker replicas stay index-aligned."""
        _attach_sinks_on_primary(self.workers, attach=True)


def _attach_sinks_on_primary(workers: list, attach: bool) -> int:
    """Build every registered sink table on every worker replica (index
    alignment), then attach the actual sink drivers on worker 0's scope
    (single-threaded sinks, reference data_storage.rs:611) — or skip the
    attachment entirely (follower processes). Returns the shared graph
    length: nodes past it exist only on the attaching scope."""
    from pathway_tpu.internals import parse_graph

    sinks = list(parse_graph.G.sinks)
    nodes = [workers[0].build(s.table) for s in sinks]
    for w in workers[1:]:
        for s in sinks:
            w.build(s.table)
    n_shared = len(workers[0].scope.nodes)
    if attach:
        for sink, node in zip(sinks, nodes):
            driver = sink.attach(workers[0].scope, node)
            if driver is not None:
                workers[0].drivers.append(driver)
    parse_graph.G.sinks = []
    return n_shared


class DistributedGraphRunner:
    """Multi-process execution: the same program running in PATHWAY_PROCESSES
    processes, exchanging key-sharded batches over the TCP mesh
    (engine/distributed.py; reference CommunicationConfig::Cluster,
    config.rs:72-86, launched by `pathway spawn`, cli.py:93-107).

    Every process hosts ``threads`` local worker replicas; total workers =
    threads x processes. Process 0 is the coordinator: connector drivers
    poll there, sinks attach there, and it broadcasts commit/finish
    commands to the followers.
    """

    def __init__(
        self,
        threads: int,
        processes: int,
        process_id: int,
        first_port: int = 10000,
        persistence_config: Any = None,
    ) -> None:
        if processes < 2:
            raise ValueError("DistributedGraphRunner needs processes >= 2")
        if not 0 <= process_id < processes:
            raise ValueError(
                f"PATHWAY_PROCESS_ID={process_id} out of range for "
                f"{processes} processes"
            )
        from pathway_tpu.internals.license import check_worker_count

        check_worker_count(threads * processes)
        self.threads = threads
        self.processes = processes
        self.process_id = process_id
        self.first_port = first_port
        #: the full persistence config, kept on EVERY process: operator-
        #: persisting meshes give each process its own snapshot manager
        #: (journal/UDF-cache wiring below stays primary-only)
        self.persistence = persistence_config
        primary = process_id == 0
        self.workers = [
            GraphRunner(
                persistence_config=persistence_config if primary else None,
                attach_drivers=primary and i == 0,
            )
            for i in range(threads)
        ]
        self.monitor: Any = None
        self._epoch = 0

    def build(self, table: "Table") -> list[Node]:
        return [w.build(table) for w in self.workers]

    def attach_sinks(self) -> None:
        """Build every sink table on every local replica (index alignment
        across processes); attach actual sink drivers on process 0 only."""
        self.n_shared = _attach_sinks_on_primary(
            self.workers, attach=self.process_id == 0
        )

    def run(self):
        from pathway_tpu.engine.distributed import (
            DistributedScheduler,
            MeshTransport,
        )

        if os.environ.get("PATHWAY_TPU_RESHARD"):
            # one-shot re-shard helper (MeshSupervisor rescale): the same
            # program, launched with the NEW process count, rewrites the
            # per-process operator snapshots instead of joining a mesh
            return self._reshard_snapshots(
                int(os.environ["PATHWAY_TPU_RESHARD"])
            )
        transport = MeshTransport(
            self.process_id, self.processes, self.first_port
        )
        try:
            sched = DistributedScheduler(
                [w.scope for w in self.workers],
                self.process_id,
                self.processes,
                transport,
                # attach_sinks records the pre-attachment length; without
                # sinks, every node is shared on every replica
                n_shared=getattr(
                    self, "n_shared", len(self.workers[0].scope.nodes)
                ),
                # followers always probe: their piggybacked mesh snapshots
                # must carry per-operator series for the leader's /metrics
                # even though their own monitoring level is forced NONE
                probe=(
                    self.monitor is not None
                    and getattr(self.monitor, "wants_operator_stats", True)
                )
                or getattr(self, "probe_stats", False)
                or self.process_id != 0,
            )
            self.scheduler = sched  # telemetry sampler reads stats here
            if self.monitor is not None:
                self.monitor.scheduler = sched
                # live reference: the leader's endpoint renders follower
                # snapshots as they arrive on round frames
                self.monitor.mesh_snapshots = sched.mesh_metrics
            if self.process_id == 0:
                sched.announce_topology()
                self._coordinate(sched, transport)
            else:
                sched.receive_topology()
                self._follow(sched, transport)
            return sched
        finally:
            transport.close()

    # -- rescale -------------------------------------------------------------

    def _reshard_snapshots(self, old_processes: int):
        """Re-shard the mesh's per-process operator snapshots from
        ``old_processes`` to ``self.processes`` worker processes.

        Runs in a dedicated helper child between the quiesced old mesh and
        the relaunched new one: the graph is already built (the program ran
        normally up to ``pw.run``), so the live routing partitioners are
        available.  The helper applies the same graph-optimizer plan the
        mesh would (announce_topology + _ensure_optimized inputs), so node
        classes match the snapshot signatures."""
        import json as _json

        if self.persistence is None:
            raise RuntimeError(
                "PATHWAY_TPU_RESHARD requires persistence "
                "(PersistenceMode.OPERATOR_PERSISTING)"
            )
        scopes = [w.scope for w in self.workers]
        n_shared = getattr(self, "n_shared", len(scopes[0].nodes))
        protected = set()
        for node in scopes[0].nodes[:n_shared]:
            for consumer, _port in node.consumers:
                if consumer.index >= n_shared:
                    protected.add(node.index)
        from pathway_tpu.optimize import optimize_scopes

        optimize_scopes(scopes, n_shared=n_shared, protected=protected)
        from pathway_tpu.engine.persistence import (
            reshard_process_snapshots,
        )

        report = reshard_process_snapshots(
            self.persistence.backend,
            old_processes,
            self.processes,
            self.threads,
            scopes,
            n_shared=n_shared,
        )
        _metrics.FLIGHT.record("reshard", **report)
        print("PATHWAY_RESHARD_JSON " + _json.dumps(report), flush=True)
        return None

    # -- fault tolerance ----------------------------------------------------

    def _note_epoch(self) -> None:
        _metrics.REGISTRY.gauge(
            "pathway_mesh_epoch",
            "current mesh recovery epoch (bumped by every recovery or "
            "leader election; frames from older epochs are fenced)",
        ).set(self._epoch)

    def _report_rescale_metrics(self) -> None:
        """A leader relaunched after ``MeshSupervisor.rescale`` carries
        the supervisor's rescale stamps in its environment: surface them
        as metric families on this (fresh) process's registry so the
        leader ``/metrics`` reports the cumulative rescale history."""
        try:
            rescales = int(os.environ.get("PATHWAY_TPU_RESCALED", "0"))
        except ValueError:
            rescales = 0
        if rescales <= 0:
            return
        _metrics.REGISTRY.counter(
            "pathway_mesh_rescales_total",
            "completed N->M mesh rescales (quiesce + re-shard + relaunch)",
        ).inc(rescales)
        try:
            wall = float(os.environ.get("PATHWAY_TPU_RESCALE_WALL_S", ""))
        except ValueError:
            wall = None
        if wall is not None:
            _metrics.REGISTRY.histogram(
                "pathway_mesh_rescale_seconds",
                "wall time of the most recent rescale, quiesce request "
                "to relaunch",
                buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
            ).observe(wall)

    def _snapshot_manager(self):
        """Per-process operator snapshot manager, or None when persistence
        is absent / not OPERATOR_PERSISTING.  Every process snapshots its
        OWN replica states under a process-qualified name, keeping a small
        ring of recent commits so the mesh can roll back to a COMMON one."""
        if self.persistence is None:
            return None
        from pathway_tpu.engine.persistence import OperatorSnapshotManager
        from pathway_tpu.persistence import PersistenceMode

        if (
            getattr(self.persistence, "persistence_mode", None)
            != PersistenceMode.OPERATOR_PERSISTING
        ):
            return None
        return OperatorSnapshotManager(
            self.persistence.backend,
            getattr(self.persistence, "snapshot_interval_ms", 0),
            name=f"operator-snapshot-p{self.process_id}",
            retain=3,
        )

    @staticmethod
    def _recovery_enabled(snapshot_mgr) -> bool:
        """Worker recovery is OPT-IN: it needs both the env switch and an
        operator-snapshot backend.  Everything else fail-stops, exactly as
        before this layer existed."""
        return snapshot_mgr is not None and os.environ.get(
            "PATHWAY_TPU_RECOVER", ""
        ).lower() in ("1", "true", "yes")

    @staticmethod
    def _recover_deadline() -> float:
        try:
            return max(
                1.0,
                float(os.environ.get("PATHWAY_TPU_RECOVER_DEADLINE", "60")),
            )
        except ValueError:
            return 60.0

    @staticmethod
    def _fault_plan():
        if not os.environ.get("PATHWAY_TPU_FAULT_PLAN"):
            return None
        from pathway_tpu.engine.faults import active_plan

        return active_plan()

    @staticmethod
    def _request_kill(peer: int) -> None:
        """Ask the MeshSupervisor (if one launched this mesh) to SIGKILL a
        suspected-hung worker so the death→restart path takes over; a
        no-op without a supervisor (the caller then fail-stops on the
        reestablish deadline)."""
        sup_dir = os.environ.get("PATHWAY_TPU_SUPERVISOR_DIR")
        if not sup_dir:
            return
        try:
            with open(
                os.path.join(sup_dir, f"kill-{peer}"), "w"
            ) as fh:
                fh.write(str(os.getpid()))
        except OSError:
            pass

    def _rewind_sinks(self, to_time: int) -> None:
        """Truncate file sinks past the rollback point so re-driven
        commits land exactly once.  Callback sinks (pw.io.subscribe) have
        no rewind seam: re-driven commits reach them at-least-once — a
        documented recovery limit."""
        from pathway_tpu.engine.connectors import FILE_WRITERS

        for writer in list(FILE_WRITERS):
            writer.rewind_to(to_time)

    def _recover_mesh(
        self, sched, transport, snapshot_mgr, dead_peer: int, drivers: list
    ) -> None:
        """Leader-side recovery: park survivors, get the dead worker
        restarted (supervisor), re-mesh, re-handshake, roll every process
        back to the restarted worker's snapshot, and resync the links."""
        import time as _time

        t0 = _time.monotonic()
        self._epoch += 1
        epoch = self._epoch
        self._note_epoch()
        _metrics.FLIGHT.record(
            "peer_dead", peer=dead_peer, time=sched.time, epoch=epoch
        )
        _metrics.FLIGHT.dump(f"peer {dead_peer} lost (leader view)")
        # abandon the in-flight sampled trace AFTER the dump, so the dump
        # references its trace id; drop the dead incarnation's piggybacked
        # metrics snapshot and spans so the aggregated /metrics stops
        # rendering stale worker label sets
        _tracing.TRACER.drop()
        sched.mesh_metrics.pop(dead_peer, None)
        sched.trace_peer_spans.pop(dead_peer, None)
        _profiling.PROFILER.prune(dead=(dead_peer,))
        _timeseries.STORE.prune_workers(dead={str(dead_peer)})
        _metrics.FLIGHT.record(
            "recovery_start", peer=dead_peer, epoch=epoch
        )
        deadline = self._recover_deadline()
        # survivors park in `recovering` (their own PeerLostError or this
        # command gets them there) and re-mesh toward the restarted worker
        for peer in sorted(sched._outbox):
            if peer == dead_peer or peer in transport.dead_peers:
                continue
            transport.send(peer, ("cmd", "recover", dead_peer, epoch))
        # a hung (not dead) worker must actually die before its restart
        # can bind the exchange port again
        self._request_kill(dead_peer)
        detect_s = _time.monotonic() - t0
        transport.reestablish(dead_peer, deadline=deadline)
        sched.reannounce_to(dead_peer)
        frame = transport.recv(dead_peer, timeout=deadline)
        if not (
            isinstance(frame, tuple) and frame and frame[0] == "rejoin"
        ):
            raise RuntimeError(
                f"process 0: expected the restarted worker {dead_peer}'s "
                f"rejoin frame, got {frame!r}"
            )
        rejoin_time = int(frame[1])
        if rejoin_time < 0:
            raise RuntimeError(
                f"process 0: restarted worker {dead_peer} has no operator "
                "snapshot to resume from (it died before its first commit "
                "boundary); cold-starting one worker of a warm mesh would "
                "diverge state — fail-stop"
            )
        transport.broadcast(("cmd", "rollback", rejoin_time, epoch))
        sched.rollback(rejoin_time, snapshot_mgr, drivers)
        self._rewind_sinks(rejoin_time)
        sched.resync(epoch)
        _metrics.REGISTRY.counter(
            "pathway_mesh_recoveries_total",
            "mesh-wide recoveries completed after a worker loss",
        ).inc(1)
        _metrics.FLIGHT.record(
            "recovery_done",
            peer=dead_peer,
            epoch=epoch,
            to_time=rejoin_time,
            detect_s=round(detect_s, 6),
            wall_s=round(_time.monotonic() - t0, 6),
        )
        _metrics.FLIGHT.dump(f"peer {dead_peer} recovered (leader view)")

    # -- the two run loops --------------------------------------------------

    def _coordinate(self, sched, transport) -> None:
        import time as _time

        from pathway_tpu.engine.distributed import (
            RECV_TIMEOUT,
            PeerLostError,
        )

        w0 = self.workers[0]
        drivers = list(w0.drivers)
        persistent = [d for d in drivers if hasattr(d, "replay")]
        for d in persistent:
            d.replay()
        snapshot_mgr = self._snapshot_manager()
        recovery = self._recovery_enabled(snapshot_mgr)
        fault_plan = self._fault_plan()
        self._report_rescale_metrics()
        common = -1
        if snapshot_mgr is not None:
            # startup rejoin protocol: collect every follower's latest
            # snapshot time, roll the whole mesh back to the oldest
            # common commit, then barrier — a plain cold start runs the
            # same path with T = -1.  Rejoin frames carry each survivor's
            # mesh epoch: a leader restarted after failover must resume
            # ABOVE the epochs the survivors advanced to, or its rollback
            # command would be rejected by their fences as a zombie's.
            times = [snapshot_mgr.latest_time()]
            peer_epochs = [0]
            for peer in sorted(sched._outbox):
                frame = transport.recv(peer)
                if not (
                    isinstance(frame, tuple)
                    and frame
                    and frame[0] == "rejoin"
                ):
                    raise RuntimeError(
                        f"process 0: expected peer {peer}'s rejoin frame, "
                        f"got {frame!r}"
                    )
                times.append(frame[1])
                peer_epochs.append(
                    int(frame[2]) if len(frame) >= 3 else 0
                )
            common = min(
                (t if t is not None else -1) for t in times
            )
            self._epoch = max([self._epoch] + peer_epochs) + 1
            self._note_epoch()
            transport.broadcast(("cmd", "rollback", common, self._epoch))
            sched.fence.admit("rollback", self._epoch)
            sched.rollback(common, snapshot_mgr, drivers)
            # the resumed sink files may carry commits newer than the
            # mesh's last COMMON snapshot (a cold restart lost them):
            # truncate so re-driven commits land exactly once
            self._rewind_sinks(common)
            sched.resync(self._epoch)
        quiesce_path = None
        sup_dir = os.environ.get("PATHWAY_TPU_SUPERVISOR_DIR")
        if sup_dir and snapshot_mgr is not None:
            quiesce_path = os.path.join(sup_dir, "quiesce")

        def maybe_quiesce(committed_time: int | None) -> None:
            """Service a supervisor rescale request: stop at a commit
            boundary, force a durable snapshot of it on every process,
            and exit with the quiesce code so the supervisor can re-shard
            and relaunch."""
            if quiesce_path is None or not os.path.exists(quiesce_path):
                return
            from pathway_tpu.engine.supervisor import EXIT_QUIESCED

            try:
                if committed_time is None:
                    # idle stream: every polled row has been committed
                    # (on_data commits per poll batch), so the current
                    # state IS the state at the last commit — quiesce
                    # there rather than cutting an empty commit, which
                    # would shift later commit timestamps off the
                    # uninterrupted run's and break sink bit-identity.
                    # sched.time is the NEXT commit's stamp; the last
                    # committed boundary is one behind it.
                    committed_time = sched.time - 1
                transport.broadcast(("cmd", "quiesce", committed_time))
            except PeerLostError:
                # a peer died mid-quiesce: skip this attempt and let the
                # ordinary recovery paths run — the marker file stays, so
                # quiesce retries at the next boundary after recovery
                return
            snapshot_mgr.snapshot(sched.scopes, drivers, committed_time)
            _metrics.FLIGHT.record(
                "quiesce", time=committed_time, process=self.process_id
            )
            _metrics.FLIGHT.dump("quiesced for rescale")
            raise SystemExit(EXIT_QUIESCED)

        if common < 0:
            # fresh start: the initial barrier commit establishes time 1
            # and flushes static sources.  A mesh RESUMED from a common
            # snapshot must skip it — the restored state is already at
            # the rollback boundary, and an extra (empty) commit here
            # would shift every later commit timestamp off the
            # uninterrupted run's numbering, breaking sink bit-identity.
            transport.broadcast(("cmd", "commit"))
            barrier_time = sched.commit_local()
            if snapshot_mgr is not None:
                # followers snapshot EVERY commit (including this one);
                # the leader must too, or a worker that dies before the
                # first data commit forces a rollback to a boundary the
                # leader cannot restore.  Same exactly-once seam as the
                # data path: the barrier commit flushes static sources,
                # which can stage device work this snapshot must contain
                _device_pipeline.drain_until(barrier_time)
                snapshot_mgr.on_commit(sched.scopes, drivers, barrier_time)
        last_sign_of_life = _time.monotonic()

        def on_data() -> None:
            nonlocal last_sign_of_life
            started = _time.monotonic()
            try:
                transport.raise_if_peer_dead()
                stamp, sources = _take_ingest_stamp(drivers)
                rows_before = _OUT_ROWS.value
                # begin BEFORE the broadcast: the context tuple rides the
                # first exchange round's frames so followers adopt it at
                # commit start
                ctx = _tracing.TRACER.begin(
                    sched.time, origin_mono=stamp, sources=sources
                )
                transport.broadcast(("cmd", "commit"))
                time = sched.commit_local()
            except PeerLostError as exc:
                if not recovery or exc.peer is None or exc.peer == 0:
                    raise
                self._recover_mesh(
                    sched, transport, snapshot_mgr, exc.peer, drivers
                )
                return  # the rolled-back commit re-drives on the next poll
            if ctx is not None:
                _tracing.TRACER.end(
                    time, peer_spans=dict(sched.trace_peer_spans)
                )
                sched.trace_peer_spans.clear()
            _observe_commit_latency(stamp, started, rows_before)
            serving = _serving.enabled()
            if persistent or snapshot_mgr is not None or serving:
                # exactly-once seam: checkpoint only fully-completed commits
                _device_pipeline.drain_until(time)
            for d in persistent:
                d.on_commit(time)
            if snapshot_mgr is not None:
                snapshot_mgr.on_commit(sched.scopes, drivers, time)
            if serving:
                # leader publishes its own shard; followers publish theirs
                # in _follow — rollback republication truncates stale views
                _serving.publish_on_commit(sched.scopes, time)
            if fault_plan is not None:
                fault_plan.on_commit(self.process_id, time)
            if self.monitor is not None:
                w0.monitor = self.monitor
                w0._sync_monitor_connectors()
                self.monitor.on_commit(time, started)
            last_sign_of_life = started
            maybe_quiesce(time)

        # pings must always undercut the followers' recv timeout, or a
        # quiet stream trips spurious peer-crash errors
        ping_every = min(30.0, RECV_TIMEOUT / 2.0)

        def on_idle() -> None:
            # fail-stop promptly when a peer's socket closed — the
            # send path alone needs TWO sends after the RST to notice
            nonlocal last_sign_of_life
            try:
                transport.raise_if_peer_dead()
            except PeerLostError as exc:
                if not recovery or exc.peer is None or exc.peer == 0:
                    raise
                self._recover_mesh(
                    sched, transport, snapshot_mgr, exc.peer, drivers
                )
                last_sign_of_life = _time.monotonic()
                return
            maybe_quiesce(None)
            # keep follower recv timeouts from tripping during long quiet
            # stretches of a streaming run
            if _time.monotonic() - last_sign_of_life > ping_every:
                transport.broadcast(("cmd", "ping"))
                last_sign_of_life = _time.monotonic()

        _pump_drivers(w0, drivers, on_data, on_idle)
        transport.broadcast(("cmd", "finish"))
        sched.finish_local()
        _tracing.TRACER.export()  # leader holds the assembled mesh traces
        for d in persistent:
            d.on_commit(sched.time)
        if snapshot_mgr is not None:
            snapshot_mgr.snapshot(sched.scopes, drivers, sched.time)

    def _follow(self, sched, transport) -> None:
        from pathway_tpu.engine.distributed import PeerLostError

        snapshot_mgr = self._snapshot_manager()
        recovery = self._recovery_enabled(snapshot_mgr)
        fault_plan = self._fault_plan()
        deadline = self._recover_deadline()
        if snapshot_mgr is not None:
            latest = snapshot_mgr.latest_time()
            transport.send(
                0,
                ("rejoin", latest if latest is not None else -1,
                 self._epoch),
            )
        while True:
            try:
                frame = transport.recv(0)
            except PeerLostError:
                # the leader itself died or hung: dump forensics and —
                # with recovery on — elect an interim leader, take over
                # its duties, and rejoin its restarted successor
                self._leader_failover(sched, transport, snapshot_mgr)
                continue
            kind = frame[0]
            if kind != "cmd":
                raise RuntimeError(
                    f"process {self.process_id}: expected a coordinator "
                    f"command, got {kind!r}"
                )
            cmd = frame[1]
            if cmd == "ping":
                # answer so the leader's suspicion clock sees an idle-but-
                # alive follower (absorbed by its receiver thread)
                transport.heartbeat(0)
                continue
            if cmd == "commit":
                try:
                    time = sched.commit_local()
                except PeerLostError as exc:
                    if exc.peer == 0 or 0 in transport.dead_peers:
                        self._leader_failover(
                            sched, transport, snapshot_mgr
                        )
                        continue
                    if not recovery or exc.peer is None:
                        raise
                    try:
                        self._park_for_recovery(sched, transport, exc.peer)
                    except PeerLostError as parked:
                        # the leader died while this survivor was parked
                        # waiting for its recovery command
                        if parked.peer == 0 or 0 in transport.dead_peers:
                            self._leader_failover(
                                sched, transport, snapshot_mgr
                            )
                        else:
                            raise
                    continue
                serving = _serving.enabled()
                if snapshot_mgr is not None or serving:
                    # exactly-once seam (follower): a per-worker snapshot
                    # for commit N waits for N's staged device work
                    _device_pipeline.drain_until(time)
                    if snapshot_mgr is not None:
                        snapshot_mgr.on_commit(sched.scopes, [], time)
                    if serving:
                        _serving.publish_on_commit(sched.scopes, time)
                if fault_plan is not None:
                    fault_plan.on_commit(self.process_id, time)
            elif cmd == "recover":
                # a peer died; this follower survived without noticing
                # (or already parked — _park_for_recovery consumed the
                # command and re-meshed; this branch is the idle path).
                # Fencing makes fault-injected duplicates no-ops.
                if not sched.fence.admit("recover", frame[3]):
                    continue
                _dead = frame[2]
                _metrics.FLIGHT.record(
                    "peer_dead",
                    peer=_dead,
                    time=sched.time,
                    epoch=frame[3],
                )
                _metrics.FLIGHT.dump(
                    f"peer {_dead} lost (survivor view)"
                )
                transport.reestablish(_dead, deadline=deadline)
                _metrics.FLIGHT.record(
                    "recovery_remesh", peer=_dead, epoch=frame[3]
                )
            elif cmd == "rollback":
                # a re-processed rollback would deadlock in resync, so a
                # zombie ex-leader's (or a duplicated) command is fenced
                if not sched.fence.admit("rollback", frame[3]):
                    continue
                self._epoch = max(self._epoch, int(frame[3]))
                self._note_epoch()
                sched.rollback(frame[2], snapshot_mgr, [])
                sched.resync(frame[3])
            elif cmd == "quiesce":
                from pathway_tpu.engine.supervisor import EXIT_QUIESCED

                if snapshot_mgr is not None:
                    snapshot_mgr.snapshot(sched.scopes, [], frame[2])
                _metrics.FLIGHT.record(
                    "quiesce", time=frame[2], process=self.process_id
                )
                _metrics.FLIGHT.dump("quiesced for rescale")
                raise SystemExit(EXIT_QUIESCED)
            elif cmd == "finish":
                sched.finish_local()
                if snapshot_mgr is not None:
                    snapshot_mgr.snapshot(sched.scopes, [], sched.time)
                return
            else:
                raise RuntimeError(f"unknown coordinator command {cmd!r}")

    def _leader_failover(self, sched, transport, snapshot_mgr) -> None:
        """Follower-side response to losing the leader (process 0).

        Every survivor dumps its flight ring first — leader loss must
        leave forensics whether or not failover is possible.  With
        recovery off that is the whole story: fail-stop, and the
        supervisor reports EXIT_LEADER_LOST.

        With recovery on, survivors run a deterministic epoch-stamped
        election: the lowest live rank becomes the *interim leader* and
        takes over the leader-only duties that cannot wait for the
        restart — the supervisor kill request (a HUNG ex-leader must
        actually die before its successor can bind the exchange port)
        and the aggregation of survivor metrics snapshots.  Everyone
        then re-meshes toward the supervisor-restarted process 0,
        re-runs the topology handshake against it, and sends an
        epoch-stamped rejoin; the restarted leader resumes coordination
        (rollback to the last common commit) above the survivors'
        epoch, so any frame a zombie ex-leader manages to flush is
        rejected by the epoch fence (and its replaced socket).  A
        cascading survivor death during the window fail-stops on the
        election deadline."""
        import time as _time

        from pathway_tpu.engine.distributed import (
            PeerLostError,
            elect_leader,
        )

        recovery = self._recovery_enabled(snapshot_mgr)
        last_seen = getattr(transport, "last_seen", {}).get(0)
        _metrics.FLIGHT.record(
            "leader_dead",
            process=self.process_id,
            time=sched.time,
            epoch=self._epoch,
            recovery=recovery,
            # silence on the leader link before it was declared dead —
            # the detection latency (suspicion timeout or socket close)
            detect_s=(
                None
                if last_seen is None
                else round(_time.monotonic() - last_seen, 6)
            ),
        )
        _metrics.FLIGHT.dump("leader (process 0) lost")
        _tracing.TRACER.drop()  # after the dump — it references the id
        if not recovery:
            raise PeerLostError(
                f"process {self.process_id}: leader (process 0) lost "
                "and recovery is disabled — fail-stop (flight ring "
                "dumped)",
                peer=0,
            )
        t0 = _time.monotonic()
        deadline = self._recover_deadline()
        end = t0 + deadline
        survivors = sorted(
            p
            for p in range(self.processes)
            if p != 0 and p not in transport.dead_peers
        )
        epoch = self._epoch + 1
        interim = elect_leader(survivors)
        others = [p for p in survivors if p != self.process_id]
        latest = snapshot_mgr.latest_time()
        latest = -1 if latest is None else latest
        if self.process_id == interim:
            # interim leader inherits /metrics aggregation: start from a
            # clean slate so the dead leader's (and any other dead
            # incarnation's) worker label sets don't linger in the
            # rendered exposition
            sched.prune_mesh_metrics(dead=(0,))
            for peer in others:
                transport.send(peer, ("elect", epoch, interim))
            rejoin_times = [latest]
            for peer in others:
                # collect the survivor's ack, absorbing round/abort
                # debris its broken commit may have left on the link
                while True:
                    remaining = max(0.1, end - _time.monotonic())
                    frame = transport.recv(peer, timeout=remaining)
                    if (
                        isinstance(frame, tuple)
                        and len(frame) >= 4
                        and frame[0] == "elect-ack"
                        and frame[1] == epoch
                    ):
                        break
                rejoin_times.append(frame[2])
                if frame[3] is not None:
                    # the ack carries the survivor's metrics snapshot with
                    # an optional piggybacked profiler payload — route the
                    # sidecar to the new leader's profile aggregation so
                    # `cli profile` keeps covering the mesh across failover
                    peer_profile = frame[3].pop("__profile__", None)
                    if peer_profile is not None:
                        _profiling.PROFILER.absorb(peer, peer_profile)
                    sched.mesh_metrics[peer] = frame[3]
            self._request_kill(0)
            _metrics.REGISTRY.counter(
                "pathway_mesh_elections_total",
                "leader elections completed after losing process 0",
            ).inc(1)
            _metrics.REGISTRY.histogram(
                "pathway_mesh_election_seconds",
                "leader-loss detection to election-complete wall time",
                buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0),
            ).observe(_time.monotonic() - t0)
            _metrics.FLIGHT.record(
                "election_done",
                interim=interim,
                epoch=epoch,
                survivors=survivors,
                rollback_target=min(rejoin_times),
                wall_s=round(_time.monotonic() - t0, 6),
            )
        else:
            while True:
                remaining = end - _time.monotonic()
                if remaining <= 0:
                    raise PeerLostError(
                        f"process {self.process_id}: no election from "
                        f"interim leader {interim} within {deadline:g}s "
                        "of losing the leader — fail-stop",
                        peer=interim,
                    )
                try:
                    frame = transport.recv(
                        interim, timeout=min(remaining, 1.0)
                    )
                except PeerLostError:
                    if interim in transport.dead_peers:
                        raise  # cascade: the interim died too
                    continue  # poll timeout: keep waiting
                if (
                    isinstance(frame, tuple)
                    and len(frame) >= 3
                    and frame[0] == "elect"
                    and frame[1] > self._epoch
                ):
                    epoch = int(frame[1])
                    break
            transport.send(
                interim,
                ("elect-ack", epoch, latest,
                 sched._metrics_snapshot()),
            )
        self._epoch = epoch
        self._note_epoch()
        sched.fence.admit("elect", epoch)
        # re-mesh toward the restarted process 0 and re-run the startup
        # handshake; the normal follow loop takes the rollback from there
        transport.reestablish(
            0, deadline=max(1.0, end - _time.monotonic())
        )
        sched.receive_topology()
        transport.send(0, ("rejoin", latest, self._epoch))
        _metrics.FLIGHT.record(
            "leader_failover_done",
            process=self.process_id,
            epoch=self._epoch,
            wall_s=round(_time.monotonic() - t0, 6),
        )
        # second dump so the on-disk forensics cover the whole failover
        # lifecycle (the first dump happened at leader_dead, before the
        # election outcome existed)
        _metrics.FLIGHT.dump("leader failover complete")

    def _park_for_recovery(self, sched, transport, dead_peer: int) -> None:
        """Survivor path when a peer dies MID-COMMIT: dump forensics, then
        park in `recovering` — drain the leader link (with backoff, under
        a bounded deadline) until its recover command arrives, and re-mesh
        toward the restarted worker.  The subsequent rollback command is
        handled by the normal follow loop."""
        import random as _random
        import time as _time

        from pathway_tpu.engine.distributed import PeerLostError

        _metrics.FLIGHT.record(
            "peer_dead", peer=dead_peer, time=sched.time
        )
        _metrics.FLIGHT.dump(f"peer {dead_peer} lost (survivor view)")
        _tracing.TRACER.drop()  # after the dump — it references the id
        _metrics.FLIGHT.record("recovery_parked", peer=dead_peer)
        deadline = self._recover_deadline()
        end = _time.monotonic() + deadline
        wait = 0.05
        frame = sched._pending_recover
        sched._pending_recover = None
        while True:
            if frame is not None:
                if (
                    isinstance(frame, tuple)
                    and len(frame) >= 4
                    and frame[0] == "cmd"
                    and frame[1] == "recover"
                ):
                    # a duplicated (fault-injected or zombie-leader)
                    # recover from an already-handled epoch is fenced;
                    # a fresh one advances the fence so the idle-path
                    # handler won't re-run it
                    if sched.fence.admit("recover", frame[3]):
                        break
                    frame = None
                    continue
                # stale commit/round debris from the aborted exchange
                frame = None
            remaining = end - _time.monotonic()
            if remaining <= 0:
                raise PeerLostError(
                    f"process {self.process_id}: no recovery command "
                    f"within {deadline:g}s of losing peer {dead_peer} — "
                    "fail-stop",
                    peer=dead_peer,
                )
            try:
                frame = transport.recv(
                    0, timeout=min(remaining, wait)
                )
            except PeerLostError:
                if 0 in transport.dead_peers:
                    raise  # the leader itself is gone: fatal
                frame = None  # just a poll timeout: keep waiting
            wait = min(wait * 2, 1.0) * (0.75 + 0.5 * _random.random())
        transport.reestablish(frame[2], deadline=deadline)
        _metrics.FLIGHT.record(
            "recovery_remesh", peer=frame[2], epoch=frame[3]
        )
