"""Retry strategies for UDF execution.

Reference: python/pathway/internals/udfs/retries.py:58,107
(ExponentialBackoffRetryStrategy / FixedDelayRetryStrategy).
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Any, Awaitable, Callable


class AsyncRetryStrategy:
    """Base: no retries."""

    async def invoke(self, fn: Callable[[], Awaitable[Any]]) -> Any:
        return await fn()

    def invoke_sync(self, fn: Callable[[], Any]) -> Any:
        return fn()


class NoRetryStrategy(AsyncRetryStrategy):
    pass


class ExponentialBackoffRetryStrategy(AsyncRetryStrategy):
    """Retry with exponentially growing delay + uniform jitter."""

    def __init__(
        self,
        max_retries: int = 3,
        initial_delay: int = 1_000,  # milliseconds, matching the reference
        backoff_factor: float = 2.0,
        jitter_ms: int = 300,
    ) -> None:
        self.max_retries = max_retries
        self.initial_delay = initial_delay / 1000.0
        self.backoff_factor = backoff_factor
        self.jitter = jitter_ms / 1000.0

    def _delays(self):
        delay = self.initial_delay
        for _ in range(self.max_retries):
            yield delay + random.uniform(0, self.jitter)
            delay *= self.backoff_factor

    async def invoke(self, fn: Callable[[], Awaitable[Any]]) -> Any:
        last: Exception | None = None
        try:
            return await fn()
        except Exception as e:  # noqa: BLE001
            last = e
        for delay in self._delays():
            await asyncio.sleep(delay)
            try:
                return await fn()
            except Exception as e:  # noqa: BLE001
                last = e
        assert last is not None
        raise last

    def invoke_sync(self, fn: Callable[[], Any]) -> Any:
        last: Exception | None = None
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            last = e
        for delay in self._delays():
            time.sleep(delay)
            try:
                return fn()
            except Exception as e:  # noqa: BLE001
                last = e
        assert last is not None
        raise last


class FixedDelayRetryStrategy(ExponentialBackoffRetryStrategy):
    """Retry with a constant delay between attempts."""

    def __init__(self, max_retries: int = 3, delay_ms: int = 1_000) -> None:
        super().__init__(
            max_retries=max_retries,
            initial_delay=delay_ms,
            backoff_factor=1.0,
            jitter_ms=0,
        )
