"""UDF subsystem: ``pw.udf`` with executors, caching and retries.

Reference: python/pathway/internals/udfs/__init__.py:68 (UDF class),
executors.py, caches.py, retries.py. A UDF call inside ``select`` lowers to
the engine's BatchApplyNode, which hands whole commit-batches of rows to the
executor — so async UDFs (LLM calls) run concurrently and device UDFs
(jit embedders/rerankers) get microbatches instead of rows.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

from pathway_tpu.internals.expression import (
    BatchApplyExpression,
    ColumnExpression,
)
from pathway_tpu.internals.udfs.caches import (
    CacheStrategy,
    DefaultCache,
    DiskCache,
    InMemoryCache,
)
from pathway_tpu.internals.udfs.executors import (
    AsyncExecutor,
    BatchExecutor,
    Executor,
    SyncExecutor,
    async_executor,
    auto_executor,
    batch_executor,
    make_kw_fn,
    sync_executor,
)
from pathway_tpu.internals.udfs.retries import (
    AsyncRetryStrategy,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    NoRetryStrategy,
)
from pathway_tpu.internals.udfs.caches import _digest, fn_cache_name


class UDF:
    """A callable lowered to engine batch execution when used in ``select``.

    Subclass with ``__wrapped__`` or pass ``fn``; calling it with column
    expressions builds the expression node.
    """

    def __init__(
        self,
        fn: Callable[..., Any] | None = None,
        *,
        return_type: Any = None,
        deterministic: bool = False,
        propagate_none: bool = False,
        executor: Executor | None = None,
        cache_strategy: CacheStrategy | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        max_batch_size: int | None = None,
        cache_name: str | None = None,
    ) -> None:
        """``cache_name`` qualifies cache keys for closure-configured UDFs:
        two instances wrapping the same closure code but different captured
        config (model name, params) MUST pass distinct cache_names or they
        will share cached results."""
        if fn is None:
            fn = getattr(self, "__wrapped__", None)
        if fn is None:
            raise TypeError("UDF needs a function")
        self._fn = fn
        self._name = getattr(fn, "__name__", "udf")
        self._return_type = return_type
        self._deterministic = deterministic
        self._propagate_none = propagate_none
        if executor is None:
            executor = auto_executor(fn)
        if max_batch_size is not None:
            if not isinstance(executor, BatchExecutor):
                raise ValueError(
                    "max_batch_size requires a batch executor "
                    "(pw.udfs.batch_executor())"
                )
            # fresh instance: never mutate a caller-shared executor
            executor = BatchExecutor(max_batch_size=max_batch_size)
        self._executor = executor
        self._cache = cache_strategy
        self._retry = retry_strategy
        self._cache_name = cache_name or fn_cache_name(fn)

    def __call__(self, *args: Any, **kwargs: Any) -> ColumnExpression:
        rows_fn = functools.partial(
            self.execute_rows, n_pos=len(args), kw_names=tuple(kwargs)
        )
        return BatchApplyExpression(
            rows_fn,
            self._return_type,
            args,
            kwargs,
            propagate_none=self._propagate_none,
            deterministic=self._deterministic,
            name=self._name,
        )

    def _call_fn(self, n_pos: int, kw_names: tuple) -> Callable[..., Any]:
        return make_kw_fn(self._fn, n_pos, list(kw_names))

    # -- engine entry point --------------------------------------------------

    def execute_rows(
        self,
        rows: list[tuple],
        n_pos: int | None = None,
        kw_names: tuple = (),
    ) -> list[tuple[bool, Any]]:
        """(ok, value) per row; cache consulted before the executor runs."""
        fn = self._call_fn(n_pos if n_pos is not None else len(rows[0]), kw_names)
        if self._cache is None:
            return self._executor.run(fn, rows, self._retry)
        results: list[tuple[bool, Any] | None] = [None] * len(rows)
        missing: list[int] = []
        keys: list[str] = []
        for i, args in enumerate(rows):
            key = _digest(self._cache_name, args)
            keys.append(key)
            hit = self._cache.get(key)
            if CacheStrategy.missing(hit):
                missing.append(i)
            else:
                results[i] = (True, hit)
        if missing:
            # dedupe identical pending args within the batch: one compute
            # per distinct cache key
            unique: dict[str, list[int]] = {}
            for i in missing:
                unique.setdefault(keys[i], []).append(i)
            reps = [idxs[0] for idxs in unique.values()]
            computed = self._executor.run(
                fn, [rows[i] for i in reps], self._retry
            )
            for rep, res in zip(reps, computed):
                for i in unique[keys[rep]]:
                    results[i] = res
                if res[0]:
                    self._cache.put(keys[rep], res[1])
        return [r for r in results if r is not None]


def udf(
    fn: Callable[..., Any] | None = None,
    /,
    *,
    return_type: Any = None,
    deterministic: bool = False,
    propagate_none: bool = False,
    executor: Executor | None = None,
    cache_strategy: CacheStrategy | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
    max_batch_size: int | None = None,
) -> Any:
    """``@pw.udf`` decorator (reference: udfs/__init__.py:68)."""

    def make(f: Callable[..., Any]) -> UDF:
        u = UDF(
            f,
            return_type=return_type,
            deterministic=deterministic,
            propagate_none=propagate_none,
            executor=executor,
            cache_strategy=cache_strategy,
            retry_strategy=retry_strategy,
            max_batch_size=max_batch_size,
        )
        functools.update_wrapper(u, f, updated=())
        return u

    if fn is not None:
        return make(fn)
    return make


__all__ = [
    "AsyncExecutor",
    "AsyncRetryStrategy",
    "BatchExecutor",
    "CacheStrategy",
    "DefaultCache",
    "DiskCache",
    "ExponentialBackoffRetryStrategy",
    "Executor",
    "FixedDelayRetryStrategy",
    "InMemoryCache",
    "NoRetryStrategy",
    "SyncExecutor",
    "UDF",
    "async_executor",
    "auto_executor",
    "batch_executor",
    "sync_executor",
    "udf",
]
