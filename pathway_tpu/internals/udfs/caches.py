"""UDF result caches.

Reference: python/pathway/internals/udfs/caches.py:35,120 (DiskCache via the
diskcache lib, InMemoryCache). Here DiskCache is a dependency-free
content-addressed pickle directory, so it doubles as the UDF-caching
persistence mode (reference PersistenceMode::UdfCaching).
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Callable  # noqa: F401 — Callable used in fn_cache_name

_SENTINEL = object()


def _digest(name: str, args: tuple) -> str:
    """``name`` must uniquely identify the UDF (see UDF._cache_name: it
    includes module, qualname and a code hash so same-named UDFs or edited
    code never collide in a shared disk cache)."""
    try:
        payload = pickle.dumps((name, args), protocol=4)
    except Exception:  # unpicklable args — hash reprs
        payload = repr((name, args)).encode()
    return hashlib.sha256(payload).hexdigest()


def fn_cache_name(fn: Callable) -> str:
    """Stable-across-runs identifier for a function: module + qualname +
    bytecode digest (invalidates cached results when the code changes)."""
    module = getattr(fn, "__module__", "?")
    qualname = getattr(fn, "__qualname__", getattr(fn, "__name__", "udf"))
    code = getattr(fn, "__code__", None)
    code_hash = (
        hashlib.sha256(code.co_code).hexdigest()[:16] if code is not None else ""
    )
    return f"{module}.{qualname}#{code_hash}"


class CacheStrategy:
    def get(self, key: str) -> Any:
        return _SENTINEL

    def put(self, key: str, value: Any) -> None:
        pass

    @staticmethod
    def missing(value: Any) -> bool:
        return value is _SENTINEL


class InMemoryCache(CacheStrategy):
    def __init__(self, max_size: int | None = None) -> None:
        self._data: dict[str, Any] = {}
        self._max_size = max_size

    def get(self, key: str) -> Any:
        return self._data.get(key, _SENTINEL)

    def put(self, key: str, value: Any) -> None:
        if self._max_size is not None and len(self._data) >= self._max_size:
            self._data.pop(next(iter(self._data)))
        self._data[key] = value


_udf_cache_root: str | None = None


def set_udf_cache_root(path: str | None) -> None:
    """Wire persistence-config UDF caching (PersistenceMode.UDF_CACHING):
    DiskCaches constructed without an explicit directory resolve here."""
    global _udf_cache_root
    _udf_cache_root = path


class DiskCache(CacheStrategy):
    """Pickle-per-key directory cache. The directory resolves lazily at
    first use: explicit ``directory`` > persistence-config root
    (set_udf_cache_root) > PATHWAY_TPU_UDF_CACHE env > ./.pathway/udf-cache
    — so a cache declared at UDF-definition time honors a persistence
    config passed later to pw.run."""

    def __init__(self, directory: str | None = None) -> None:
        self._explicit = directory
        self._resolved: str | None = None

    def _base(self) -> str:
        resolved = (
            self._explicit
            or _udf_cache_root
            or os.environ.get("PATHWAY_TPU_UDF_CACHE")
            or os.path.join(".pathway", "udf-cache")
        )
        if resolved != self._resolved:
            os.makedirs(resolved, exist_ok=True)
            self._resolved = resolved
        return resolved

    def _path(self, key: str) -> str:
        return os.path.join(self._base(), key[:2], key)

    def get(self, key: str) -> Any:
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                return pickle.load(f)
        except (FileNotFoundError, EOFError, pickle.UnpicklingError):
            return _SENTINEL

    def put(self, key: str, value: Any) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(value, f, protocol=4)
            os.replace(tmp, path)
        except Exception:  # unpicklable result — skip caching
            try:
                os.unlink(tmp)
            except OSError:
                pass


class DefaultCache(DiskCache):
    """Reference-compatible alias (udfs.DefaultCache == disk-backed)."""
