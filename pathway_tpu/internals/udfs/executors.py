"""UDF executors: how a batch of pending rows is driven through user code.

Reference: python/pathway/internals/udfs/executors.py:92,132 (SyncExecutor /
AsyncExecutor with capacity+timeout). The engine hands executors whole
commit-batches of rows (engine/graph.py BatchApplyNode), which is also the
microbatching seam for TPU UDFs: a BatchExecutor receives all rows at once
and can pad them into one jit call instead of row-at-a-time dispatch — the
TPU-native replacement for the reference's tokio `map_named_async`
(src/engine/dataflow/operators.rs:182).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any, Callable, Sequence

from typing import Awaitable

from pathway_tpu.internals.udfs.retries import AsyncRetryStrategy

RowResult = tuple[bool, Any]  # (ok, value-or-exception)


def make_kw_fn(fn: Callable, n_pos: int, kw_names: list[str]) -> Callable:
    """Rebind a flat positional arg tuple to ``fn(*pos, **kw)``."""
    if not kw_names:
        return fn

    def wrapped(*vals: Any) -> Any:
        pos = vals[:n_pos]
        kws = dict(zip(kw_names, vals[n_pos:]))
        return fn(*pos, **kws)

    return wrapped


class Executor:
    kind = "sync"

    def run(
        self,
        fn: Callable[..., Any],
        rows: Sequence[tuple],
        retry: AsyncRetryStrategy | None = None,
    ) -> list[RowResult]:
        raise NotImplementedError


class SyncExecutor(Executor):
    def run(self, fn, rows, retry=None):
        out: list[RowResult] = []
        for args in rows:
            try:
                if retry is not None:
                    out.append((True, retry.invoke_sync(lambda: fn(*args))))
                else:
                    out.append((True, fn(*args)))
            except Exception as e:  # noqa: BLE001
                out.append((False, e))
        return out


class _EventLoopThread:
    """A process-wide background event loop for async UDFs.

    The reference runs async UDFs on a shared tokio runtime
    (src/async_runtime.rs); the analog here is one persistent loop thread —
    it survives across commits (async clients keep their loop) and works
    whether or not the caller itself runs inside an event loop (notebooks).
    """

    _lock = threading.Lock()
    _instance: "_EventLoopThread | None" = None

    def __init__(self) -> None:
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, name="pw-udf-loop", daemon=True
        )
        self.thread.start()

    @classmethod
    def get(cls) -> "_EventLoopThread":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def run(self, coro: Awaitable[Any]) -> Any:
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result()


class AsyncExecutor(Executor):
    """Concurrent execution on the shared loop thread, bounded by
    ``capacity``.

    ``timeout`` (seconds) applies per call, inside the retry loop like the
    reference (executors.py:286 async_options).
    """

    kind = "async"

    def __init__(
        self, capacity: int | None = None, timeout: float | None = None
    ) -> None:
        self.capacity = capacity
        self.timeout = timeout

    def run(self, fn, rows, retry=None):
        async def one(args: tuple, sem: asyncio.Semaphore | None):
            async def call():
                coro = fn(*args)
                if self.timeout is not None:
                    return await asyncio.wait_for(coro, self.timeout)
                return await coro

            try:
                if sem is not None:
                    async with sem:
                        if retry is not None:
                            return (True, await retry.invoke(call))
                        return (True, await call())
                if retry is not None:
                    return (True, await retry.invoke(call))
                return (True, await call())
            except Exception as e:  # noqa: BLE001
                return (False, e)

        async def gather():
            sem = (
                asyncio.Semaphore(self.capacity)
                if self.capacity is not None
                else None
            )
            return await asyncio.gather(*(one(args, sem) for args in rows))

        return _EventLoopThread.get().run(gather())


class BatchExecutor(Executor):
    """Whole-batch execution: ``fn`` receives parallel lists (one per arg)
    and returns a list of results — the jit-microbatch entry point.

    ``max_batch_size`` splits oversized commits so padded device buffers
    stay bounded.  ``sizer`` (optional callable -> int | None) lets the
    device pipeline's adaptive controller narrow the chunk size at run
    time; it can only shrink below the configured cap, never exceed it.
    """

    kind = "batch"

    def __init__(
        self,
        max_batch_size: int | None = None,
        sizer: Callable[[], int | None] | None = None,
    ) -> None:
        self.max_batch_size = max_batch_size
        self.sizer = sizer

    def run(self, fn, rows, retry=None):
        out: list[RowResult] = []
        step = self.max_batch_size or len(rows) or 1
        if self.sizer is not None:
            suggested = self.sizer()
            if suggested:
                step = max(1, min(step, int(suggested)))
        for start in range(0, len(rows), step):
            chunk = rows[start : start + step]
            cols = tuple(list(c) for c in zip(*chunk))
            try:
                if retry is not None:
                    results = retry.invoke_sync(lambda: fn(*cols))
                else:
                    results = fn(*cols)
                results = list(results)
                if len(results) != len(chunk):
                    raise ValueError(
                        f"batch UDF returned {len(results)} results "
                        f"for {len(chunk)} rows"
                    )
                out.extend((True, r) for r in results)
            except Exception as e:  # noqa: BLE001
                out.extend((False, e) for _ in chunk)
        return out


def sync_executor() -> SyncExecutor:
    return SyncExecutor()


def auto_executor(fn: Callable[..., Any]) -> Executor:
    if inspect.iscoroutinefunction(fn):
        return AsyncExecutor()
    return SyncExecutor()


def async_executor(
    capacity: int | None = None, timeout: float | None = None
) -> AsyncExecutor:
    return AsyncExecutor(capacity=capacity, timeout=timeout)


def batch_executor(
    max_batch_size: int | None = None,
    sizer: Callable[[], int | None] | None = None,
) -> BatchExecutor:
    return BatchExecutor(max_batch_size=max_batch_size, sizer=sizer)
