"""Universe (key-set) tracking.

Replaces the reference's SAT-based UniverseSolver
(reference: python/pathway/internals/universe_solver.py — pysat Glucose4)
with a union-find over equality promises plus a subset DAG; the engine's
zip/restrict operators are forgiving enough that full SAT reasoning is not
needed for correctness, only for early error messages.
"""

from __future__ import annotations

import itertools

_counter = itertools.count()


class Universe:
    __slots__ = ("id",)

    def __init__(self) -> None:
        self.id = next(_counter)

    def __repr__(self) -> str:
        return f"Universe({self.id})"

    def subset(self) -> "Universe":
        u = Universe()
        solver.register_subset(u, self)
        return u

    def superset(self) -> "Universe":
        u = Universe()
        solver.register_subset(self, u)
        return u


class UniverseSolver:
    def __init__(self) -> None:
        self._parent: dict[int, int] = {}
        self._subsets: set[tuple[int, int]] = set()  # (sub, sup) pairs on roots

    def _find(self, x: int) -> int:
        parent = self._parent.get(x, x)
        if parent == x:
            return x
        root = self._find(parent)
        self._parent[x] = root
        return root

    def register_equal(self, a: Universe, b: Universe) -> None:
        ra, rb = self._find(a.id), self._find(b.id)
        if ra != rb:
            self._parent[ra] = rb

    def register_subset(self, sub: Universe, sup: Universe) -> None:
        self._subsets.add((self._find(sub.id), self._find(sup.id)))

    def query_are_equal(self, a: Universe, b: Universe) -> bool:
        return self._find(a.id) == self._find(b.id)

    def query_is_subset(self, sub: Universe, sup: Universe) -> bool:
        rs, rp = self._find(sub.id), self._find(sup.id)
        if rs == rp:
            return True
        # BFS over subset edges (roots may drift after unions; normalize)
        edges: dict[int, set[int]] = {}
        for s, p in self._subsets:
            edges.setdefault(self._find(s), set()).add(self._find(p))
        seen = {rs}
        frontier = [rs]
        while frontier:
            cur = frontier.pop()
            if cur == rp:
                return True
            for nxt in edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return rp in seen

    def query_related(self, a: Universe, b: Universe) -> bool:
        return (
            self.query_are_equal(a, b)
            or self.query_is_subset(a, b)
            or self.query_is_subset(b, a)
        )


solver = UniverseSolver()
