"""Universe (key-set) tracking — SAT-based solver.

Matches the reference's UniverseSolver design (reference:
python/pathway/internals/universe_solver.py — encodes universe relations
as propositional clauses over "a generic element is in universe U"
variables and asks pysat's Glucose4). No SAT library ships in this image,
so the solver here is a compact DPLL with unit propagation — graph-sized
clause sets make that ample.

Encoding (one boolean variable per universe; clauses hold for an
arbitrary fixed element):
- ``A ⊆ B``       →  (¬A ∨ B)
- ``A == B``      →  (¬A ∨ B), (¬B ∨ A)
- ``U = A ∪ B``   →  (¬A ∨ U), (¬B ∨ U), (¬U ∨ A ∨ B)
- ``I = A ∩ B``   →  (¬I ∨ A), (¬I ∨ B), (¬A ∨ ¬B ∨ I)
- ``D = A ∖ B``   →  (¬D ∨ A), (¬D ∨ ¬B), (¬A ∨ B ∨ D)

``A ⊆ B`` holds iff clauses ∧ A ∧ ¬B is UNSAT; equality is subset both
ways. This makes derived facts (e.g. ``A∖B ⊆ A∪C``) provable, where the
previous union-find + subset DAG only followed registered edges.
"""

from __future__ import annotations

import itertools

_counter = itertools.count(1)  # DPLL literals are ±id; 0 is reserved


class Universe:
    __slots__ = ("id",)

    def __init__(self) -> None:
        self.id = next(_counter)

    def __repr__(self) -> str:
        return f"Universe({self.id})"

    def subset(self) -> "Universe":
        u = Universe()
        solver.register_subset(u, self)
        return u

    def superset(self) -> "Universe":
        u = Universe()
        solver.register_subset(self, u)
        return u


def _dpll(clauses: list[tuple[int, ...]], init: dict[int, bool]) -> bool:
    """Satisfiability of CNF ``clauses`` (literals ±var) given the ``init``
    assumptions. Iterative DPLL: a trail with assign/undo backtracking (no
    recursion, no dict copies) and per-variable occurrence lists so unit
    propagation only visits clauses touched by new assignments — a
    negative subset query on a graph-sized clause set costs one
    propagation sweep, not O(clauses^2)."""
    occurs: dict[int, list[int]] = {}
    for ci, clause in enumerate(clauses):
        for lit in clause:
            occurs.setdefault(abs(lit), []).append(ci)

    assignment: dict[int, bool] = {}
    trail: list[int] = []  # assignment order, for undo
    #: open decisions: (trail length at decision, decided var)
    decisions: list[tuple[int, int]] = []

    def assign(var: int, value: bool) -> bool:
        """Assign + propagate; False on conflict (trail keeps additions
        for the caller to undo via backtrack)."""
        queue = [(var, value)]
        while queue:
            v, val = queue.pop()
            seen = assignment.get(v)
            if seen is not None:
                if seen != val:
                    return False
                continue
            assignment[v] = val
            trail.append(v)
            for ci in occurs.get(v, ()):
                clause = clauses[ci]
                free = None
                n_free = 0
                satisfied = False
                for lit in clause:
                    lv, want = abs(lit), lit > 0
                    cur = assignment.get(lv)
                    if cur is None:
                        n_free += 1
                        free = lit
                    elif cur == want:
                        satisfied = True
                        break
                if satisfied:
                    continue
                if n_free == 0:
                    return False
                if n_free == 1:
                    queue.append((abs(free), free > 0))
        return True

    def backtrack() -> bool:
        """Flip the most recent decision still holding its first phase;
        False when no decision remains (exhausted -> UNSAT)."""
        while decisions:
            mark, var = decisions.pop()
            first = assignment[var]
            while len(trail) > mark:
                del assignment[trail.pop()]
            # second phase is not a decision: it is forced
            if assign(var, not first):
                return True
            # conflict again: keep unwinding
            while len(trail) > mark:
                del assignment[trail.pop()]
        return False

    for var, value in init.items():
        if not assign(var, value):
            return False

    scan = 0  # moving pointer over clauses; satisfied ones are skipped
    while scan < len(clauses):
        clause = clauses[scan]
        satisfied = False
        free = None
        for lit in clause:
            lv, want = abs(lit), lit > 0
            cur = assignment.get(lv)
            if cur is None:
                free = lit
            elif cur == want:
                satisfied = True
                break
        if satisfied:
            scan += 1
            continue
        if free is None:  # falsified without any open decision left
            if not backtrack():
                return False
            scan = 0
            continue
        # decide: try the phase that satisfies this clause first
        decisions.append((len(trail), abs(free)))
        if assign(abs(free), free > 0):
            # propagation caught every falsified/unit consequence, so
            # clauses behind the pointer stay satisfied: keep moving
            # (rescanning from 0 here made scans O(clauses^2))
            scan += 1
        else:
            if not backtrack():
                return False
            scan = 0  # assignments were removed: earlier clauses may reopen
    return True


class UniverseSolver:
    """SAT-backed subset/equality reasoning with memoized queries."""

    def __init__(self) -> None:
        self._clauses: list[tuple[int, ...]] = []
        self._unions: dict[tuple[int, ...], Universe] = {}
        self._intersections: dict[tuple[int, ...], Universe] = {}
        self._differences: dict[tuple[int, int], Universe] = {}
        # clause sets only grow, and subset=True means UNSAT — which more
        # clauses can never undo: positive answers cache forever, negative
        # answers are dropped (O(1)) whenever clauses are added
        self._cache_true: set[tuple[int, int]] = set()
        self._cache_false: set[tuple[int, int]] = set()

    def _add(self, *clauses: tuple[int, ...]) -> None:
        self._clauses.extend(clauses)
        self._cache_false.clear()

    # -- axioms ------------------------------------------------------------

    def register_equal(self, a: Universe, b: Universe) -> None:
        self._add((-a.id, b.id), (-b.id, a.id))

    def register_subset(self, sub: Universe, sup: Universe) -> None:
        self._add((-sub.id, sup.id))

    def register_union(self, result: Universe, *parts: Universe) -> None:
        self._add(
            *((-p.id, result.id) for p in parts),
            (-result.id, *(p.id for p in parts)),
        )

    def register_intersection(self, result: Universe, *parts: Universe) -> None:
        self._add(
            *((-result.id, p.id) for p in parts),
            (*(-p.id for p in parts), result.id),
        )

    def register_difference(
        self, result: Universe, a: Universe, b: Universe
    ) -> None:
        self._add(
            (-result.id, a.id),
            (-result.id, -b.id),
            (-a.id, b.id, result.id),
        )

    # -- derived universes (memoized, reference get_union etc.) ------------

    def get_union(self, *parts: Universe) -> Universe:
        key = tuple(sorted(p.id for p in parts))
        got = self._unions.get(key)
        if got is None:
            got = self._unions[key] = Universe()
            self.register_union(got, *parts)
        return got

    def get_intersection(self, *parts: Universe) -> Universe:
        key = tuple(sorted(p.id for p in parts))
        got = self._intersections.get(key)
        if got is None:
            got = self._intersections[key] = Universe()
            self.register_intersection(got, *parts)
        return got

    def get_difference(self, a: Universe, b: Universe) -> Universe:
        key = (a.id, b.id)
        got = self._differences.get(key)
        if got is None:
            got = self._differences[key] = Universe()
            self.register_difference(got, a, b)
        return got

    # -- queries -----------------------------------------------------------

    def query_is_subset(self, sub: Universe, sup: Universe) -> bool:
        """True iff the axioms force every element of ``sub`` into
        ``sup``: clauses ∧ sub ∧ ¬sup must be unsatisfiable."""
        if sub.id == sup.id:
            return True
        key = (sub.id, sup.id)
        if key in self._cache_true:
            return True
        if key in self._cache_false:
            return False
        got = not _dpll(self._clauses, {sub.id: True, sup.id: False})
        (self._cache_true if got else self._cache_false).add(key)
        return got

    def query_are_equal(self, a: Universe, b: Universe) -> bool:
        return self.query_is_subset(a, b) and self.query_is_subset(b, a)

    def query_related(self, a: Universe, b: Universe) -> bool:
        return self.query_is_subset(a, b) or self.query_is_subset(b, a)


solver = UniverseSolver()
