"""pw.sql — SQL over Tables.

Reference: python/pathway/internals/sql.py (726 LoC) parses with sqlglot and
lowers onto Table ops. sqlglot is not in this image, so the dialect is
parsed by a tokenizer + recursive-descent grammar producing a proper AST
with standard precedence (OR < AND < NOT < comparisons/IS/IN < +- < */%),
then lowered onto Table ops: SELECT expressions (+aliases, arithmetic,
parenthesized nesting, literals, quoted identifiers), FROM with table
aliases and derived tables (nested subqueries, arbitrarily deep),
INNER/LEFT JOIN ... ON equalities (subqueries join too), WHERE,
IN/NOT IN value lists, GROUP BY with aggregates (count/sum/min/max/avg),
global aggregates without GROUP BY, HAVING, UNION ALL, INTERSECT,
WITH/CTE blocks (chained, reusable, valid in any subquery position), and
non-correlated scalar subqueries (lifted to live left-cross-join inputs,
so the scalar updates incrementally; reference threads its WITH blocks
through every SELECT at internals/sql.py:175-176,525).
"""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import (
    ColumnExpression,
    apply as pw_apply,
    wrap_expression,
)
from pathway_tpu.internals.table import Table

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>\d+\.\d+|\d+)|(?P<str>'(?:[^']|'')*')"
    r'|(?P<qname>"(?:[^"]|"")*"|`[^`]*`)'
    r"|(?P<op><=|>=|<>|!=|==|[(),*+\-/<>=.%])"
    r"|(?P<name>[A-Za-z_][A-Za-z_0-9]*))"
)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "as", "and", "or",
    "not", "join", "inner", "left", "on", "union", "all", "intersect",
    "except", "in", "count", "sum", "min", "max", "avg", "null", "true",
    "false", "is", "case", "when", "then", "else", "end", "between",
    "like", "cast", "coalesce", "nullif", "distinct", "with",
}


def _tokenize(text: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise ValueError(f"pw.sql: cannot tokenize at {text[pos:pos+20]!r}")
        pos = m.end()
        if m.group("num") is not None:
            out.append(("num", m.group("num")))
        elif m.group("str") is not None:
            out.append(("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("qname") is not None:
            q = m.group("qname")
            # quoted identifier: case preserved, never a keyword
            if q.startswith('"'):
                out.append(("name", q[1:-1].replace('""', '"')))
            else:
                out.append(("name", q[1:-1]))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            name = m.group("name")
            kind = "kw" if name.lower() in _KEYWORDS else "name"
            out.append((kind, name.lower() if kind == "kw" else name))
    out.append(("end", ""))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]) -> None:
        self.tokens = tokens
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.tokens[self.i]

    def next(self) -> tuple[str, str]:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def accept(self, kind: str, value: str | None = None) -> bool:
        k, v = self.peek()
        if k == kind and (value is None or v == value):
            self.i += 1
            return True
        return False

    def expect(self, kind: str, value: str | None = None) -> str:
        k, v = self.next()
        if k != kind or (value is not None and v != value):
            raise ValueError(f"pw.sql: expected {value or kind}, got {v!r}")
        return v

    # -- grammar -------------------------------------------------------------

    def parse_query(self) -> dict:
        q = self.parse_query_expr()
        self.expect("end")
        return q

    def parse_query_expr(self) -> dict:
        """Optional WITH prologue over a set chain. CTEs see earlier CTEs
        (chained), and a WITH may open any subquery position (derived
        tables, IN (...), scalar subqueries), like standard SQL."""
        if self.accept("kw", "with"):
            ctes: list[tuple[str, dict]] = []
            while True:
                name = self.expect("name")
                self.expect("kw", "as")
                self.expect("op", "(")
                sub = self.parse_query_expr()
                self.expect("op", ")")
                ctes.append((name, sub))
                if not self.accept("op", ","):
                    break
            return {
                "kind": "with",
                "ctes": ctes,
                "query": self.parse_query_expr(),
            }
        return self.parse_set_chain()

    def parse_set_chain(self) -> dict:
        """UNION ALL chain over INTERSECT chains (INTERSECT binds tighter,
        standard SQL precedence) — shared by top-level queries and derived
        tables."""
        q = self.parse_intersect_chain()
        while True:
            if self.accept("kw", "union"):
                distinct = not self.accept("kw", "all")
                q = {
                    "kind": "union",
                    "distinct": distinct,
                    "left": q,
                    "right": self.parse_intersect_chain(),
                }
            elif self.accept("kw", "except"):
                q = {
                    "kind": "except",
                    "left": q,
                    "right": self.parse_intersect_chain(),
                }
            else:
                return q

    def parse_intersect_chain(self) -> dict:
        q = self.parse_select()
        while self.accept("kw", "intersect"):
            q = {"kind": "intersect", "left": q, "right": self.parse_select()}
        return q

    def parse_select(self) -> dict:
        self.expect("kw", "select")
        items: list[tuple[Any, str | None]] = []
        if self.accept("op", "*"):
            items.append(("*", None))
        else:
            while True:
                e = self.parse_expr()
                alias = None
                if self.accept("kw", "as"):
                    alias = self.expect("name")
                elif self.peek()[0] == "name":
                    alias = self.next()[1]
                items.append((e, alias))
                if not self.accept("op", ","):
                    break
        self.expect("kw", "from")
        base = self.parse_table_ref()
        joins = []
        while self.peek() == ("kw", "join") or self.peek() == ("kw", "inner") or self.peek() == ("kw", "left"):
            how = "inner"
            if self.accept("kw", "left"):
                how = "left"
            self.accept("kw", "inner")
            self.expect("kw", "join")
            other = self.parse_table_ref()
            self.expect("kw", "on")
            cond = self.parse_expr()
            joins.append({"table": other, "on": cond, "how": how})
        where = None
        if self.accept("kw", "where"):
            where = self.parse_expr()
        group_by = None
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by = [self.parse_expr()]
            while self.accept("op", ","):
                group_by.append(self.parse_expr())
        having = None
        if self.accept("kw", "having"):
            having = self.parse_expr()
        return {
            "kind": "select",
            "items": items,
            "from": base,
            "joins": joins,
            "where": where,
            "group_by": group_by,
            "having": having,
        }

    def parse_table_ref(self) -> dict:
        """A FROM/JOIN operand: plain table name, or a parenthesized
        subquery with a mandatory alias (standard derived-table form)."""
        if self.accept("op", "("):
            sub = self.parse_query_expr()
            self.expect("op", ")")
            self.accept("kw", "as")
            alias = self.expect("name")
            return {"subquery": sub, "alias": alias}
        name = self.expect("name")
        alias = name
        if self.accept("kw", "as"):
            alias = self.expect("name")
        elif self.peek()[0] == "name":
            alias = self.next()[1]
        return {"table": name, "alias": alias}

    def parse_expr(self) -> Any:
        return self.parse_or()

    def parse_or(self) -> Any:
        e = self.parse_and()
        while self.accept("kw", "or"):
            e = ("or", e, self.parse_and())
        return e

    def parse_and(self) -> Any:
        e = self.parse_not()
        while self.accept("kw", "and"):
            e = ("and", e, self.parse_not())
        return e

    def parse_not(self) -> Any:
        if self.accept("kw", "not"):
            return ("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self) -> Any:
        e = self.parse_add()
        k, v = self.peek()
        if k == "op" and v in ("=", "==", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            op = {"=": "==", "<>": "!="}.get(v, v)
            return (op, e, self.parse_add())
        if self.accept("kw", "is"):
            negated = self.accept("kw", "not")
            self.expect("kw", "null")
            return ("is_not_null" if negated else "is_null", e)
        negated_in = False
        if self.peek() == ("kw", "not") and self.tokens[self.i + 1][0] == "kw" and self.tokens[
            self.i + 1
        ][1] in ("in", "between", "like"):
            self.next()
            negated_in = True
        if self.accept("kw", "between"):
            lo = self.parse_add()
            self.expect("kw", "and")
            hi = self.parse_add()
            node = ("and", (">=", e, lo), ("<=", e, hi))
            return ("not", node) if negated_in else node
        if self.accept("kw", "like"):
            k2, pattern = self.next()
            if k2 != "str":
                raise ValueError("pw.sql: LIKE needs a string literal pattern")
            # negation folds into the node so NULL propagates through
            # NOT LIKE too (SQL three-valued logic: NULL LIKE x is NULL)
            return ("like", e, pattern, negated_in)
        if self.accept("kw", "in"):
            self.expect("op", "(")
            if self.peek() in (("kw", "select"), ("kw", "with")):
                sub = self.parse_query_expr()
                self.expect("op", ")")
                # semi-join form; negation stays in the node (the WHERE
                # lowering turns it into intersect/difference, which a
                # generic NOT wrapper could not express)
                return ("in_subquery", e, sub, negated_in)
            values = [self.parse_expr()]
            while self.accept("op", ","):
                values.append(self.parse_expr())
            self.expect("op", ")")
            node = ("in", e, values)
            return ("not", node) if negated_in else node
        return e

    def parse_add(self) -> Any:
        e = self.parse_mul()
        while True:
            if self.accept("op", "+"):
                e = ("+", e, self.parse_mul())
            elif self.accept("op", "-"):
                e = ("-", e, self.parse_mul())
            else:
                return e

    def parse_mul(self) -> Any:
        e = self.parse_atom()
        while True:
            if self.accept("op", "*"):
                e = ("*", e, self.parse_atom())
            elif self.accept("op", "/"):
                e = ("/", e, self.parse_atom())
            elif self.accept("op", "%"):
                e = ("%", e, self.parse_atom())
            else:
                return e

    def parse_atom(self) -> Any:
        k, v = self.next()
        if k == "num":
            return ("lit", float(v) if "." in v else int(v))
        if k == "str":
            return ("lit", v)
        if k == "kw" and v in ("count", "sum", "min", "max", "avg"):
            self.expect("op", "(")
            if v == "count" and self.accept("op", "*"):
                self.expect("op", ")")
                return ("agg", "count", None)
            if v == "count" and self.accept("kw", "distinct"):
                arg = self.parse_expr()
                self.expect("op", ")")
                return ("agg", "count_distinct", arg)
            arg = self.parse_expr()
            self.expect("op", ")")
            return ("agg", v, arg)
        if k == "kw" and v == "case":
            arms = []
            while self.accept("kw", "when"):
                cond = self.parse_expr()
                self.expect("kw", "then")
                arms.append((cond, self.parse_expr()))
            default = ("lit", None)
            if self.accept("kw", "else"):
                default = self.parse_expr()
            self.expect("kw", "end")
            if not arms:
                raise ValueError("pw.sql: CASE needs at least one WHEN arm")
            return ("case", arms, default)
        if k == "kw" and v == "cast":
            self.expect("op", "(")
            e = self.parse_expr()
            self.expect("kw", "as")
            tname = self.expect("name").lower()
            self.expect("op", ")")
            return ("cast", e, tname)
        if k == "kw" and v == "coalesce":
            self.expect("op", "(")
            args = [self.parse_expr()]
            while self.accept("op", ","):
                args.append(self.parse_expr())
            self.expect("op", ")")
            return ("coalesce", args)
        if k == "kw" and v == "nullif":
            self.expect("op", "(")
            a = self.parse_expr()
            self.expect("op", ",")
            b = self.parse_expr()
            self.expect("op", ")")
            return ("nullif", a, b)
        if k == "kw" and v == "null":
            return ("lit", None)
        if k == "kw" and v == "true":
            return ("lit", True)
        if k == "kw" and v == "false":
            return ("lit", False)
        if k == "op" and v == "(":
            if self.peek() in (("kw", "select"), ("kw", "with")):
                sub = self.parse_query_expr()
                self.expect("op", ")")
                return ("scalar_subquery", sub)
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if k == "op" and v == "-":
            return ("neg", self.parse_atom())
        if k == "name":
            if self.accept("op", "."):
                col = self.expect("name")
                return ("col", v, col)
            return ("col", None, v)
        raise ValueError(f"pw.sql: unexpected token {v!r}")


class _Lowerer:
    def __init__(self, tables: dict[str, Table]) -> None:
        self.tables = tables
        # after a JOIN, alias -> {original column name -> materialized name};
        # duplicate names across join sides are qualified as f"{alias}_{name}"
        self.colmap: dict[str, dict[str, str]] = {}
        # scalar subquery AST node (by identity) -> grafted aux column name
        self._scalar_cols: dict[int, str] = {}

    @staticmethod
    def _distinct(t: Table) -> Table:
        cols = t.column_names()
        return t.groupby(*[t[c] for c in cols]).reduce(
            **{c: t[c] for c in cols}
        )

    def lower(self, q: dict) -> Table:
        if q["kind"] == "with":
            # each CTE lowers ONCE into a Table the later CTEs and the
            # main query see by name (reference threads the WITH block
            # through every SELECT, internals/sql.py:175-176,525); a CTE
            # referenced twice reuses the same dataflow subgraph
            env = dict(self.tables)
            for name, sub in q["ctes"]:
                env[name] = _Lowerer(env).lower(sub)
            return _Lowerer(env).lower(q["query"])
        if q["kind"] == "union":
            left = self.lower(q["left"])
            right = self.lower(q["right"])
            merged = left.concat_reindex(right)
            if q.get("distinct"):
                return self._distinct(merged)
            return merged
        if q["kind"] == "except":
            # set difference: distinct left rows with no equal right row
            left = self._distinct(self.lower(q["left"]))
            right = self._distinct(self.lower(q["right"]))
            lcols = left.column_names()
            rcols = right.column_names()
            if len(lcols) != len(rcols):
                raise ValueError("EXCEPT sides must have equal arity")
            conds = [left[lc] == right[rc] for lc, rc in zip(lcols, rcols)]
            # the arity-0 select materialises the JoinResult into a Table
            # (difference needs a universe); no column payload is carried
            matched = left.join(right, *conds, id=left.id).select()
            kept = left.difference(matched)
            return kept.select(**{lc: kept[lc] for lc in lcols})
        if q["kind"] == "intersect":
            # set semantics: distinct rows present on both sides. Each side
            # deduplicates FIRST so duplicate-heavy inputs can't blow up
            # the join (k*m rows per repeated value otherwise)
            distinct = self._distinct
            left = distinct(self.lower(q["left"]))
            right = distinct(self.lower(q["right"]))
            lcols = left.column_names()
            rcols = right.column_names()
            if len(lcols) != len(rcols):
                raise ValueError("INTERSECT sides must have equal arity")
            conds = [left[lc] == right[rc] for lc, rc in zip(lcols, rcols)]
            return left.join(right, *conds).select(
                **{lc: left[lc] for lc in lcols}
            )
        return self.lower_select(q)

    def _resolve_col(self, tname: str | None, col: str, scope: dict[str, Table]):
        if tname is not None:
            if tname not in scope:
                raise ValueError(f"pw.sql: unknown table {tname!r}")
            actual = self.colmap.get(tname, {}).get(col, col)
            t = scope[tname]
            if actual not in t.column_names():
                raise ValueError(f"pw.sql: unknown column {tname}.{col}")
            return t[actual]
        if self.colmap:
            # post-join: resolve against per-alias original names so
            # same-named columns from both sides stay distinguishable;
            # tables in scope but not yet joined (no colmap entry) also
            # count as candidate owners
            owners = [a for a, m in self.colmap.items() if col in m]
            others = [
                a
                for a, t in scope.items()
                if a != "__joined__"
                and a not in self.colmap
                and col in t.column_names()
            ]
            if len(owners) + len(others) > 1:
                raise ValueError(
                    f"pw.sql: ambiguous column {col!r} "
                    f"(qualify as one of: "
                    f"{', '.join(f'{a}.{col}' for a in owners + others)})"
                )
            if owners:
                return scope[owners[0]][self.colmap[owners[0]][col]]
            if others:
                return scope[others[0]][col]
            # fall through: columns introduced after the join (e.g. aux)
        unique = {id(t): t for t in scope.values()}
        matches = [t for t in unique.values() if col in t.column_names()]
        if not matches:
            raise ValueError(f"pw.sql: unknown column {col!r}")
        if len(matches) > 1:
            raise ValueError(f"pw.sql: ambiguous column {col!r}")
        return matches[0][col]

    def expr(self, node: Any, scope: dict[str, Table]) -> Any:
        op = node[0]
        if op == "lit":
            return wrap_expression(node[1])
        if op == "col":
            return self._resolve_col(node[1], node[2], scope)
        if op == "neg":
            return -self.expr(node[1], scope)
        if op == "not":
            return ~self.expr(node[1], scope)
        if op in ("and", "or"):
            left = self.expr(node[1], scope)
            right = self.expr(node[2], scope)
            return (left & right) if op == "and" else (left | right)
        if op == "is_null":
            e = self.expr(node[1], scope)
            return e.is_none()
        if op == "is_not_null":
            e = self.expr(node[1], scope)
            return e.is_not_none()
        if op == "agg":
            raise ValueError("pw.sql: aggregate used outside GROUP BY select")
        if op == "in":
            e = self.expr(node[1], scope)
            parts = [e == self.expr(v, scope) for v in node[2]]
            out = parts[0]
            for part in parts[1:]:
                out = out | part
            return out
        if op in ("case", "like", "cast", "coalesce", "nullif"):
            return self._special(node, lambda n: self.expr(n, scope))
        if op == "scalar_subquery":
            aux = self._scalar_cols.get(id(node))
            if aux is None:
                raise ValueError(
                    "pw.sql: scalar subquery in an unsupported position "
                    "(supported: SELECT items, WHERE, GROUP BY, HAVING)"
                )
            return next(iter(scope.values()))[aux]
        if op == "in_subquery":
            raise ValueError(
                "pw.sql: IN (SELECT ...) is only supported as a top-level "
                "AND conjunct of WHERE"
            )
        left = self.expr(node[1], scope)
        right = self.expr(node[2], scope)
        return {
            "+": lambda: left + right,
            "-": lambda: left - right,
            "*": lambda: left * right,
            "/": lambda: left / right,
            "%": lambda: left % right,
            "==": lambda: left == right,
            "!=": lambda: left != right,
            "<": lambda: left < right,
            "<=": lambda: left <= right,
            ">": lambda: left > right,
            ">=": lambda: left >= right,
        }[op]()

    def _special(self, node: Any, rec: Any) -> Any:
        """CASE / LIKE / CAST / COALESCE / NULLIF lowering, shared by the
        plain and aggregate expression walkers (``rec`` recurses with the
        right walker)."""
        from pathway_tpu.internals.expression import if_else

        op = node[0]
        if op == "case":
            arms, default = node[1], node[2]
            out = rec(default)
            for cond, val in reversed(arms):
                out = if_else(rec(cond), rec(val), out)
            return out
        if op == "like":
            pattern, negated = node[2], node[3]
            regex = re.compile(
                "^"
                + re.escape(pattern).replace("%", ".*").replace("_", ".")
                + "$",
                re.DOTALL,
            )

            def like(s, _rx=regex, _neg=negated):
                if s is None:
                    return None  # NULL [NOT] LIKE x is NULL: WHERE drops it
                return bool(_rx.match(str(s))) != _neg

            return pw_apply(like, rec(node[1]))
        if op == "cast":
            def to_bool(v):
                if isinstance(v, str):
                    s = v.strip().lower()
                    if s in ("true", "t", "1", "yes", "on"):
                        return True
                    if s in ("false", "f", "0", "no", "off"):
                        return False
                    raise ValueError(f"invalid boolean literal {v!r}")
                return bool(v)

            target = {
                "int": int,
                "integer": int,
                "bigint": int,
                "float": float,
                "double": float,
                "real": float,
                "text": str,
                "varchar": str,
                "string": str,
                "bool": to_bool,
                "boolean": to_bool,
            }.get(node[2])
            if target is None:
                raise ValueError(f"pw.sql: unsupported CAST type {node[2]!r}")
            return pw_apply(
                lambda v, _t=target: None if v is None else _t(v),
                rec(node[1]),
            )
        if op == "coalesce":
            args = [rec(a) for a in node[1]]
            out = args[-1]
            for a in reversed(args[:-1]):
                out = if_else(a.is_not_none(), a, out)
            return out
        if op == "nullif":
            a, b = rec(node[1]), rec(node[2])
            return if_else(a == b, wrap_expression(None), a)
        raise AssertionError(op)

    def _agg_expr(
        self, node: Any, scope: dict[str, Table], gb: tuple = ()
    ) -> Any:
        """Expression where ('agg', fn, arg) becomes a reducer expression.
        ``gb`` maps GROUP BY key ASTs to their materialized key columns:
        any subtree structurally equal to a group key lowers to that key
        (required for computed keys, which are invalid inside reduce)."""
        for g_ast, g_expr in gb:
            if node == g_ast:
                return g_expr
        if isinstance(node, tuple) and node[0] == "agg":
            fn, arg = node[1], node[2]
            if fn == "count":
                return reducers.count()
            if fn == "count_distinct":
                return reducers.count_distinct(self.expr(arg, scope))
            inner = self.expr(arg, scope)
            return {
                "sum": reducers.sum,
                "min": reducers.min,
                "max": reducers.max,
                "avg": reducers.avg,
            }[fn](inner)
        if isinstance(node, tuple) and node[0] not in ("lit", "col"):
            if node[0] == "in":
                # ('in', expr, [values]): OR chain of equalities; the
                # values list is NOT an expression child
                e = self._agg_expr(node[1], scope, gb)
                out = None
                for v in node[2]:
                    part = e == self._agg_expr(v, scope, gb)
                    out = part if out is None else (out | part)
                return out
            if node[0] in ("is_null", "is_not_null"):
                e = self._agg_expr(node[1], scope, gb)
                return e.is_none() if node[0] == "is_null" else e.is_not_none()
            if node[0] in ("case", "like", "cast", "coalesce", "nullif"):
                return self._special(
                    node, lambda n: self._agg_expr(n, scope, gb)
                )
            if node[0] == "scalar_subquery":
                # inside a reduce the grafted aux column is not a group
                # key; it is constant across all rows, so min() recovers
                # the scalar without changing semantics
                return reducers.min(self.expr(node, scope))
            parts = [self._agg_expr(c, scope, gb) for c in node[1:]]
            return self._combine(node[0], parts)
        return self.expr(node, scope)

    def _combine(self, op: str, parts: list) -> Any:
        if op == "neg":
            return -parts[0]
        if op == "not":
            return ~parts[0]
        if op == "and":
            return parts[0] & parts[1]
        if op == "or":
            return parts[0] | parts[1]
        left, right = parts
        return {
            "+": lambda: left + right,
            "-": lambda: left - right,
            "*": lambda: left * right,
            "/": lambda: left / right,
            "%": lambda: left % right,
            "==": lambda: left == right,
            "!=": lambda: left != right,
            "<": lambda: left < right,
            "<=": lambda: left <= right,
            ">": lambda: left > right,
            ">=": lambda: left >= right,
        }[op]()

    def _item_name(self, node: Any, alias: str | None, idx: int) -> str:
        if alias:
            return alias
        if isinstance(node, tuple) and node[0] == "col":
            tname, name = node[1], node[2]
            if tname is not None and name in self.colmap.get(tname, {}):
                # qualified ref to a join-duplicated column: keep the
                # qualified output name (e.g. b_val) to avoid collisions
                return self.colmap[tname][name]
            return name
        if isinstance(node, tuple) and node[0] == "agg":
            return node[1]
        return f"col_{idx}"

    def _resolve_table(self, ref: dict) -> tuple[Table, str]:
        """FROM/JOIN operand -> (Table, alias). Derived tables (nested
        subqueries) lower through a FRESH lowerer so their join colmaps
        can't leak into this SELECT's."""
        if "subquery" in ref:
            return _Lowerer(self.tables).lower(ref["subquery"]), ref["alias"]
        base = self.tables.get(ref["table"])
        if base is None:
            raise ValueError(f"pw.sql: unknown table {ref['table']!r}")
        return base, ref["alias"]

    @staticmethod
    def _fresh_copy(table: Table) -> Table:
        """Independent view of a table (self-joins: both aliases must
        resolve to DISTINCT Table objects or every qualified reference
        collapses onto one side)."""
        return table.select(**{n: table[n] for n in table.column_names()})

    def _graft_scalar_subqueries(
        self, q: dict, current: Table, scope: dict[str, Table]
    ) -> tuple[Table, dict[str, Table]]:
        """Lift each non-correlated scalar subquery to a computed join
        input: lower it to its (single-row, single-column) table and
        LEFT-cross-join it onto ``current`` as an aux column, so the
        value streams incrementally like any other input (an empty
        subquery result reads as NULL, matching SQL). Correlated
        subqueries fail the inner lowering's name resolution."""

        def collect(node: Any, acc: list) -> None:
            if isinstance(node, tuple):
                if node and node[0] == "scalar_subquery":
                    acc.append(node)
                    return
                for child in node[1:]:
                    collect(child, acc)
            elif isinstance(node, list):
                for child in node:
                    collect(child, acc)

        found: list = []
        for node, _alias in q["items"]:
            if node != "*":
                collect(node, found)
        collect(q["where"], found)
        for g in q["group_by"] or ():
            collect(g, found)
        collect(q["having"], found)
        by_shape: dict[str, str] = {}  # structural dedup of repeats
        for i, node in enumerate(found):
            if id(node) in self._scalar_cols:
                continue
            shape = repr(node)
            aux = by_shape.get(shape)
            if aux is not None:
                # textually identical subquery: reuse the grafted column
                self._scalar_cols[id(node)] = aux
                continue
            sub_t = _Lowerer(self.tables).lower(node[1])
            sub_cols = sub_t.column_names()
            if len(sub_cols) != 1:
                raise ValueError(
                    "pw.sql: scalar subquery needs exactly one output "
                    "column"
                )
            aux = f"_pw_sq_{i}"
            # collapse to ONE row: unique() poisons with ERROR when the
            # subquery yields several distinct values (SQL's more-than-
            # one-row runtime error, expressed through error poisoning);
            # an empty subquery leaves no row and left-join pads NULL
            sub_one = sub_t.reduce(
                **{aux: reducers.unique(sub_t[sub_cols[0]])}
            )
            keep = {n: current[n] for n in current.column_names()}
            current = current.join(sub_one, how="left").select(
                **keep, **{aux: sub_one[aux]}
            )
            self._scalar_cols[id(node)] = aux
            by_shape[shape] = aux
            scope = {name: current for name in scope}
        return current, scope

    def lower_select(self, q: dict) -> Table:
        self.colmap = {}  # per-SELECT: a UNION branch must not see the other's joins
        scope: dict[str, Table] = {}
        base, base_alias = self._resolve_table(q["from"])
        scope[base_alias] = base
        current = base
        for j in q["joins"]:
            other, other_alias = self._resolve_table(j["table"])
            if any(existing is other for existing in scope.values()):
                other = self._fresh_copy(other)  # self-join
            scope[other_alias] = other
            cond_ast = j["on"]
            if not (isinstance(cond_ast, tuple) and cond_ast[0] == "=="):
                raise ValueError("pw.sql: JOIN ON must be an equality")
            lcond = self.expr(cond_ast[1], scope)
            rcond = self.expr(cond_ast[2], scope)
            joined = current.join(other, lcond == rcond, how=j["how"])
            # materialize all columns of both sides for further stages;
            # duplicate names across sides are qualified f"{alias}_{name}"
            # so `SELECT a.val, b.val` returns both (first alias keeps the
            # bare name; unqualified refs to a duplicate raise 'ambiguous')
            cols: dict[str, Any] = {}
            newmap: dict[str, dict[str, str]] = {}
            for alias, t in scope.items():
                if alias == "__joined__":
                    continue
                visible = self.colmap.get(
                    alias, {n: n for n in t.column_names()}
                )
                amap: dict[str, str] = {}
                for name, actual in visible.items():
                    target = name
                    if target in cols:
                        target = f"{alias}_{name}"
                        k = 2
                        while target in cols:
                            target = f"{alias}_{name}_{k}"
                            k += 1
                    cols[target] = t[actual]
                    amap[name] = target
                newmap[alias] = amap
            current = joined.select(**cols)
            self.colmap = newmap
            scope = {name: current for name in scope}
            scope["__joined__"] = current
        current, scope = self._graft_scalar_subqueries(q, current, scope)
        if q["where"] is not None:
            def conjuncts(node):
                if isinstance(node, tuple) and node[0] == "and":
                    return conjuncts(node[1]) + conjuncts(node[2])
                return [node]

            plain = []
            for part in conjuncts(q["where"]):
                if isinstance(part, tuple) and part[0] == "in_subquery":
                    _tag, e_ast, sub, negated = part
                    sub_table = _Lowerer(self.tables).lower(sub)
                    sub_cols = sub_table.column_names()
                    if len(sub_cols) != 1:
                        raise ValueError(
                            "pw.sql: IN (SELECT ...) needs exactly one "
                            "output column"
                        )
                    needle = self.expr(e_ast, scope)
                    sub_d = self._distinct(sub_table)
                    matched = current.join(
                        sub_d,
                        needle == sub_d[sub_cols[0]],
                        id=current.id,
                    ).select()
                    current = (
                        current.difference(matched)
                        if negated
                        else current.restrict(matched)
                    )
                    scope = {name: current for name in scope}
                else:
                    plain.append(part)
            def has_in_subquery(node):
                if isinstance(node, tuple):
                    if node and node[0] == "in_subquery":
                        return True
                    return any(has_in_subquery(c) for c in node)
                if isinstance(node, list):
                    return any(has_in_subquery(c) for c in node)
                return False

            for part in plain:
                if has_in_subquery(part):
                    raise ValueError(
                        "pw.sql: IN (SELECT ...) is only supported as a "
                        "top-level AND conjunct of WHERE"
                    )
                current = current.filter(self.expr(part, scope))
                scope = {name: current for name in scope}
        if q["group_by"] is not None:
            from pathway_tpu.internals.expression import ColumnReference

            by_exprs = [self.expr(g, scope) for g in q["group_by"]]
            if not all(isinstance(b, ColumnReference) for b in by_exprs):
                # group by computed expressions: materialize them first
                aux = {
                    f"_pw_gb_{i}": b
                    for i, b in enumerate(by_exprs)
                    if not isinstance(b, ColumnReference)
                }
                keep = {n: current[n] for n in current.column_names()}
                current = current.select(**keep, **aux)
                scope = {name: current for name in scope}
                by_exprs = [
                    b
                    if isinstance(b, ColumnReference)
                    else current[f"_pw_gb_{i}"]
                    for i, b in enumerate(by_exprs)
                ]
            grouped = current.groupby(*by_exprs)
            gb = tuple(zip(q["group_by"], by_exprs))
            out: dict[str, Any] = {}
            for idx, (node, alias) in enumerate(q["items"]):
                if node == "*":
                    raise ValueError("pw.sql: SELECT * with GROUP BY")
                name = self._item_name(node, alias, idx)
                out[name] = self._agg_expr(node, scope, gb)
            if q["having"] is not None:
                out["_pw_having"] = self._agg_expr(q["having"], scope, gb)
            result = grouped.reduce(**out)
            if q["having"] is not None:
                result = result.filter(result["_pw_having"])[
                    [n for n in out if n != "_pw_having"]
                ]
            return result
        def has_agg(node: Any) -> bool:
            if isinstance(node, tuple):
                if node and node[0] == "agg":
                    return True
                if node and node[0] == "scalar_subquery":
                    return False  # its aggregates belong to the subquery
                return any(has_agg(c) for c in node[1:])
            if isinstance(node, list):
                return any(has_agg(c) for c in node)
            return False

        if any(
            node != "*" and has_agg(node) for node, _a in q["items"]
        ) or (q["having"] is not None and has_agg(q["having"])):
            # global aggregate (no GROUP BY): ONE output row over the
            # whole table, e.g. SELECT count(*), max(v) FROM t — present
            # even when the input is empty (SQL: count(*)=0 row)
            out = {}
            count_rooted: list[str] = []
            for idx, (node, alias) in enumerate(q["items"]):
                if node == "*":
                    raise ValueError("pw.sql: SELECT * with aggregates")
                name = self._item_name(node, alias, idx)
                out[name] = self._agg_expr(node, scope)
                if (
                    isinstance(node, tuple)
                    and node[0] == "agg"
                    and node[1] in ("count", "count_distinct")
                ):
                    count_rooted.append(name)
            if q["having"] is not None:
                out["_pw_having"] = self._agg_expr(q["having"], scope)
            result = current.reduce(**out)
            # an empty input leaves reduce with NO row; a static one-row
            # marker left-cross-joined in restores SQL's single row:
            # count-rooted items read 0, everything else NULL (compound
            # expressions over aggregates read NULL when empty — a
            # documented approximation)
            import pathway_tpu.debug as _debug
            from pathway_tpu.internals.schema import schema_from_types

            marker = _debug.table_from_rows(
                schema_from_types(_pw_one=int), [(1,)]
            )
            padded = marker.join(result, how="left")
            from pathway_tpu.internals.expression import if_else

            pad_cols = {}
            for n in out:
                col = result[n]
                if n in count_rooted or n == "_pw_having":
                    pad_cols[n] = if_else(
                        col.is_not_none(),
                        col,
                        wrap_expression(
                            0 if n in count_rooted else False
                        ),
                    )
                else:
                    pad_cols[n] = col
            result = padded.select(**pad_cols)
            if q["having"] is not None:
                result = result.filter(result["_pw_having"])[
                    [n for n in out if n != "_pw_having"]
                ]
            return result
        if q["having"] is not None:
            raise ValueError(
                "pw.sql: HAVING without GROUP BY requires an aggregate "
                "predicate"
            )
        out = {}
        for idx, (node, alias) in enumerate(q["items"]):
            if node == "*":
                for name in current.column_names():
                    if not name.startswith("_pw_sq_"):
                        out[name] = current[name]
                continue
            out[self._item_name(node, alias, idx)] = self.expr(node, scope)
        return current.select(**out)


def sql(query: str, **tables: Table) -> Table:
    """Run a SQL query over the given tables (reference: pw.sql)."""
    ast = _Parser(_tokenize(query)).parse_query()
    return _Lowerer(tables).lower(ast)
