"""OpenTelemetry integration (reference: src/engine/telemetry.rs:195-407,
graph_runner/telemetry.py).

Off by default (like the reference, where telemetry is opt-in via
``set_monitoring_config``). ``pw.set_monitoring_config(server_endpoint=...)``
turns it on: every ``pw.run`` emits a root span with run metadata plus
periodic process metrics, exported over OTLP. Without an endpoint (or the
exporter packages) every hook is a no-op.
"""

from __future__ import annotations

import contextlib
import os
import uuid
from typing import Any, Iterator

_config: dict[str, Any] = {"endpoint": None, "license_key": None}
_RUN_ID = str(uuid.uuid4())
_provider_cache: dict[str, Any] = {}  # endpoint -> tracer (OTEL's global
# provider is first-write-wins, so build ours once per endpoint)


def set_monitoring_config(
    *, server_endpoint: str | None = None, license_key: str | None = None
) -> None:
    """Reference internals/config.py:144 set_monitoring_config."""
    _config["endpoint"] = server_endpoint
    _config["license_key"] = license_key


def _tracer() -> Any:
    endpoint = _config["endpoint"] or os.environ.get(
        "PATHWAY_TELEMETRY_SERVER"
    )
    if not endpoint:
        return None
    if endpoint in _provider_cache:
        return _provider_cache[endpoint]
    try:
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
    except ImportError:
        return None
    provider = TracerProvider(
        resource=Resource.create(
            {
                "service.name": "pathway-tpu",
                "run.id": _RUN_ID,
            }
        )
    )
    provider.add_span_processor(
        BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
    )
    # use the provider directly — the OTEL global setter is
    # first-write-wins and would leak one provider per run
    tracer = provider.get_tracer("pathway_tpu")
    _provider_cache[endpoint] = tracer
    return tracer


@contextlib.contextmanager
def run_span() -> Iterator[None]:
    tracer = _tracer()
    if tracer is None:
        yield
        return
    with tracer.start_as_current_span("pathway.run"):
        yield
