"""OpenTelemetry integration (reference: src/engine/telemetry.rs:195-407,
graph_runner/telemetry.py).

Off by default (like the reference, where telemetry is opt-in via
``set_monitoring_config``). ``pw.set_monitoring_config(server_endpoint=...)``
turns it on: every ``pw.run`` emits

- a root ``pathway.run`` span with run metadata,
- one child span per operator at run end carrying that operator's
  insertions/deletions/batches and time inside ``process()``
  (the per-operator trace surface of telemetry.rs),
- periodic process metrics — RSS, CPU utilization, thread count — plus
  per-operator row counters, sampled by a background thread every
  ``PATHWAY_TELEMETRY_INTERVAL_S`` seconds (default 5; reference
  telemetry.rs:195-407 periodic reader).

Metric samples are ALWAYS collected into an in-process snapshot
(:func:`latest_process_metrics`) while a run is live — the OTLP export is
the only part gated on the endpoint, so tests and the monitoring HTTP
surface read the same numbers without exporter packages.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time as _time
import uuid
from typing import Any, Iterator

_config: dict[str, Any] = {"endpoint": None, "license_key": None}
_RUN_ID = str(uuid.uuid4())
_provider_cache: dict[str, Any] = {}  # endpoint -> tracer (OTEL's global
# provider is first-write-wins, so build ours once per endpoint)
_latest_metrics: dict[str, Any] = {}


def set_monitoring_config(
    *, server_endpoint: str | None = None, license_key: str | None = None
) -> None:
    """Reference internals/config.py:144 set_monitoring_config."""
    _config["endpoint"] = server_endpoint
    _config["license_key"] = license_key


def _tracer() -> Any:
    endpoint = _config["endpoint"] or os.environ.get(
        "PATHWAY_TELEMETRY_SERVER"
    )
    if not endpoint:
        return None
    if endpoint in _provider_cache:
        return _provider_cache[endpoint]
    try:
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
    except ImportError:
        return None
    provider = TracerProvider(
        resource=Resource.create(
            {
                "service.name": "pathway-tpu",
                "run.id": _RUN_ID,
            }
        )
    )
    provider.add_span_processor(
        BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
    )
    # use the provider directly — the OTEL global setter is
    # first-write-wins and would leak one provider per run
    tracer = provider.get_tracer("pathway_tpu")
    _provider_cache[endpoint] = tracer
    return tracer


def _operator_stats(scheduler: Any) -> dict[str, dict[str, Any]]:
    """idx-labelled per-operator counters, snapshotting the stats dict
    (the run thread inserts entries lazily mid-run)."""
    ops: dict[str, dict[str, Any]] = {}
    if scheduler is None:
        return ops
    for idx, st in list(getattr(scheduler, "stats", {}).items()):
        try:
            node = scheduler.scope.nodes[idx]
            name = f"{idx}:{getattr(node, 'name', type(node).__name__)}"
        except Exception:  # noqa: BLE001
            name = str(idx)
        ops[name] = dict(
            insertions=getattr(st, "insertions", 0),
            deletions=getattr(st, "deletions", 0),
            batches=getattr(st, "batches", 0),
            time_spent=getattr(st, "time_spent", 0.0),
        )
    return ops


def _sample_process(scheduler: Any) -> dict[str, Any]:
    """One metrics sample: process gauges + per-operator counters."""
    sample: dict[str, Any] = {"ts": _time.time()}
    try:
        import psutil

        proc = psutil.Process()
        sample["memory_rss_bytes"] = proc.memory_info().rss
        sample["cpu_percent"] = proc.cpu_percent(interval=None)
        sample["num_threads"] = proc.num_threads()
    except Exception:  # noqa: BLE001 — psutil optional
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        sample["memory_rss_bytes"] = ru.ru_maxrss * 1024
        sample["cpu_seconds"] = ru.ru_utime + ru.ru_stime
    if scheduler is not None:
        sample["operators"] = _operator_stats(scheduler)
    return sample


def latest_process_metrics() -> dict[str, Any]:
    """Most recent sample of the live (or last) run (published
    atomically by the sampler; a final sample lands at run end)."""
    return dict(_latest_metrics)


def telemetry_enabled() -> bool:
    return bool(
        _config["endpoint"]
        or os.environ.get("PATHWAY_TELEMETRY_SERVER")
        or os.environ.get("PATHWAY_PROCESS_METRICS")
    )


class _MetricsSampler(threading.Thread):
    """Periodic process-metrics pump (reference telemetry.rs:195-407).

    Samples regardless of OTLP; exports each sample as gauge values when
    an endpoint + the OTEL metrics packages are available."""

    def __init__(self, scheduler_ref: Any, interval_s: float) -> None:
        super().__init__(name="pw-telemetry", daemon=True)
        self._scheduler_ref = scheduler_ref
        self._interval = interval_s
        self._stop = threading.Event()
        self._exporter = self._make_exporter()

    def _make_exporter(self) -> Any:
        endpoint = _config["endpoint"] or os.environ.get(
            "PATHWAY_TELEMETRY_SERVER"
        )
        if not endpoint:
            return None
        try:
            from opentelemetry.exporter.otlp.proto.grpc.metric_exporter import (
                OTLPMetricExporter,
            )
            from opentelemetry.sdk.metrics import MeterProvider
            from opentelemetry.sdk.metrics.export import (
                PeriodicExportingMetricReader,
            )
            from opentelemetry.sdk.resources import Resource
        except ImportError:
            return None
        reader = PeriodicExportingMetricReader(
            OTLPMetricExporter(endpoint=endpoint),
            export_interval_millis=int(self._interval * 1000),
        )
        provider = MeterProvider(
            metric_readers=[reader],
            resource=Resource.create(
                {"service.name": "pathway-tpu", "run.id": _RUN_ID}
            ),
        )
        meter = provider.get_meter("pathway_tpu")
        gauges = {
            "memory_rss_bytes": meter.create_gauge("process.memory.rss"),
            "cpu_percent": meter.create_gauge("process.cpu.percent"),
            "num_threads": meter.create_gauge("process.threads"),
        }
        return {"provider": provider, "gauges": gauges}

    def _sample_once(self) -> None:
        global _latest_metrics
        sample = _sample_process(self._scheduler_ref())
        _latest_metrics = sample  # atomic publish by rebinding
        if self._exporter is not None:
            for key, gauge in self._exporter["gauges"].items():
                if key in sample:
                    gauge.set(sample[key])

    def run(self) -> None:
        while not self._stop.wait(self._interval):
            with contextlib.suppress(Exception):
                self._sample_once()

    def stop(self) -> None:
        self._stop.set()
        # join before the final sample: a raising run must not leave the
        # sampler thread alive mid-_sample_once (shutdown hygiene — the
        # regression test asserts no pw-telemetry thread survives pw.run)
        if self.is_alive():
            with contextlib.suppress(Exception):
                self.join(timeout=5.0)
        # final sample: runs shorter than one interval still publish their
        # end-of-run process + operator counters
        with contextlib.suppress(Exception):
            self._sample_once()
        if self._exporter is not None:
            with contextlib.suppress(Exception):
                self._exporter["provider"].shutdown()


def _emit_operator_spans(tracer: Any, scheduler: Any) -> None:
    """One span per operator with its run-total counters — the
    per-operator trace surface (reference telemetry.rs spans)."""
    if tracer is None or scheduler is None:
        return
    for name, st in _operator_stats(scheduler).items():
        with tracer.start_as_current_span(f"operator.{name}") as span:
            span.set_attribute("operator.insertions", st["insertions"])
            span.set_attribute("operator.deletions", st["deletions"])
            span.set_attribute("operator.batches", st["batches"])
            span.set_attribute("operator.time_spent_s", st["time_spent"])


@contextlib.contextmanager
def run_span(scheduler_getter: Any = None) -> Iterator[None]:
    """Root run span + periodic metrics sampler around ``pw.run``.

    ``scheduler_getter`` returns the live scheduler (or None before the
    run starts) so the sampler and operator spans can read its stats."""
    tracer = _tracer()
    getter = scheduler_getter or (lambda: None)
    # sampling follows the telemetry switch, not tracer availability — an
    # endpoint without the OTEL trace packages still collects samples
    enabled = telemetry_enabled()
    sampler: _MetricsSampler | None = None
    if enabled:
        interval = float(
            os.environ.get("PATHWAY_TELEMETRY_INTERVAL_S", "5")
        )
        sampler = _MetricsSampler(getter, interval)
        sampler.start()
    try:
        if tracer is None:
            yield
        else:
            with tracer.start_as_current_span("pathway.run"):
                yield
                _emit_operator_spans(tracer, getter())
    finally:
        if sampler is not None:
            sampler.stop()
