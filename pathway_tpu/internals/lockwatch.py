"""Runtime lock-order recorder — the dynamic complement to the static
``PWC4xx`` lint (:mod:`pathway_tpu.analysis.concurrency`).

The static pass sees every *lexical* acquisition but cannot observe
orders that only materialize through indirection (callbacks, per-peer
lock dicts, locks passed across modules).  This watcher wraps
``threading.Lock``/``RLock`` creation so every acquisition records:

- the **lock-order graph**: an edge ``A -> B`` whenever ``B`` is
  acquired while ``A`` is held.  A new edge that closes a directed
  cycle is a potential deadlock — it lands in the flight recorder, in
  ``cycles()``, and as a ``pathway_lockwatch_cycle_p<pid>.json`` report
  under ``PATHWAY_TPU_LOCKWATCH_DIR`` (default: the temp dir) so soak
  gates can fail on it after the fact.
- **hold-time gauges**: ``pathway_lock_hold_seconds_max{lock=...}`` and
  ``pathway_lock_acquisitions_total{lock=...}`` on the process registry,
  keyed by the lock's creation site (``file.py:lineno``).

Enable with ``PATHWAY_TPU_LOCKWATCH=1`` (the chaos/soak gates in
``tools/check.py`` do).  Installation must happen before the runtime
modules create their locks — ``pathway_tpu/__init__`` calls
:func:`maybe_install` first thing, so setting the env var before import
is enough.  When disabled nothing is patched and the overhead is zero;
when enabled, each acquire/release pays two dict operations and a
perf-counter read.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time as _time
from typing import Any

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: creation-site name -> {successor name -> first-observed (file, line)}
_ORDER: dict[str, dict[str, tuple[str, int]]] = {}
_ORDER_LOCK = _REAL_LOCK()
_CYCLES: list[dict[str, Any]] = []
_HELD = threading.local()
_INSTALLED = False
_METRIC_HANDLES: dict[str, tuple[Any, Any]] = {}


def enabled() -> bool:
    return os.environ.get("PATHWAY_TPU_LOCKWATCH", "0") not in (
        "0",
        "",
        "false",
    )


def _creation_site(depth: int = 2) -> str:
    """``file.py:lineno`` of the lock's creation, skipping this module."""
    frame = sys._getframe(depth)
    while frame is not None and frame.f_code.co_filename == __file__:
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    fname = os.path.basename(frame.f_code.co_filename)
    return f"{fname}:{frame.f_lineno}"


def _held_stack() -> list[str]:
    stack = getattr(_HELD, "stack", None)
    if stack is None:
        stack = _HELD.stack = []
    return stack


def _handles(name: str) -> tuple[Any, Any]:
    pair = _METRIC_HANDLES.get(name)
    if pair is None:
        from pathway_tpu.internals import metrics as _metrics

        pair = (
            _metrics.REGISTRY.gauge(
                "pathway_lock_hold_seconds_max",
                "longest observed hold of this lock",
                lock=name,
            ),
            _metrics.REGISTRY.counter(
                "pathway_lock_acquisitions_total",
                "times this lock was acquired",
                lock=name,
            ),
        )
        _METRIC_HANDLES[name] = pair
    return pair


def _report_cycle(path: list[str], mod_edge: tuple[str, str]) -> None:
    report = {
        "kind": "lock_order_cycle",
        "cycle": path,
        "closing_edge": list(mod_edge),
        "pid": os.getpid(),
        "wall": _time.time(),
    }
    _CYCLES.append(report)
    try:
        from pathway_tpu.internals.metrics import FLIGHT

        FLIGHT.record(
            "lock_order_cycle",
            cycle=" -> ".join(path),
            closing_edge=f"{mod_edge[0]} -> {mod_edge[1]}",
        )
    except Exception:  # noqa: BLE001 — never let forensics break the app
        pass
    directory = os.environ.get(
        "PATHWAY_TPU_LOCKWATCH_DIR"
    ) or tempfile.gettempdir()
    try:
        os.makedirs(directory, exist_ok=True)
        path_out = os.path.join(
            directory, f"pathway_lockwatch_cycle_p{os.getpid()}.json"
        )
        with open(path_out, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(report) + "\n")
    except OSError:
        pass


def _record_edge(holder: str, target: str) -> None:
    """Add ``holder -> target``; on a NEW edge, DFS for a return path."""
    cycle: list[str] | None = None
    with _ORDER_LOCK:
        succ = _ORDER.setdefault(holder, {})
        if target in succ:
            return
        succ[target] = ("", 0)
        # does target already reach holder?  (new edge closes a cycle)
        stack, seen = [target], {target}
        path_parent: dict[str, str] = {}
        while stack and cycle is None:
            node = stack.pop()
            if node == holder:
                cycle = [holder]
                cur = holder
                while cur != target:
                    cur = path_parent[cur]
                    cycle.append(cur)
                cycle.append(holder)
                break
            for nxt in _ORDER.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    path_parent[nxt] = node
                    stack.append(nxt)
    if cycle is not None:
        # emit OUTSIDE the order lock: the flight recorder's own (watched)
        # lock acquisition re-enters this module
        _report_cycle(cycle, (holder, target))


class _WatchedLock:
    """Delegating wrapper; quacks enough like ``threading.Lock`` for
    ``Condition`` (acquire/release/locked + context manager)."""

    __slots__ = ("_inner", "_name", "_t0", "_reentry")

    def __init__(self, inner: Any, name: str) -> None:
        self._inner = inner
        self._name = name
        self._t0 = 0.0
        self._reentry = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            stack = _held_stack()
            if self._name in stack:
                # RLock re-entry: no new edge, no double bookkeeping
                self._reentry += 1
            else:
                if stack:
                    _record_edge(stack[-1], self._name)
                stack.append(self._name)
                self._t0 = _time.perf_counter()
        return got

    def release(self) -> None:
        stack = _held_stack()
        dur = None
        if self._reentry and self._name in stack:
            self._reentry -= 1
        elif self._name in stack:
            stack.remove(self._name)
            dur = _time.perf_counter() - self._t0
        # inner FIRST: the gauge update below re-enters the registry,
        # whose own lock may be the very lock being released
        self._inner.release()
        if dur is not None and not getattr(_HELD, "in_metrics", False):
            _HELD.in_metrics = True
            try:
                g_max, c_total = _handles(self._name)
                if dur > g_max.value:
                    g_max.value = round(dur, 6)
                c_total.inc()
            except Exception:  # noqa: BLE001 — metrics must not break locks
                pass
            finally:
                _HELD.in_metrics = False

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # Condition() introspects these when present on RLocks; delegate so
    # a watched RLock still wait()s correctly.
    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<WatchedLock {self._name} {self._inner!r}>"


def _make_lock() -> _WatchedLock:
    return _WatchedLock(_REAL_LOCK(), _creation_site())


def _make_rlock() -> _WatchedLock:
    return _WatchedLock(_REAL_RLOCK(), _creation_site())


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` factories (idempotent)."""
    global _INSTALLED
    if _INSTALLED:
        return
    threading.Lock = _make_lock  # type: ignore[assignment]
    threading.RLock = _make_rlock  # type: ignore[assignment]
    _INSTALLED = True


def uninstall() -> None:
    global _INSTALLED
    if not _INSTALLED:
        return
    threading.Lock = _REAL_LOCK  # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
    _INSTALLED = False


def maybe_install() -> None:
    if enabled():
        install()


def cycles() -> list[dict[str, Any]]:
    """Cycle reports recorded so far (this process)."""
    with _ORDER_LOCK:
        return list(_CYCLES)


def reset() -> None:
    """Drop recorded state (tests)."""
    with _ORDER_LOCK:
        _ORDER.clear()
        _CYCLES.clear()
