"""GroupedTable — `table.groupby(...).reduce(...)`.

(reference: python/pathway/internals/groupbys.py, 402 LoC)
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.desugaring import resolve_this, substitute
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
)

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class GroupedTable:
    def __init__(
        self,
        table: "Table",
        by: list[ColumnReference],
        set_id: bool = False,
        instance_last: bool = False,
    ) -> None:
        self._table = table
        self._by = by
        self._set_id = set_id
        self._instance_last = instance_last

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        from pathway_tpu.internals.table import Table, TableSpec

        table = self._table
        exprs: dict[str, ColumnExpression] = {}
        for arg in args:
            from pathway_tpu.internals.thisclass import ThisStar

            if isinstance(arg, ThisStar):
                from pathway_tpu.internals.thisclass import this

                if arg._owner is not this:
                    raise ValueError(
                        f"{arg!r} cannot be used here; use *pw.this"
                    )
                # *pw.this inside reduce: the grouping columns (anything
                # else is invalid in a reduce anyway)
                for ref in self._by:
                    exprs[ref.name] = ref
                continue
            resolved = resolve_this(arg, table)
            if not isinstance(resolved, ColumnReference):
                raise ValueError("positional reduce arguments must be column references")
            exprs[resolved.name] = resolved
        for name, value in kwargs.items():
            exprs[name] = resolve_this(value, table)

        by_names = {ref.name for ref in self._by}
        # validate: plain column refs in outputs must be grouping columns
        for name, e in exprs.items():
            for ref in e._dependencies():
                if isinstance(ref, ColumnReference) and ref.table is table:
                    if ref.name not in by_names and not self._set_id and ref.name != "id":
                        # it may appear under a reducer; verified during lowering
                        pass

        dtypes = {n: e._dtype for n, e in exprs.items()}
        return Table(
            TableSpec(
                "groupby_reduce",
                [table],
                {
                    "by": self._by,
                    "exprs": exprs,
                    "set_id": self._set_id,
                    "instance_last": self._instance_last,
                },
            ),
            list(exprs.keys()),
            dtypes,
        )
