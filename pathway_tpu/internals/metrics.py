"""Unified metrics plane: registry, histograms, snapshots, flight recorder.

One process-wide :data:`REGISTRY` holds every counter, gauge and
fixed-bucket histogram the engine hot paths touch.  Design constraints,
in order:

- **lock-cheap, allocation-free hot path** — instrument handles are
  created once (under a lock) and cached by the call site; a bump is a
  plain attribute ``+=`` (GIL-atomic enough for monitoring counters) and
  a histogram observe is a ``bisect`` plus two adds, no allocation;
- **mesh-transparent** — :meth:`Registry.snapshot` returns a plain
  picklable/JSON-able dict that followers piggyback on existing
  ``MeshTransport`` round frames; the leader merges the per-worker
  snapshots and :func:`render_snapshots` exposes the whole mesh from one
  ``/metrics`` endpoint with ``worker="<pid>"`` labels
  (reference telemetry: src/engine/telemetry.rs:195-407, endpoint:
  src/engine/http_server.rs:22-194);
- **no engine imports** — ``engine/graph.py`` and friends import this
  module, so it depends on the stdlib only; pull-collectors for the
  native kernels and the graph optimizer defer their imports to scrape
  time.

The :class:`FlightRecorder` is the crash-forensics side of the same
plane: a bounded ring of recent structured events (commits, exchanges,
retractions, errors) that ``pw.run`` dumps to a JSON file when a run
raises, from any worker (``PATHWAY_TPU_FLIGHT_DIR`` picks the directory,
``PATHWAY_TPU_FLIGHT_EVENTS`` the ring size).

The fault-tolerance layer (engine/distributed.py, engine/faults.py,
internals/runner.py) reports through the same registry:

- ``pathway_mesh_recoveries_total`` — mesh-wide recoveries completed
  after a worker loss (leader increments after the post-rollback
  resync barrier);
- ``pathway_mesh_send_retries_total`` — mesh frames recovered by the
  bounded send-retry path (transient socket errors, not peer deaths);
- ``pathway_connector_retries_total`` — connector reader polls retried
  after transient I/O errors;
- ``pathway_mesh_recv_backpressure`` — receiver threads currently
  blocked on a full per-peer frame queue
  (``PATHWAY_TPU_MESH_QUEUE_HWM``);

The elastic-mesh layer (leader failover + rescale) adds:

- ``pathway_mesh_epoch`` — gauge; current mesh recovery epoch (bumped
  by every recovery and every leader election; frames stamped with an
  older epoch are fenced);
- ``pathway_mesh_fenced_frames_total`` — stale epoch-stamped command
  frames rejected by the fence (zombie ex-leader or fault-injected
  duplicates);
- ``pathway_mesh_elections_total`` / ``pathway_mesh_election_seconds``
  — leader elections completed after losing process 0, and their
  detection→election-complete wall time (interim leader observes);
- ``pathway_mesh_rescales_total`` / ``pathway_mesh_rescale_seconds``
  — completed N→M rescales and their quiesce→relaunch wall time
  (relaunched leader surfaces both from the supervisor's env stamps).

The async device pipeline (engine/device_pipeline.py) adds:

- ``pathway_device_queue_depth`` — gauge; commits currently staged in or
  completing through the device pipeline (0 when idle or synchronous);
- ``pathway_device_occupancy_ratio`` — gauge; EMA share of wall time the
  completion stage is busy (1.0 = the device is the bottleneck);
- ``pathway_device_dispatch_complete_seconds`` — histogram; commit
  dispatch → in-order completion latency;
- ``pathway_device_pipeline_commits_total`` — device commits retired
  through the async path;
- ``pathway_device_knn_updates_total`` / ``pathway_device_knn_queries_total``
  — mutation and query volume dispatched to the device KNN index.

The device-residency plane (engine/device_residency.py) adds:

- ``pathway_device_transfer_h2d_events_total`` /
  ``pathway_device_transfer_h2d_bytes_total`` — host→device uploads on
  the exchange/operator seam (counted in both residency modes, so
  on/off runs are directly comparable);
- ``pathway_device_transfer_d2h_events_total`` /
  ``pathway_device_transfer_d2h_bytes_total`` — device→host fetches on
  the same seam, including decline-path whole-buffer materializations;
- ``pathway_device_residency_bytes_saved_total`` — payload bytes that
  stayed on device instead of round-tripping at the seam;
- ``pathway_device_residency_events_total`` — labelled ``kind=`` with
  ``resident_batches``, ``device_consumes``, ``materializations``,
  ``declines`` — lifecycle volume of the resident delta-batch plane
  (mirrors ``device_residency.RESIDENCY_STATS``).

Each family renders on the leader ``/metrics`` with exactly one
HELP/TYPE block (the registry keys families by name).

The flight recorder carries the recovery lifecycle as events:
``peer_dead``, ``recovery_start``, ``recovery_parked``,
``recovery_remesh``, ``recovery_rollback``, ``recovery_done``,
``fault_kill``, ``leader_dead``, ``election_done``,
``leader_failover_done``, ``fenced_frame``, ``quiesce``, ``reshard`` —
every surviving worker dumps its ring when a peer is declared dead, so
a post-mortem has one JSON file per worker.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time as _time
from bisect import bisect_left
from collections import deque
from typing import Any, Callable, Iterable

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "FLIGHT",
    "FlightRecorder",
    "REQUESTS",
    "RequestLog",
    "MirroredCounterDict",
    "DEFAULT_LATENCY_BUCKETS",
    "full_snapshot",
    "render_snapshots",
    "parse_prometheus_text",
    "validate_exposition",
]

#: ingest->sink latency bucket upper bounds, seconds (power-of-~2.5 ladder
#: from 1ms to 10s, the span between "same-commit" and "stalled mesh")
DEFAULT_LATENCY_BUCKETS = (
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)


class Counter:
    """Monotonic counter; ``inc`` is a bare attribute add."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    """Point-in-time value; ``set`` is a bare attribute store."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


class Histogram:
    """Fixed-bucket histogram: ``len(bounds) + 1`` per-bucket counts (the
    last one is +Inf), a running sum and a total count.  ``observe`` is a
    bisect plus three adds — no allocation, no lock."""

    __slots__ = ("bounds", "counts", "sum", "count", "exemplars")

    def __init__(self, bounds: Iterable[float]) -> None:
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly increasing")
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        #: bucket index -> (trace_id, value); written only for sampled
        #: requests, read at scrape — whole-tuple replacement per slot,
        #: so concurrent writers/readers see either value, never a tear
        self.exemplars: dict[int, tuple] = {}

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def exemplar(self, v: float, trace_id: str) -> None:
        """Attach a trace-id exemplar to the bucket ``v`` falls in —
        called only for SAMPLED requests (off the unsampled hot path),
        so ``observe`` itself stays a bisect plus three adds."""
        self.exemplars[bisect_left(self.bounds, v)] = (
            str(trace_id),
            float(v),
        )

    def observe_n(self, v: float, n: int) -> None:
        """One value standing for ``n`` events (e.g. every row of a delta
        batch shares the batch's ingest->sink latency)."""
        if n <= 0:
            return
        self.counts[bisect_left(self.bounds, v)] += n
        self.sum += v * n
        self.count += n

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (0 when empty)."""
        if self.count <= 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= target and c > 0:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
                frac = (target - seen) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.bounds[-1]


class _Family:
    __slots__ = ("name", "kind", "help", "buckets", "series")

    def __init__(self, name: str, kind: str, help: str, buckets=None) -> None:
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = buckets
        #: sorted-label-items tuple -> Counter | Gauge | Histogram
        self.series: dict[tuple, Any] = {}


class Registry:
    """Named metric families, each a set of label-addressed series.

    Handle creation takes the lock; the returned instrument is meant to
    be cached by the call site so the hot path never re-enters here."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}  # guarded-by: self._lock
        self._collectors: list[Callable[[], Iterable[tuple]]] = []  # guarded-by: self._lock

    # -- instrument handles --------------------------------------------------

    def _series(self, name, kind, help, labels, factory, buckets=None):
        key = tuple(sorted(labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, kind, help, buckets)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            inst = fam.series.get(key)
            if inst is None:
                inst = fam.series[key] = factory()
            return inst

    def counter(self, name: str, help: str = "", **labels: str) -> Counter:
        return self._series(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "", **labels: str) -> Gauge:
        return self._series(name, "gauge", help, labels, Gauge)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS,
        **labels: str,
    ) -> Histogram:
        buckets = tuple(float(b) for b in buckets)
        return self._series(
            name,
            "histogram",
            help,
            labels,
            lambda: Histogram(buckets),
            buckets,
        )

    # -- pull collectors -----------------------------------------------------

    def register_collector(self, fn: Callable[[], Iterable[tuple]]) -> None:
        """``fn`` yields ``(name, kind, help, labels_dict, value)`` sample
        tuples at scrape/snapshot time (native kernels, optimizer, ...)."""
        with self._lock:
            self._collectors.append(fn)

    # -- snapshots -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Plain-dict view of every family (plus collector samples):
        ``{name: {kind, help, buckets, series: [{labels, ...values}]}}``.
        Picklable (mesh frames) and JSON-able (flight dumps)."""
        with self._lock:
            fams = [
                (f.name, f.kind, f.help, f.buckets, list(f.series.items()))
                for f in self._families.values()
            ]
            collectors = list(self._collectors)
        out: dict = {}
        for name, kind, help, buckets, series in fams:
            fam = out[name] = {
                "kind": kind,
                "help": help,
                "buckets": list(buckets) if buckets else None,
                "series": [],
            }
            for key, inst in series:
                entry: dict = {"labels": dict(key)}
                if kind == "histogram":
                    entry["counts"] = list(inst.counts)
                    entry["sum"] = inst.sum
                    entry["count"] = inst.count
                    ex = getattr(inst, "exemplars", None)
                    if ex:
                        entry["exemplars"] = {
                            str(i): [tid, v]
                            for i, (tid, v) in sorted(ex.items())
                        }
                else:
                    entry["value"] = inst.value
                fam["series"].append(entry)
        for fn in collectors:
            try:
                samples = list(fn())
            except Exception:
                continue  # a broken collector must not break the scrape
            merge_samples(out, samples)
        return out

    def reset(self) -> None:
        """Drop every family (tests only — cached handles go stale)."""
        with self._lock:
            self._families.clear()


def merge_samples(snap: dict, samples: Iterable[tuple]) -> dict:
    """Fold ``(name, kind, help, labels, value)`` tuples into a snapshot
    dict (collector output, per-operator scheduler series)."""
    for name, kind, help, labels, value in samples:
        fam = snap.get(name)
        if fam is None:
            fam = snap[name] = {
                "kind": kind,
                "help": help,
                "buckets": None,
                "series": [],
            }
        fam["series"].append({"labels": dict(labels), "value": float(value)})
    return snap


def operator_samples(stats: dict, nodes: Iterable = ()) -> list[tuple]:
    """Per-operator sample tuples from a scheduler's ``stats`` mapping
    (index -> OperatorStats); ``nodes`` supplies names when available."""
    names = {}
    for node in nodes:
        try:
            names[node.index] = node.name
        except Exception:
            pass
    out = []
    for index, st in sorted(stats.items()):
        labels = {
            "operator": str(names.get(index, "")),
            "index": str(index),
        }
        out.append(
            (
                "pathway_operator_rows",
                "gauge",
                "net rows resident per operator",
                labels,
                st.insertions - st.deletions,
            )
        )
        out.append(
            (
                "pathway_operator_time_seconds",
                "counter",
                "cumulative process() wall time per operator",
                labels,
                st.time_spent,
            )
        )
        out.append(
            (
                "pathway_operator_batches_total",
                "counter",
                "delta batches processed per operator",
                labels,
                st.batches,
            )
        )
    return out


def full_snapshot(scheduler: Any = None) -> dict:
    """Registry snapshot plus this worker's per-operator series — the
    payload a follower piggybacks to the leader."""
    snap = REGISTRY.snapshot()
    if scheduler is not None:
        stats = getattr(scheduler, "stats", None)
        if stats:
            scope = getattr(scheduler, "scope", None)
            nodes = getattr(scope, "nodes", ()) if scope is not None else ()
            merge_samples(snap, operator_samples(dict(stats), list(nodes)))
    return snap


# -- exposition rendering ----------------------------------------------------


def escape_label_value(v: str) -> str:
    """Prometheus exposition label escaping: backslash, quote, newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_bound(b: float) -> str:
    return _fmt_value(b) if b == int(b) else ("%g" % b)


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_exemplar(ex) -> str:
    """OpenMetrics exemplar suffix for one bucket line
    (`` # {trace_id="..."} <value>``), or "" when the bucket has none."""
    if not ex:
        return ""
    tid, value = ex[0], ex[1]
    return (
        f' # {{trace_id="{escape_label_value(str(tid))}"}}'
        f" {_fmt_value(float(value))}"
    )


def render_snapshots(snaps: "dict[str, dict]") -> str:
    """Exposition text for worker-keyed snapshots.  Key ``""`` renders
    without a ``worker`` label (the leader's legacy local series); any
    other key is added as ``worker="<key>"`` on every sample.  Each
    family name gets exactly one HELP/TYPE block even when several
    workers report it.  Keys starting with ``__`` are reserved for
    piggybacked sidecar payloads (e.g. the profiler's
    ``"__profile__"``) and are never rendered as families."""
    order: list[str] = []
    meta: dict[str, dict] = {}
    for snap in snaps.values():
        for name, fam in snap.items():
            if name.startswith("__"):
                continue
            if name not in meta:
                meta[name] = fam
                order.append(name)
    lines: list[str] = []
    for name in order:
        fam = meta[name]
        help = fam.get("help") or name
        lines.append(f"# HELP {name} {help}".replace("\n", " "))
        lines.append(f"# TYPE {name} {fam['kind']}")
        for worker, snap in snaps.items():
            wfam = snap.get(name)
            if wfam is None:
                continue
            for entry in wfam["series"]:
                labels = dict(entry["labels"])
                if worker != "":
                    labels["worker"] = worker
                if fam["kind"] == "histogram":
                    bounds = list(wfam.get("buckets") or [])
                    counts = entry["counts"]
                    exemplars = entry.get("exemplars") or {}
                    cum = 0
                    for i, (bound, c) in enumerate(zip(bounds, counts)):
                        cum += c
                        blabels = dict(labels)
                        blabels["le"] = _fmt_bound(bound)
                        lines.append(
                            f"{name}_bucket{_label_str(blabels)} {cum}"
                            f"{_fmt_exemplar(exemplars.get(str(i)))}"
                        )
                    blabels = dict(labels)
                    blabels["le"] = "+Inf"
                    lines.append(
                        f"{name}_bucket{_label_str(blabels)} {entry['count']}"
                        f"{_fmt_exemplar(exemplars.get(str(len(bounds))))}"
                    )
                    lines.append(
                        f"{name}_sum{_label_str(labels)} "
                        f"{_fmt_value(entry['sum'])}"
                    )
                    lines.append(
                        f"{name}_count{_label_str(labels)} {entry['count']}"
                    )
                else:
                    lines.append(
                        f"{name}{_label_str(labels)} "
                        f"{_fmt_value(entry['value'])}"
                    )
    return "\n".join(lines) + "\n" if lines else ""


# -- exposition parsing ------------------------------------------------------


def _parse_labels(text: str, lineno: int) -> dict:
    labels: dict[str, str] = {}
    i = 0
    n = len(text)
    while i < n:
        while i < n and text[i] in " \t":
            i += 1
        j = i
        while j < n and (text[j].isalnum() or text[j] == "_"):
            j += 1
        if j == i:
            raise ValueError(f"line {lineno}: bad label name at {text[i:]!r}")
        name = text[i:j]
        if j >= n or text[j] != "=":
            raise ValueError(f"line {lineno}: expected '=' after {name}")
        j += 1
        if j >= n or text[j] != '"':
            raise ValueError(f"line {lineno}: expected '\"' in {name} value")
        j += 1
        buf = []
        while j < n and text[j] != '"':
            if text[j] == "\\":
                j += 1
                if j >= n:
                    raise ValueError(f"line {lineno}: dangling escape")
                c = text[j]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(c, "\\" + c))
            else:
                buf.append(text[j])
            j += 1
        if j >= n:
            raise ValueError(f"line {lineno}: unterminated label value")
        labels[name] = "".join(buf)
        j += 1
        if j < n and text[j] == ",":
            j += 1
        elif j < n:
            raise ValueError(f"line {lineno}: expected ',' got {text[j]!r}")
        i = j
    return labels


def parse_prometheus_text(text: str) -> dict:
    """Parse exposition text into
    ``{family: {"type", "help", "samples": [(name, labels, value)]}}``.
    Histogram ``_bucket``/``_sum``/``_count`` samples are grouped under
    their family name.  Raises ``ValueError`` on malformed lines."""
    families: dict[str, dict] = {}

    def fam(name: str) -> dict:
        f = families.get(name)
        if f is None:
            f = families[name] = {"type": None, "help": None, "samples": []}
        return f

    typed: dict[str, str] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: bad comment {raw!r}")
            name = parts[2]
            if parts[1] == "TYPE":
                kind = parts[3].strip() if len(parts) > 3 else ""
                if kind not in ("counter", "gauge", "histogram", "summary"):
                    raise ValueError(f"line {lineno}: bad type {kind!r}")
                fam(name)["type"] = kind
                typed[name] = kind
            else:
                fam(name)["help"] = parts[3] if len(parts) > 3 else ""
            continue
        # OpenMetrics exemplar suffix: `<sample> # {labels} <value>` —
        # split it off FIRST so rfind("}") sees the sample's own braces
        line, _sep, exemplar_part = line.partition(" # ")
        exemplar_part = exemplar_part.strip()
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ValueError(f"line {lineno}: unbalanced braces")
            sample_name = line[:brace]
            labels = _parse_labels(line[brace + 1 : close], lineno)
            rest = line[close + 1 :].strip()
        else:
            bits = line.split()
            if len(bits) < 2:
                raise ValueError(f"line {lineno}: no value on {raw!r}")
            sample_name, rest = bits[0], " ".join(bits[1:])
            labels = {}
        value_str = rest.split()[0] if rest else ""
        try:
            value = float(value_str)
        except ValueError:
            raise ValueError(
                f"line {lineno}: bad value {value_str!r}"
            ) from None
        base = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            cand = sample_name[: -len(suffix)]
            if sample_name.endswith(suffix) and typed.get(cand) == "histogram":
                base = cand
                break
        fam(base)["samples"].append((sample_name, labels, value))
        if exemplar_part:
            if not exemplar_part.startswith("{"):
                raise ValueError(
                    f"line {lineno}: bad exemplar {exemplar_part!r}"
                )
            ex_close = exemplar_part.find("}")
            if ex_close < 0:
                raise ValueError(
                    f"line {lineno}: unterminated exemplar labels"
                )
            ex_labels = _parse_labels(exemplar_part[1:ex_close], lineno)
            ex_rest = exemplar_part[ex_close + 1 :].split()
            if not ex_rest:
                raise ValueError(f"line {lineno}: exemplar without value")
            try:
                ex_value = float(ex_rest[0])
            except ValueError:
                raise ValueError(
                    f"line {lineno}: bad exemplar value {ex_rest[0]!r}"
                ) from None
            fam(base).setdefault("exemplars", []).append(
                (sample_name, labels, ex_labels, ex_value)
            )
    return families


def validate_exposition(text: str) -> dict:
    """Strict OpenMetrics-style conformance check used by the test suite:
    every sample must belong to a family with HELP and TYPE lines;
    histogram families must expose cumulative ``_bucket`` series with an
    ``le="+Inf"`` bucket equal to ``_count``.  Returns the parse."""
    families = parse_prometheus_text(text)
    for name, fam in families.items():
        if fam["type"] is None:
            raise ValueError(f"family {name}: missing # TYPE line")
        if fam["help"] is None:
            raise ValueError(f"family {name}: missing # HELP line")
        if not fam["samples"]:
            raise ValueError(f"family {name}: no samples")
        if fam["type"] != "histogram":
            for sample_name, _labels, _v in fam["samples"]:
                if sample_name != name:
                    raise ValueError(
                        f"family {name}: stray sample {sample_name}"
                    )
            continue
        # histogram: group by label set minus le
        groups: dict[tuple, dict] = {}
        for sample_name, labels, value in fam["samples"]:
            rest = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            g = groups.setdefault(
                rest, {"buckets": [], "sum": None, "count": None}
            )
            if sample_name == name + "_bucket":
                if "le" not in labels:
                    raise ValueError(f"family {name}: bucket without le")
                g["buckets"].append((labels["le"], value))
            elif sample_name == name + "_sum":
                g["sum"] = value
            elif sample_name == name + "_count":
                g["count"] = value
            else:
                raise ValueError(f"family {name}: stray sample {sample_name}")
        for rest, g in groups.items():
            if not g["buckets"] or g["sum"] is None or g["count"] is None:
                raise ValueError(
                    f"family {name}{dict(rest)}: incomplete "
                    "_bucket/_sum/_count triple"
                )
            if g["buckets"][-1][0] != "+Inf":
                raise ValueError(f"family {name}: last bucket must be +Inf")
            values = [v for _le, v in g["buckets"]]
            if values != sorted(values):
                raise ValueError(f"family {name}: non-cumulative buckets")
            if values[-1] != g["count"]:
                raise ValueError(f"family {name}: +Inf bucket != _count")
    return families


# -- EXCHANGE_STATS absorption -----------------------------------------------


class MirroredCounterDict(dict):
    """Plain-dict façade whose integer writes mirror into a labelled
    registry counter family.  ``engine/routing.py``'s ``EXCHANGE_STATS``
    call sites all go through ``d[key] += 1`` (or ``d[key] = 0`` from
    tests), i.e. ``__setitem__`` with the new absolute total — so the
    mirror *sets* the counter's value, keeping the historical dict alias
    (imported by sharded.py and distributed.py) alive and authoritative.

    ``extra_labels`` attaches additional constant labels per key — e.g.
    EXCHANGE_STATS tags every kind with a ``path`` label (elided / host /
    device / total) so the exposition can distinguish delivery planes
    without breaking the flat dict the engine increments."""

    def __init__(
        self,
        metric: str,
        label: str,
        initial: dict,
        help: str = "",
        extra_labels: dict | None = None,
    ) -> None:
        super().__init__(initial)
        self._metric = metric
        self._label = label
        self._help = help
        self._extra = dict(extra_labels or {})
        self._series: dict[Any, Counter] = {}
        for key, value in initial.items():
            self[key] = value

    def __setitem__(self, key, value) -> None:
        dict.__setitem__(self, key, value)
        c = self._series.get(key)
        if c is None:
            labels = {self._label: str(key)}
            labels.update(self._extra.get(key, {}))
            c = REGISTRY.counter(self._metric, self._help, **labels)
            self._series[key] = c
        c.value = float(value)


# -- flight recorder ---------------------------------------------------------

# Optional callback returning the id of the in-flight sampled trace, if
# any (internals/tracing.py registers one at import; this module stays
# free of engine imports).  Flight events and dumps reference it so
# crash forensics can be joined against exported traces.
_TRACE_ID_PROVIDER = None


def set_trace_id_provider(fn) -> None:
    global _TRACE_ID_PROVIDER
    _TRACE_ID_PROVIDER = fn


def _active_trace_id():
    fn = _TRACE_ID_PROVIDER
    if fn is None:
        return None
    try:
        return fn()
    except Exception:
        return None


class FlightRecorder:
    """Bounded ring of recent structured events; dumped to JSON when a
    run raises so post-mortems see the last commits/exchanges/errors of
    *this* worker without any live scrape."""

    def __init__(self, maxlen: int | None = None) -> None:
        if maxlen is None:
            try:
                maxlen = int(
                    os.environ.get("PATHWAY_TPU_FLIGHT_EVENTS", "256")
                )
            except ValueError:
                maxlen = 256
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, maxlen))  # guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock
        self._dumps = 0  # guarded-by: self._lock

    def record(self, kind: str, **fields: Any) -> None:
        event = {"kind": kind, "wall": _time.time(), **fields}
        trace_id = _active_trace_id()
        if trace_id is not None:
            event.setdefault("trace_id", trace_id)
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, reason: str) -> str | None:
        """Write the ring to ``PATHWAY_TPU_FLIGHT_DIR`` (default: the
        system temp dir); returns the path, or None when even the dump
        fails (forensics must never mask the original error)."""
        try:
            directory = os.environ.get(
                "PATHWAY_TPU_FLIGHT_DIR", tempfile.gettempdir()
            )
            os.makedirs(directory, exist_ok=True)
            process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
            with self._lock:
                self._dumps += 1
                dump_no = self._dumps
            # pid + per-recorder counter in the name: concurrent workers
            # (and repeated dumps from one worker) sharing a FLIGHT_DIR
            # never clobber each other
            path = os.path.join(
                directory,
                f"pathway_flight_p{process_id}"
                f"_pid{os.getpid()}_{dump_no:03d}.json",
            )
            payload = {
                "reason": reason,
                "process_id": process_id,
                "pid": os.getpid(),
                "dumped_at": _time.time(),
                "trace_id": _active_trace_id(),
                "events": self.snapshot(),
            }
            with open(path, "w") as fh:
                json.dump(payload, fh, default=repr, indent=1)
            return path
        except Exception:
            return None


class RequestLog:
    """Bounded ring of per-request WIDE EVENTS: one structured record
    per served read-tier request (endpoint, status, stamp vector, cache
    disposition, fan-out width, shed/refusal reason, per-hop ns, trace
    id), served raw at ``/requests`` on the monitoring port.

    Same shape as the :class:`FlightRecorder` but a separate ring: the
    flight ring is crash forensics (commits, exchanges, errors) and a
    query flood must not evict it."""

    def __init__(self, maxlen: int | None = None) -> None:
        if maxlen is None:
            try:
                maxlen = int(
                    os.environ.get("PATHWAY_TPU_REQUEST_TRACE_RING", "256")
                )
            except ValueError:
                maxlen = 256
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, maxlen))  # guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock

    def record(self, **fields: Any) -> None:
        event = dict(fields)
        event.setdefault("wall", _time.time())
        trace_id = _active_trace_id()
        if trace_id is not None:
            event.setdefault("trace_id", trace_id)
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


#: the process-wide registry every engine hot path bumps
REGISTRY = Registry()

#: the process-wide flight recorder ``pw.run`` dumps on a raising run
FLIGHT = FlightRecorder()

#: the process-wide per-request wide-event ring behind ``/requests``
REQUESTS = RequestLog()


# -- built-in pull collectors (imports deferred to scrape time) ---------------


def _native_collector() -> list[tuple]:
    from pathway_tpu import native

    out = []
    for kernel, hits in native.hit_counts().items():
        out.append(
            (
                "pathway_native_kernel_hits_total",
                "counter",
                "C++ kernel engagements (native.hit_counts)",
                {"kernel": kernel},
                hits,
            )
        )
    kernel_ns = getattr(native, "kernel_ns", None)
    if kernel_ns is not None:
        for kernel, ns in kernel_ns().items():
            out.append(
                (
                    "pathway_native_kernel_ns_total",
                    "counter",
                    "cumulative nanoseconds inside each C++ kernel",
                    {"kernel": kernel},
                    ns,
                )
            )
    return out


def _optimizer_collector() -> list[tuple]:
    from pathway_tpu.optimize import optimizer_stats

    return [
        (
            f"pathway_optimizer_{key}",
            "gauge",
            "graph-rewriter counter from the most recent optimize run",
            {},
            value,
        )
        for key, value in optimizer_stats().items()
    ]


def _device_ops_collector() -> list[tuple]:
    """The device-operator twin of :func:`_native_collector`: JAX kernel
    hit/ns counters plus the placement policy's current per-operator
    decision (1 = device, 0 = host).  Rides the mesh snapshot piggyback
    like every registered collector, so leader ``/metrics`` and ``cli
    stats`` see every worker's device placement."""
    from pathway_tpu.engine import device_ops
    from pathway_tpu.optimize import placement

    out = []
    for kernel, hits in device_ops.hit_counts().items():
        out.append(
            (
                "pathway_device_ops_kernel_hits_total",
                "counter",
                "JAX device operator kernel launches (device_ops.hit_counts)",
                {"kernel": kernel},
                hits,
            )
        )
    for kernel, ns in device_ops.kernel_ns().items():
        out.append(
            (
                "pathway_device_ops_kernel_ns_total",
                "counter",
                "cumulative host-observed nanoseconds per device kernel",
                {"kernel": kernel},
                ns,
            )
        )
    for op, st in placement.POLICY.decisions().items():
        out.append(
            (
                "pathway_device_ops_placement",
                "gauge",
                "current operator placement (1 = device, 0 = host)",
                {"op": op},
                1 if st["device"] else 0,
            )
        )
    return out


REGISTRY.register_collector(_native_collector)
REGISTRY.register_collector(_optimizer_collector)
REGISTRY.register_collector(_device_ops_collector)
