"""Row transformers — the legacy ``@pw.transformer`` class syntax.

Reference: python/pathway/internals/row_transformer.py (294) over the
engine's demand-driven complex columns
(src/engine/dataflow/complex_columns.rs:489, Computer/ComplexColumn
graph.rs:302-343). A transformer class declares one inner class per input
table with ``input_attribute``s and computed ``output_attribute``s;
computations can follow pointers into sibling tables
(``self.transformer.other[ptr].attr``) and into other computed outputs —
including recursively (linked-list walks).

The reference resolves demand through a dataflow request/response loop;
here each output table is an engine node that recomputes affected rows
with memoised recursive evaluation per commit — same results, host-side
recursion instead of dataflow loops (the engine's usual local-recompute
strategy).
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.value import Pointer
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table, TableSpec


class _InputAttribute:
    def __init__(self) -> None:
        self.name: str | None = None


class _OutputAttribute:
    def __init__(self, fn: Callable, internal: bool = False) -> None:
        self.fn = fn
        self.name = fn.__name__
        #: internal computed attributes (@pw.attribute) are usable in other
        #: computations but are NOT output columns (reference semantics)
        self.internal = internal


class _Method:
    def __init__(self, fn: Callable) -> None:
        self.fn = fn
        self.name = fn.__name__


def input_attribute(type: Any = None) -> Any:  # noqa: A002
    return _InputAttribute()


def output_attribute(fn: Callable | None = None, **_kwargs: Any) -> Any:
    if fn is None:
        return lambda f: _OutputAttribute(f)
    return _OutputAttribute(fn)


def method(fn: Callable | None = None, **_kwargs: Any) -> Any:
    if fn is None:
        return lambda f: _Method(f)
    return _Method(fn)


def attribute(fn: Callable | None = None, **_kwargs: Any) -> Any:
    """Internal computed attribute: usable from other computations, not an
    output column (reference row_transformer.py attribute)."""
    if fn is None:
        return lambda f: _OutputAttribute(f, internal=True)
    return _OutputAttribute(fn, internal=True)


input_method = input_attribute


class ClassArg:
    """Base for a transformer's per-table inner class (reference
    row_transformer.py ClassArg)."""

    _output_schema: Any = None

    def __init_subclass__(cls, output: Any = None, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        cls._output_schema = output


def _class_args(cls: type) -> dict[str, type]:
    return {
        name: value
        for name, value in vars(cls).items()
        if isinstance(value, type) and issubclass(value, ClassArg)
    }


class RowReference:
    """``self`` inside an output computation: input attributes, computed
    outputs of any table, ``self.id``, ``self.transformer``, methods."""

    __slots__ = ("_evaluator", "_arg", "_key")

    def __init__(self, evaluator: "_Evaluator", arg: str, key: Pointer):
        self._evaluator = evaluator
        self._arg = arg
        self._key = key

    @property
    def id(self) -> Pointer:
        return self._key

    @property
    def transformer(self) -> "_TransformerNamespace":
        return _TransformerNamespace(self._evaluator)

    def pointer_from(self, *args: Any) -> Pointer:
        from pathway_tpu.engine.value import ref_scalar

        return ref_scalar(*args)

    def __getattr__(self, name: str) -> Any:
        return self._evaluator.value(self._arg, self._key, name)


class _TableNamespace:
    __slots__ = ("_evaluator", "_arg")

    def __init__(self, evaluator: "_Evaluator", arg: str):
        self._evaluator = evaluator
        self._arg = arg

    def __getitem__(self, key: Pointer) -> RowReference:
        return RowReference(self._evaluator, self._arg, key)


class _TransformerNamespace:
    __slots__ = ("_evaluator",)

    def __init__(self, evaluator: "_Evaluator"):
        self._evaluator = evaluator

    def __getattr__(self, name: str) -> _TableNamespace:
        return _TableNamespace(self._evaluator, name)


class _Evaluator:
    """One evaluation epoch: memoised recursive output computation over the
    current input states (the host analog of complex_columns' demand loop)."""

    def __init__(self, spec: "RowTransformer", states: dict[str, dict]):
        self.spec = spec
        self.states = states  # arg name -> {key: row tuple}
        self.memo: dict[tuple[str, Pointer, str], Any] = {}
        self.in_flight: set[tuple[str, Pointer, str]] = set()

    def value(self, arg: str, key: Pointer, attr: str) -> Any:
        cls = self.spec.args[arg]
        member = getattr(cls, attr, None)
        if isinstance(member, _InputAttribute):
            row = self.states[arg].get(key)
            if row is None:
                raise KeyError(f"{arg}[{key!r}] has no row")
            pos = self.spec.input_positions[arg][attr]
            return row[pos]
        if isinstance(member, _OutputAttribute):
            slot = (arg, key, attr)
            if slot in self.memo:
                return self.memo[slot]
            if slot in self.in_flight:
                raise RecursionError(
                    f"cyclic output attribute {arg}.{attr} at {key!r}"
                )
            self.in_flight.add(slot)
            try:
                out = member.fn(RowReference(self, arg, key))
            finally:
                self.in_flight.discard(slot)
            self.memo[slot] = out
            return out
        if isinstance(member, _Method):
            fn = member.fn
            me = RowReference(self, arg, key)
            return lambda *a, **kw: fn(me, *a, **kw)
        raise AttributeError(f"{arg} has no attribute {attr!r}")


class RowTransformer:
    def __init__(self, name: str, args: dict[str, type]):
        self.name = name
        self.args = args
        self.input_positions: dict[str, dict[str, int]] = {}
        self.output_attrs: dict[str, list[_OutputAttribute]] = {}
        for arg_name, cls in args.items():
            inputs = [
                n
                for n, v in vars(cls).items()
                if isinstance(v, _InputAttribute)
            ]
            self.input_positions[arg_name] = {n: i for i, n in enumerate(inputs)}
            self.output_attrs[arg_name] = [
                v
                for v in vars(cls).values()
                if isinstance(v, _OutputAttribute) and not v.internal
            ]

    @classmethod
    def from_class(cls, transformer_cls: type) -> "RowTransformer":
        return cls(transformer_cls.__name__, _class_args(transformer_cls))

    def __call__(self, *tables: Table, **named: Table) -> Any:
        if len(tables) > len(self.args):
            raise TypeError(
                f"transformer {self.name} takes {len(self.args)} table(s), "
                f"got {len(tables)}"
            )
        matched = dict(zip(self.args, tables))
        matched.update(named)
        if set(matched) != set(self.args):
            raise TypeError(
                f"transformer {self.name} expects tables "
                f"{sorted(self.args)}, got {sorted(matched)}"
            )
        # project each input table onto its declared input attributes so
        # positions are stable
        projected = {
            arg: matched[arg].select(
                **{
                    n: matched[arg][n]
                    for n in self.input_positions[arg]
                }
            )
            for arg in self.args
        }
        spec = self
        ordered_args = list(self.args)

        class _Result:
            pass

        result = _Result()
        for arg in self.args:
            outputs = self.output_attrs[arg]
            out_names = [o.name for o in outputs]

            def make_compute(arg_name: str, outs: list[_OutputAttribute]):
                def compute(states_list: list[dict]) -> dict:
                    from pathway_tpu.engine.value import ERROR

                    states = dict(zip(ordered_args, states_list))
                    evaluator = _Evaluator(spec, states)
                    out: dict[Pointer, tuple] = {}
                    for key in states[arg_name]:
                        # per-row isolation: one bad row (dangling pointer,
                        # user exception) poisons its own outputs only
                        # (reference fails per-row with Value::Error too)
                        try:
                            out[key] = tuple(
                                evaluator.value(arg_name, key, o.name)
                                for o in outs
                            )
                        except Exception:  # noqa: BLE001
                            out[key] = (ERROR,) * len(outs)
                    return out

                return compute

            out_table = Table(
                TableSpec(
                    "row_transformer",
                    [projected[a] for a in ordered_args],
                    {
                        "compute": make_compute(arg, outputs),
                        "arity": len(out_names),
                    },
                ),
                out_names,
                {n: dt.ANY for n in out_names},
                universe=matched[arg]._universe,
            )
            setattr(result, arg, out_table)
        return result


def transformer(cls: type) -> RowTransformer:
    """Decorator: ``@pw.transformer`` (reference row_transformer.py)."""
    return RowTransformer.from_class(cls)
