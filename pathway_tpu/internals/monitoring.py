"""Observability: live console dashboard, per-operator probes, Prometheus.

Reference:
- rich live dashboard with connector rows + latency
  (python/pathway/internals/monitoring.py:56-228 StatsMonitor)
- Prometheus/OpenMetrics HTTP endpoint on port 20000 + process_id
  (src/engine/http_server.rs:22-194)
- per-operator probes (graph.rs:500-542, progress_reporter.rs:82)

``pw.run(monitoring_level=pw.MonitoringLevel.ALL, with_http_server=True)``
wires all three; the endpoint stays scrapeable for the lifetime of the run.
"""

from __future__ import annotations

import enum
import http.server
import threading
import time as _time
from typing import Any


class MonitoringLevel(enum.Enum):
    AUTO = "auto"
    NONE = "none"
    IN_OUT = "in_out"  # connector stats only
    ALL = "all"  # + per-operator stats


class ConnectorStats:
    """Input-side counters (reference connectors/monitoring.rs)."""

    __slots__ = ("name", "entries", "batches", "last_entry_at", "finished")

    def __init__(self, name: str) -> None:
        self.name = name
        self.entries = 0
        self.batches = 0
        self.last_entry_at: float | None = None
        self.finished = False

    @property
    def lag_seconds(self) -> float | None:
        if self.finished or self.last_entry_at is None:
            return None
        return max(0.0, _time.monotonic() - self.last_entry_at)


class StatsMonitor:
    """Collects run-wide stats; optionally renders them as a live rich
    table (reference StatsMonitor monitoring.py:165)."""

    def __init__(
        self,
        level: MonitoringLevel = MonitoringLevel.IN_OUT,
        refresh_per_second: float = 4.0,
        console: Any = None,
    ) -> None:
        self.level = level
        #: per-operator probing costs a timing pair per node per batch;
        #: only pay it when something reads the stats (ALL dashboard or a
        #: Prometheus endpoint, which sets this True)
        self.wants_operator_stats = level == MonitoringLevel.ALL
        self.connectors: dict[str, ConnectorStats] = {}
        self.scheduler: Any = None
        #: peer process id -> piggybacked metrics snapshot; the distributed
        #: runner points this at DistributedScheduler.mesh_metrics so the
        #: leader's endpoint exposes the whole mesh with worker labels
        self.mesh_snapshots: dict[int, dict] = {}
        self.started = _time.monotonic()
        self.commits = 0
        self.output_rows = 0
        self._latency_ms: float | None = None
        self._live = None
        self._refresh = refresh_per_second
        self._console = console
        self._last_render = 0.0

    # -- collection ----------------------------------------------------------

    def connector(self, name: str) -> ConnectorStats:
        st = self.connectors.get(name)
        if st is None:
            st = self.connectors[name] = ConnectorStats(name)
        return st

    def on_commit(self, time: int, wall_start: float) -> None:
        self.commits += 1
        self._latency_ms = (_time.monotonic() - wall_start) * 1000.0
        self.maybe_render()

    # -- rendering -----------------------------------------------------------

    def _table(self):
        from rich.table import Table as RichTable

        table = RichTable(title="pathway_tpu progress")
        table.add_column("connector")
        table.add_column("entries", justify="right")
        table.add_column("batches", justify="right")
        table.add_column("lag", justify="right")
        for st in self.connectors.values():
            lag = st.lag_seconds
            table.add_row(
                st.name,
                str(st.entries),
                str(st.batches),
                "done" if st.finished else (f"{lag:.2f}s" if lag else "-"),
            )
        table.add_row(
            "[commits]",
            str(self.commits),
            "-",
            f"{self._latency_ms:.1f}ms" if self._latency_ms else "-",
        )
        if self.level == MonitoringLevel.ALL and self.scheduler is not None:
            for node in self.scheduler.scope.nodes:
                st = self.scheduler.stats.get(node.index)
                if st is None:
                    continue
                table.add_row(
                    f"  op:{node.name}#{node.index}",
                    str(st.insertions - st.deletions),
                    str(st.batches),
                    f"{st.time_spent * 1000:.0f}ms",
                )
        for peer in sorted(self.mesh_snapshots):
            snap = self.mesh_snapshots[peer]

            def total(family: str) -> float:
                fam = snap.get(family) or {}
                return sum(
                    s.get("value", 0.0) for s in fam.get("series", ())
                )

            table.add_row(
                f"[worker {peer}]",
                str(int(total("pathway_operator_rows"))),
                str(int(total("pathway_operator_batches_total"))),
                f"{total('pathway_operator_time_seconds') * 1000:.0f}ms",
            )
        return table

    def start_live(self) -> None:
        from rich.live import Live

        self._live = Live(
            self._table(),
            refresh_per_second=self._refresh,
            console=self._console,
        )
        self._live.start()

    def maybe_render(self) -> None:
        if self._live is None:
            return
        now = _time.monotonic()
        if now - self._last_render >= 1.0 / self._refresh:
            self._live.update(self._table())
            self._last_render = now

    def stop(self) -> None:
        if self._live is not None:
            self._live.update(self._table())
            self._live.stop()
            self._live = None

    # -- prometheus ----------------------------------------------------------

    def prometheus_text(self) -> str:
        """OpenMetrics text format (reference http_server.rs:96-194).

        Three layers share one exposition, each family getting exactly one
        HELP/TYPE block:

        - the legacy unlabelled local series (commits, uptime, connector
          entries, per-operator rows/time) — backwards compatible;
        - this process's full registry snapshot (exchange counters, native
          kernel hits/ns, optimizer stats, ingest->sink latency histogram)
          under ``worker="<process_id>"``;
        - in a mesh run, every follower's piggybacked snapshot under its
          own ``worker`` label — the leader exposes the whole mesh.
        """
        import os

        from pathway_tpu.internals import metrics as _metrics

        legacy: dict = {}
        samples = [
            (
                "pathway_commits_total",
                "counter",
                "commits completed by this run",
                {},
                self.commits,
            ),
            (
                "pathway_uptime_seconds",
                "gauge",
                "seconds since the run started",
                {},
                _time.monotonic() - self.started,
            ),
        ]
        if self._latency_ms is not None:
            samples.append(
                (
                    "pathway_commit_latency_ms",
                    "gauge",
                    "wall latency of the most recent commit",
                    {},
                    self._latency_ms,
                )
            )
        # snapshot: the run thread inserts concurrently with scrapes
        for st in list(self.connectors.values()):
            samples.append(
                (
                    "pathway_input_entries_total",
                    "counter",
                    "entries ingested per connector",
                    {"connector": st.name},
                    st.entries,
                )
            )
        _metrics.merge_samples(legacy, samples)
        if self.scheduler is not None:
            _metrics.merge_samples(
                legacy,
                _metrics.operator_samples(
                    dict(self.scheduler.stats),
                    list(self.scheduler.scope.nodes),
                ),
            )
        worker = os.environ.get("PATHWAY_PROCESS_ID", "0")
        snaps: dict[str, dict] = {"": legacy}
        snaps[worker] = _metrics.full_snapshot(self.scheduler)
        # defensive stale-incarnation filter: recovery/failover prune the
        # scheduler's mesh_metrics dict (this dict aliases it) when a
        # worker dies; a rescale that shrank the mesh relaunches with a
        # narrower width, so snapshots beyond it are a dead incarnation's
        # (a normally-finished peer's closed socket is NOT death — its
        # final snapshot stays visible)
        width = getattr(self.scheduler, "n_processes", None)
        for peer in sorted(self.mesh_snapshots):
            if width is not None and peer >= width:
                continue
            snaps[str(peer)] = self.mesh_snapshots[peer]
        # read-tier replicas piggyback their registries over the
        # snapshot stream; they render under worker="r<id>" (a namespace
        # integer peer ids can never collide with) and disappear from
        # the exposition the moment they disconnect
        try:
            from pathway_tpu import serving as _serving

            stream = _serving.stream_server()
        except Exception:
            stream = None
        if stream is not None:
            for rid, rsnap in sorted(
                stream.replica_metrics_snapshot().items()
            ):
                snaps[f"r{rid}"] = rsnap
        return _metrics.render_snapshots(snaps)


class MonitoringHttpServer:
    """Prometheus endpoint thread on port 20000 + process_id
    (reference http_server.rs:22)."""

    BASE_PORT = 20000

    def __init__(self, monitor: StatsMonitor, port: int | None = None) -> None:
        import os

        monitor.wants_operator_stats = True
        if port is None:
            port = self.BASE_PORT + int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        monitor_ref = monitor

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                if parsed.path in ("/metrics", "/"):
                    body = monitor_ref.prometheus_text().encode()
                    self._reply(200, body, "text/plain; version=0.0.4")
                    return
                if parsed.path == "/timeseries":
                    self._timeseries(parse_qs(parsed.query))
                    return
                if parsed.path == "/requests":
                    self._requests()
                    return
                if parsed.path == "/profile":
                    self._profile()
                    return
                self.send_response(404)
                self.end_headers()

            def _reply(
                self, code: int, body: bytes, ctype: str = "application/json"
            ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _timeseries(self, query: dict) -> None:
                """``/timeseries?family=...&window=...`` — windowed reads
                off the history ring (internals/timeseries.py); extra
                query params filter on series labels.  Without a family,
                an index of recorded families + ring bound stats."""
                import json as _json

                from pathway_tpu.internals import timeseries as _ts

                family = (query.get("family") or [None])[0]
                if not family:
                    payload = {
                        "families": _ts.STORE.families(),
                        "stats": _ts.STORE.stats(),
                        "slos": [s.to_dict() for s in _ts.SENTINEL.specs()],
                    }
                    self._reply(200, _json.dumps(payload).encode())
                    return
                try:
                    window = float((query.get("window") or ["60"])[0])
                except ValueError:
                    self._reply(
                        400, b'{"error": "window must be a number"}'
                    )
                    return
                labels = {
                    k: v[0]
                    for k, v in query.items()
                    if k not in ("family", "window") and v
                }
                result = _ts.STORE.query(family, window, labels)
                self._reply(200, _json.dumps(result).encode())

            def _requests(self) -> None:
                """``/requests`` — the bounded per-request wide-event
                ring (one structured record per served read-tier
                request, newest last)."""
                import json as _json

                from pathway_tpu.internals import metrics as _m

                events = _m.REQUESTS.snapshot()
                payload = {"requests": events, "count": len(events)}
                self._reply(
                    200, _json.dumps(payload, default=repr).encode()
                )

            def _profile(self) -> None:
                """``/profile`` — the merged profile document (this
                worker plus, on the leader, every absorbed peer
                payload); 404 while the sampling profiler is off."""
                import json as _json

                from pathway_tpu.internals import profiling as _prof

                doc = _prof.profile_document(_prof.PROFILER.mesh_payloads())
                if not doc["workers"]:
                    self._reply(
                        404,
                        b'{"error": "profiler not running '
                        b'(set PATHWAY_TPU_PROFILE=1)"}',
                    )
                    return
                self._reply(200, _json.dumps(doc, default=repr).encode())

            def log_message(self, *args: Any) -> None:
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        # join the serve thread so a raising run cannot leak it (nor keep
        # the port bound through a lingering accept loop); idempotent
        thread = getattr(self, "_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
