"""Shared row-snapshot model behind the live table renderers (console
rich view in stdlib/viz and the notebook LiveTable in
internals/interactive): one place owns add/retract semantics and the
max_rows windowing so the two surfaces cannot diverge."""

from __future__ import annotations

from typing import Any, Sequence


class RowSnapshot:
    """Current state of a table as {key: value-tuple}, fed by subscribe
    callbacks."""

    def __init__(self, column_names: Sequence[str], max_rows: int) -> None:
        self.column_names = list(column_names)
        self.max_rows = max_rows
        self.rows: dict[Any, tuple] = {}

    def apply(self, key: Any, row: dict, is_addition: bool) -> None:
        if is_addition:
            self.rows[key] = tuple(row[n] for n in self.column_names)
        else:
            self.rows.pop(key, None)

    def visible(self) -> list[tuple]:
        return list(self.rows.values())[: self.max_rows]

    @property
    def overflow(self) -> int:
        return max(0, len(self.rows) - self.max_rows)
