"""Continuous sampling profiler: mesh-wide phase-tagged flamegraphs.

The metrics plane (internals/metrics.py) answers *how much*, the tracer
(internals/tracing.py) answers *why for one commit*; this module answers
*where host time actually goes, all the time*: a per-worker daemon
sampler walks every thread's stack (``sys._current_frames()``),
aggregates them into folded-stack profiles, and tags each sampled stack
with the scheduler phase it was caught in — ingest / operator /
exchange / device / serving — the same taxonomy the PR-8 critical-path
buckets use, so a profile's phase totals reconcile with
``critical_path()`` shares (:func:`reconcile_with_critical_path`).

Design constraints, matching the rest of the observability plane:

- **default-off costs nothing** — no sampler thread exists unless
  ``PATHWAY_TPU_PROFILE=1`` (:meth:`SampleProfiler.maybe_start` is a
  boolean test when disabled);
- **self-limiting** — each sampler tick measures its own cost and the
  sampling period doubles when the duty cycle approaches the 2%%
  overhead target, decaying back toward the configured base rate
  (``PATHWAY_TPU_PROFILE_HZ``) when comfortably under — the same
  adaptive scheme as ``TraceRecorder._adapt``;
- **mesh-transparent** — a follower's profile payload rides the
  metrics snapshot it already piggybacks on quiescent round frames
  (under the reserved ``"__profile__"`` key, popped by the leader at
  absorption), so the frame arity never changes; the leader merges the
  per-worker payloads and exports one document;
- **epoch-fenced** — payloads carry the mesh recovery epoch; a payload
  stamped by a fenced-out zombie incarnation is dropped at absorption
  (:meth:`SampleProfiler.absorb`), and recovery/failover raise the
  fence alongside ``TRACER.epoch``;
- **bounded** — at most ``PATHWAY_TPU_PROFILE_STACKS`` distinct folded
  stacks are kept per worker (overflow folds into a synthetic
  ``(truncated)`` leaf so weight is never silently lost).

Exports: collapsed-stack text (:func:`folded_text`, flamegraph.pl /
speedscope importable) and speedscope JSON (:func:`speedscope`), both
checked by :func:`validate_profile` — the schema gate in
tools/check.py.  Device-side counters (native + device_ops kernel_ns,
device memory, JAX compile-cache telemetry) are folded into every
payload so host flamegraphs and device counters travel together.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time as _time
from typing import Any, Iterable

from pathway_tpu.internals import metrics as _metrics

__all__ = [
    "PHASES",
    "SampleProfiler",
    "PROFILER",
    "classify_stack",
    "device_counters",
    "profile_document",
    "merge_documents",
    "phase_totals",
    "folded_text",
    "speedscope",
    "validate_profile",
    "reconcile_with_critical_path",
]

#: phase tags, mirroring the PR-8 span taxonomy / critical-path buckets
PHASES = ("ingest", "operator", "exchange", "device", "serving", "other")

#: sampler duty-cycle share that triggers a period doubling — the same
#: target the adaptive trace sampler uses (half the 5% gate, headroom)
OVERHEAD_TARGET = 0.02

#: stack frames kept per sample (leaf-most wins; deeper is truncated)
MAX_DEPTH = 48

#: distinct folded stacks kept per worker before folding into
#: ``(truncated)`` — bounds payload and memory like tracing.MAX_SPANS
MAX_STACKS = 2048

#: profile document schema version (validate_profile checks it)
VERSION = 1

_TRUTHY = ("1", "true", "yes")

# leaf-to-root phase classification rules: (path fragment, function
# prefix or None) -> phase.  Ordered most-specific first; the first rule
# matching the leaf-most frame wins, so an operator process() reached
# through _exchange_rounds still classifies as "operator".
_PHASE_RULES: tuple[tuple[str, str | None, str], ...] = (
    ("serving/server", None, "serving"),
    ("serving/snapshot", None, "serving"),
    ("engine/device_pipeline", None, "device"),
    ("engine/device_ops", None, "device"),
    ("engine/device", None, "device"),
    ("engine/connectors", None, "ingest"),
    ("engine/routing", None, "exchange"),
    ("engine/distributed", "_exchange", "exchange"),
    ("engine/distributed", "_recv", "exchange"),
    ("engine/distributed", "_apply_remote", "exchange"),
    ("engine/distributed", "send", "exchange"),
    ("engine/distributed", "recv", "exchange"),
    ("engine/graph", None, "operator"),
    ("engine/reducers", None, "operator"),
    ("engine/expression", None, "operator"),
    ("engine/batch", None, "operator"),
    ("engine/temporal", None, "operator"),
    ("engine/external_index", None, "operator"),
)


def classify_stack(frames: Iterable[tuple[str, str]]) -> str:
    """Phase tag for one sampled stack: ``frames`` is leaf-first
    ``(filename, funcname)`` pairs; the first rule matching the
    leaf-most frame decides (so work reached *through* the exchange
    loop still attributes to the operator actually running)."""
    for filename, func in frames:
        path = filename.replace("\\", "/")
        for fragment, prefix, phase in _PHASE_RULES:
            if fragment in path and (
                prefix is None or func.startswith(prefix)
            ):
                return phase
    return "other"


def _frame_label(filename: str, func: str) -> str:
    base = os.path.basename(filename)
    if base.endswith(".py"):
        base = base[:-3]
    return f"{base}:{func}"


def device_counters() -> dict:
    """Device-side counters folded into every payload: cumulative
    kernel nanoseconds across both kernel planes (native C++ +
    device_ops JAX, same merge the tracer's critical path uses), device
    memory stats, and JAX compile-cache telemetry.  Every probe is
    best-effort — a missing backend yields an empty section, never an
    error."""
    out: dict = {}
    try:
        from pathway_tpu.internals.tracing import _kernel_ns_snapshot

        kernel_ns = _kernel_ns_snapshot()
        if kernel_ns:
            out["kernel_ns"] = kernel_ns
    except Exception:
        pass
    out.update(_jax_telemetry())
    return out


#: (wall, samples) cache so registry collectors scraping every mesh
#: round never pay a per-round jax device walk — refreshed at most 1/s
_JAX_CACHE_LOCK = threading.Lock()
_JAX_CACHE: list = [0.0, {}]  # guarded-by: _JAX_CACHE_LOCK


def _jax_telemetry(max_age_s: float = 1.0) -> dict:
    with _JAX_CACHE_LOCK:
        stamp, cached = _JAX_CACHE
        if _time.monotonic() - stamp < max_age_s:
            return dict(cached)
    fresh: dict = {}
    try:
        import jax

        memory: dict = {}
        for dev in jax.local_devices():
            stats_fn = getattr(dev, "memory_stats", None)
            if stats_fn is None:
                continue
            try:
                stats = stats_fn() or {}
            except Exception:
                continue
            picked = {
                k: int(stats[k])
                for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                if k in stats
            }
            if picked:
                memory[f"{dev.platform}:{dev.id}"] = picked
        if memory:
            fresh["memory"] = memory
        cache_info: dict = {}
        try:
            cache_info["live_arrays"] = len(jax.live_arrays())
        except Exception:
            pass
        try:
            # jit compile-cache population: every cached lowering in
            # this process (a proxy for compile churn — a growing value
            # under steady state means shape instability)
            from jax._src import pjit as _pjit

            info_fn = getattr(
                getattr(_pjit, "_pjit_lower_cached", None), "cache_info", None
            )
            if info_fn is not None:
                info = info_fn()
                cache_info["compile_cache_size"] = int(info.currsize)
                cache_info["compile_cache_hits"] = int(info.hits)
                cache_info["compile_cache_misses"] = int(info.misses)
        except Exception:
            pass
        if cache_info:
            fresh["jax"] = cache_info
    except Exception:
        pass
    with _JAX_CACHE_LOCK:
        _JAX_CACHE[0] = _time.monotonic()
        _JAX_CACHE[1] = fresh
    return dict(fresh)


def _device_telemetry_collector() -> list[tuple]:
    """Registry pull collector: device memory + JAX compile-cache
    gauges, so the new telemetry families ride the existing mesh
    snapshot piggyback and the leader ``/metrics`` exposition."""
    out: list[tuple] = []
    telemetry = _jax_telemetry()
    for dev, stats in (telemetry.get("memory") or {}).items():
        for stat, value in stats.items():
            out.append(
                (
                    "pathway_device_memory_bytes",
                    "gauge",
                    "device allocator stats (jax memory_stats)",
                    {"device": dev, "stat": stat},
                    value,
                )
            )
    jax_info = telemetry.get("jax") or {}
    if "compile_cache_size" in jax_info:
        out.append(
            (
                "pathway_jax_compile_cache_entries",
                "gauge",
                "cached jit lowerings in this process",
                {},
                jax_info["compile_cache_size"],
            )
        )
    if "compile_cache_misses" in jax_info:
        out.append(
            (
                "pathway_jax_compile_cache_misses",
                "gauge",
                "jit lowering cache misses (compile churn)",
                {},
                jax_info["compile_cache_misses"],
            )
        )
    if "live_arrays" in jax_info:
        out.append(
            (
                "pathway_jax_live_arrays",
                "gauge",
                "live device arrays held by this process",
                {},
                jax_info["live_arrays"],
            )
        )
    return out


_metrics.REGISTRY.register_collector(_device_telemetry_collector)


class SampleProfiler:
    """Process-wide sampling profiler (singleton: :data:`PROFILER`).

    The engine's only contact points are :meth:`maybe_start` (a boolean
    test when profiling is off), :meth:`payload` (called by the mesh
    piggyback when a sampler thread is running), and :meth:`absorb` /
    :meth:`prune` on the leader."""

    def __init__(
        self, enabled: bool | None = None, hz: float | None = None
    ) -> None:
        self._lock = threading.Lock()
        #: (phase, folded-stack) -> [weight_s, count]; the sampler
        #: thread accumulates while payload()/export() snapshot
        self._folded: dict[tuple[str, str], list] = {}  # guarded-by: self._lock
        #: peer id -> latest epoch-current payload (leader side)
        self._peers: dict[int, dict] = {}  # guarded-by: self._lock
        self._thread: threading.Thread | None = None  # guarded-by: self._lock
        self._stop = threading.Event()
        self._started_mono = 0.0
        self._seq = 0  # guarded-by: self._lock
        self._export_seq = 0
        self._samples = 0  # guarded-by: self._lock
        self._dropped = 0  # guarded-by: self._lock
        self._overhead_ema: float | None = None
        #: mesh recovery fence — raised by resync()/failover alongside
        #: TRACER.epoch; payloads stamped below it are zombies
        self.epoch = 0
        self.period = 0.0
        self.configure(enabled=enabled, hz=hz)

    # -- configuration -------------------------------------------------------

    def configure(
        self,
        enabled: bool | None = None,
        hz: float | None = None,
        clear: bool = False,
    ) -> None:
        """(Re)read the knobs; tests and benches call this directly
        instead of mutating the environment."""
        if enabled is None:
            enabled = (
                os.environ.get("PATHWAY_TPU_PROFILE", "").lower() in _TRUTHY
            )
        if hz is None:
            try:
                hz = float(os.environ.get("PATHWAY_TPU_PROFILE_HZ", "50"))
            except ValueError:
                hz = 50.0
        self.enabled = bool(enabled)
        self.base_period = 1.0 / max(1e-3, float(hz))
        self.period = self.base_period
        try:
            self.worker_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        except ValueError:
            self.worker_id = 0
        self._overhead_ema = None
        if clear:
            with self._lock:
                self._folded.clear()
                self._peers.clear()
                self._samples = 0
                self._dropped = 0

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def maybe_start(self) -> bool:
        """Start the daemon sampler thread if profiling is enabled and
        it is not already running.  Returns True when a thread is
        running after the call — the default-off path is one boolean
        test and no thread ever exists."""
        if not self.enabled:
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop.clear()
            self._started_mono = _time.monotonic()
            self._thread = threading.Thread(
                target=self._run, name="pathway-profiler", daemon=True
            )
            self._thread.start()
        return True

    def stop(self) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None and thread.is_alive():
            self._stop.set()
            thread.join(timeout=2.0)

    # -- sampling ------------------------------------------------------------

    def _run(self) -> None:
        tick_hist = _metrics.REGISTRY.histogram(
            "pathway_profile_sample_seconds",
            "wall cost of one profiler sampling tick",
            buckets=(1e-5, 1e-4, 1e-3, 1e-2, 0.1),
        )
        samples_ctr = _metrics.REGISTRY.counter(
            "pathway_profile_samples_total",
            "stack samples aggregated by the profiler",
        )
        rate_gauge = _metrics.REGISTRY.gauge(
            "pathway_profile_rate_hz",
            "current (adaptive) profiler sampling rate",
        )
        own_tid = threading.get_ident()
        last = _time.monotonic()
        while not self._stop.wait(self.period):
            t0 = _time.perf_counter()
            now = _time.monotonic()
            weight = max(0.0, now - last)
            last = now
            try:
                frames = sys._current_frames()
            except Exception:
                continue
            n = self._ingest(frames, own_tid, weight)
            del frames
            cost = _time.perf_counter() - t0
            tick_hist.observe(cost)
            samples_ctr.inc(n)
            self._adapt(cost)
            rate_gauge.set(1.0 / max(self.period, 1e-9))

    def _ingest(self, frames: dict, own_tid: int, weight: float) -> int:
        n = 0
        for tid, top in frames.items():
            if tid == own_tid:
                continue
            stack: list[tuple[str, str]] = []
            frame = top
            depth = 0
            while frame is not None and depth < MAX_DEPTH:
                code = frame.f_code
                stack.append((code.co_filename, code.co_name))
                frame = frame.f_back
                depth += 1
            if not stack:
                continue
            phase = classify_stack(stack)
            folded = ";".join(
                _frame_label(f, fn) for f, fn in reversed(stack)
            )
            key = (phase, folded)
            n += 1
            with self._lock:
                cell = self._folded.get(key)
                if cell is None:
                    if len(self._folded) >= MAX_STACKS:
                        # keep the weight, lose the detail: overflow
                        # folds into a per-phase synthetic leaf
                        self._dropped += 1
                        key = (phase, "(truncated)")
                        cell = self._folded.get(key)
                        if cell is None:
                            cell = self._folded[key] = [0.0, 0]
                    else:
                        cell = self._folded[key] = [0.0, 0]
                cell[0] += weight
                cell[1] += 1
                self._samples += 1
        return n

    def _adapt(self, cost_s: float) -> None:
        """Keep the sampler duty cycle under the overhead target:
        double the period when one tick's cost is too large a share of
        the period, decay back toward the configured base when the cost
        is comfortably below it (mirrors TraceRecorder._adapt)."""
        ratio = cost_s / max(self.period, 1e-9)
        ema = self._overhead_ema
        self._overhead_ema = ratio if ema is None else 0.5 * ema + 0.5 * ratio
        if self._overhead_ema > OVERHEAD_TARGET:
            self.period = min(self.period * 2.0, 2.0)
            self._overhead_ema /= 2.0  # doubling halves the duty cycle
        elif (
            self.period > self.base_period
            and self._overhead_ema < OVERHEAD_TARGET / 4.0
        ):
            self.period = max(self.base_period, self.period / 2.0)
            self._overhead_ema *= 2.0

    # -- payloads ------------------------------------------------------------

    def payload(self) -> dict:
        """This worker's picklable profile payload — what a quiet
        follower embeds (as ``"__profile__"``) in the metrics snapshot
        it already piggybacks to the leader.  Latest-wins per worker:
        ``seq`` increases monotonically."""
        with self._lock:
            self._seq += 1
            samples = [
                [phase, stack, round(cell[0], 6), cell[1]]
                for (phase, stack), cell in self._folded.items()
            ]
            seq = self._seq
            dropped = self._dropped
            total = self._samples
        return {
            "v": VERSION,
            "worker": self.worker_id,
            "pid": os.getpid(),
            "seq": seq,
            "epoch": self.epoch,
            "wall_s": round(
                max(0.0, _time.monotonic() - self._started_mono), 6
            )
            if self._started_mono
            else 0.0,
            "rate_hz": round(1.0 / max(self.period, 1e-9), 3),
            "samples": samples,
            "sample_count": total,
            "dropped_stacks": dropped,
            "device": device_counters(),
        }

    def absorb(self, peer: int, payload: dict) -> bool:
        """Leader-side: keep a peer's piggybacked payload.  A payload
        stamped with an epoch below this process's fence floor is a
        zombie incarnation's — dropped (and counted) instead of merged;
        a current payload raises the floor."""
        try:
            epoch = int(payload.get("epoch", 0))
        except (TypeError, ValueError):
            return False
        if epoch < self.epoch:
            _metrics.REGISTRY.counter(
                "pathway_profile_fenced_total",
                "stale-epoch profile payloads dropped at absorption",
            ).inc(1)
            return False
        self.epoch = max(self.epoch, epoch)
        with self._lock:
            prev = self._peers.get(peer)
            if prev is not None and prev.get("seq", 0) > payload.get("seq", 0):
                return False  # reordered older payload: latest wins
            self._peers[int(peer)] = payload
        return True

    def prune(self, dead: Iterable[int] = (), width: int | None = None) -> None:
        """Drop absorbed payloads of peers that no longer exist —
        mirrors ``DistributedScheduler.prune_mesh_metrics`` so a merged
        export never shows dead workers."""
        gone = set(dead)
        with self._lock:
            for peer in list(self._peers):
                if peer in gone or (width is not None and peer >= width):
                    self._peers.pop(peer, None)

    def mesh_payloads(self) -> dict[int, dict]:
        """Worker-keyed payloads for one merged document: this worker's
        live payload plus every absorbed epoch-current peer payload."""
        with self._lock:
            peers = {
                p: payload
                for p, payload in self._peers.items()
                if int(payload.get("epoch", 0)) >= self.epoch
            }
        out: dict[int, dict] = {}
        if self.running or self._folded:
            out[self.worker_id] = self.payload()
        out.update(peers)
        return out

    # -- export --------------------------------------------------------------

    def export(self, directory: str | None = None) -> str | None:
        """Dump one merged profile document
        (``pathway_profile_p<worker>_pid<pid>_<n>.json``) into
        ``directory`` / ``PATHWAY_TPU_PROFILE_DIR`` / the system temp
        dir.  Returns the path, or None when there is nothing to dump
        or the dump itself fails (export must never mask a run)."""
        doc = profile_document(self.mesh_payloads())
        if not doc["workers"]:
            return None
        try:
            directory = (
                directory
                or os.environ.get("PATHWAY_TPU_PROFILE_DIR")
                or tempfile.gettempdir()
            )
            os.makedirs(directory, exist_ok=True)
            self._export_seq += 1
            path = os.path.join(
                directory,
                f"pathway_profile_p{self.worker_id}"
                f"_pid{os.getpid()}_{self._export_seq:03d}.json",
            )
            with open(path, "w") as fh:
                json.dump(doc, fh, default=repr)
            return path
        except Exception:
            return None


# -- documents ----------------------------------------------------------------


def profile_document(payloads: dict[int, dict]) -> dict:
    """One merged, export-ready document from worker-keyed payloads:
    the shape ``cli profile`` consumes, ``validate_profile`` checks,
    and the speedscope/folded renderers read."""
    workers = {
        str(wid): payload for wid, payload in sorted(payloads.items())
    }
    return {
        "version": VERSION,
        "workers": workers,
        "phases": phase_totals({"workers": workers}),
    }


def merge_documents(docs: Iterable[dict]) -> dict:
    """Merge per-process export files into one document — latest
    ``seq`` wins per worker (each worker re-exports cumulative state,
    so later files supersede earlier ones)."""
    best: dict[str, dict] = {}
    for doc in docs:
        for wid, payload in (doc.get("workers") or {}).items():
            prev = best.get(str(wid))
            if prev is None or payload.get("seq", 0) >= prev.get("seq", 0):
                best[str(wid)] = payload
    return {
        "version": VERSION,
        "workers": best,
        "phases": phase_totals({"workers": best}),
    }


def phase_totals(doc: dict) -> dict[str, float]:
    """Aggregate sampled weight (seconds) per phase across every
    worker of a document — the side that reconciles against the PR-8
    critical-path buckets."""
    totals: dict[str, float] = {}
    for payload in (doc.get("workers") or {}).values():
        for phase, _stack, weight, _count in payload.get("samples", ()):
            totals[phase] = totals.get(phase, 0.0) + float(weight)
    return {k: round(v, 6) for k, v in sorted(totals.items())}


def folded_text(doc: dict) -> str:
    """Collapsed-stack text (flamegraph.pl / speedscope importable):
    one ``worker<i>;<phase>;frame;frame count`` line per folded stack,
    sample counts as weights."""
    lines = []
    for wid in sorted(doc.get("workers") or {}, key=lambda w: str(w)):
        payload = doc["workers"][wid]
        for phase, stack, _weight, count in sorted(
            payload.get("samples", ())
        ):
            lines.append(f"worker{wid};{phase};{stack} {int(count)}")
    return "\n".join(lines) + "\n" if lines else ""


def speedscope(doc: dict) -> dict:
    """Render a document as speedscope JSON
    (https://www.speedscope.app/file-format-schema.json): one
    ``sampled`` profile per worker sharing a frame table; each folded
    stack becomes one sample whose weight is its sampled seconds."""
    frames: list[dict] = []
    index: dict[str, int] = {}

    def frame_of(name: str) -> int:
        i = index.get(name)
        if i is None:
            i = index[name] = len(frames)
            frames.append({"name": name})
        return i

    profiles = []
    for wid in sorted(doc.get("workers") or {}, key=lambda w: str(w)):
        payload = doc["workers"][wid]
        samples: list[list[int]] = []
        weights: list[float] = []
        for phase, stack, weight, _count in payload.get("samples", ()):
            chain = [frame_of(f"[{phase}]")]
            chain.extend(frame_of(part) for part in stack.split(";") if part)
            samples.append(chain)
            weights.append(round(float(weight), 6))
        total = round(sum(weights), 6)
        profiles.append(
            {
                "type": "sampled",
                "name": f"worker {wid}",
                "unit": "seconds",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": weights,
            }
        )
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": profiles,
        "name": "pathway_tpu profile",
        "activeProfileIndex": 0,
        "exporter": "pathway_tpu.internals.profiling",
    }


def validate_profile(doc: Any) -> dict:
    """Strict invariant check over a profile document (the export
    schema gate in tools/check.py): version match, well-formed
    per-worker payloads, known phase tags, non-negative finite
    weights, and a structurally sound speedscope rendering (every
    sample indexes a shared frame, one weight per sample, endValue
    equal to the weight sum).  Returns the document; raises
    ``ValueError`` on any violation."""
    if not isinstance(doc, dict):
        raise ValueError(f"not a profile document: {type(doc).__name__}")
    if doc.get("version") != VERSION:
        raise ValueError(f"unsupported profile version {doc.get('version')!r}")
    workers = doc.get("workers")
    if not isinstance(workers, dict) or not workers:
        raise ValueError("profile document has no workers")
    for wid, payload in workers.items():
        if not isinstance(payload, dict):
            raise ValueError(f"worker {wid}: payload is not an object")
        if int(payload.get("epoch", -1)) < 0:
            raise ValueError(f"worker {wid}: missing/negative epoch")
        samples = payload.get("samples")
        if not isinstance(samples, list):
            raise ValueError(f"worker {wid}: samples is not a list")
        for i, sample in enumerate(samples):
            if not isinstance(sample, (list, tuple)) or len(sample) != 4:
                raise ValueError(
                    f"worker {wid} sample {i}: not a "
                    "[phase, stack, weight, count] quad"
                )
            phase, stack, weight, count = sample
            if phase not in PHASES:
                raise ValueError(
                    f"worker {wid} sample {i}: unknown phase {phase!r}"
                )
            if not isinstance(stack, str) or not stack:
                raise ValueError(f"worker {wid} sample {i}: empty stack")
            w = float(weight)
            if not (w >= 0.0) or w != w or w == float("inf"):
                raise ValueError(
                    f"worker {wid} sample {i}: bad weight {weight!r}"
                )
            if int(count) < 1:
                raise ValueError(
                    f"worker {wid} sample {i}: count {count!r} < 1"
                )
    rendered = speedscope(doc)
    n_frames = len(rendered["shared"]["frames"])
    for prof in rendered["profiles"]:
        if len(prof["samples"]) != len(prof["weights"]):
            raise ValueError(f"{prof['name']}: samples/weights mismatch")
        for chain in prof["samples"]:
            if not chain:
                raise ValueError(f"{prof['name']}: empty sample chain")
            for idx in chain:
                if not (0 <= idx < n_frames):
                    raise ValueError(
                        f"{prof['name']}: frame index {idx} out of range"
                    )
        total = sum(prof["weights"])
        if abs(total - prof["endValue"]) > 1e-3 + 1e-6 * max(1.0, total):
            raise ValueError(
                f"{prof['name']}: endValue {prof['endValue']} != "
                f"weight sum {total}"
            )
    return doc


# -- reconciliation with critical-path buckets --------------------------------

#: profile phase -> critical-path bucket.  Serving is excluded: queries
#: run concurrently with commits and are attributed separately by the
#: tracer (record_query), so they have no commit bucket to land in.
PHASE_TO_BUCKET = {
    "ingest": "queue_wait",
    "exchange": "exchange",
    "device": "device",
    "operator": "host_compute",
    "other": "host_compute",
}


def reconcile_with_critical_path(doc: dict, cp: dict) -> dict:
    """Compare a profile's phase mix against a critical-path breakdown
    (one ``critical_path()`` dict or a ``critical_path_mean`` roll-up):
    both sides normalize to bucket fractions, and ``max_abs_diff`` is
    the largest disagreement — tests assert it stays within sampling
    error on synthetic data and a loose bound live."""
    totals = phase_totals(doc) if "workers" in doc else dict(doc)
    prof_buckets: dict[str, float] = {
        b: 0.0 for b in ("queue_wait", "exchange", "device", "host_compute")
    }
    for phase, weight in totals.items():
        bucket = PHASE_TO_BUCKET.get(phase)
        if bucket is not None:
            prof_buckets[bucket] += float(weight)
    prof_total = sum(prof_buckets.values())
    prof_frac = {
        b: (v / prof_total if prof_total > 0 else 0.0)
        for b, v in prof_buckets.items()
    }
    shares = cp.get("shares")
    if shares is None:
        wall = max(float(cp.get("wall_s", 0.0)), 1e-9)
        shares = {
            "queue_wait": float(cp.get("queue_wait_s", 0.0)) / wall,
            "exchange": float(cp.get("exchange_s", 0.0)) / wall,
            "device": float(cp.get("device_s", 0.0)) / wall,
            "host_compute": float(cp.get("host_compute_s", 0.0)) / wall,
        }
    trace_frac = {b: float(shares.get(b, 0.0)) for b in prof_frac}
    diffs = {b: abs(prof_frac[b] - trace_frac[b]) for b in prof_frac}
    return {
        "profile": {b: round(v, 4) for b, v in prof_frac.items()},
        "trace": {b: round(v, 4) for b, v in trace_frac.items()},
        "max_abs_diff": round(max(diffs.values()) if diffs else 0.0, 4),
    }


#: the process-wide profiler every runtime surface consults
PROFILER = SampleProfiler()
