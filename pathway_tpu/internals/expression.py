"""User-facing column expression DSL.

New implementation of the reference's expression layer
(reference: python/pathway/internals/expression.py, 1,179 LoC): overloaded
operators build an expression tree of :class:`ColumnExpression` nodes that the
graph runner compiles to engine expressions
(:mod:`pathway_tpu.engine.expression`). ``pw.this`` placeholders are resolved
eagerly at the call site (``table.select(x=pw.this.a)``).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Callable, Iterable

from pathway_tpu.internals import dtype as dt

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class ColumnExpression:
    """Base class for all column expressions."""

    _dtype: dt.DType = dt.ANY

    # -- operator overloads -------------------------------------------------

    def _bin(self, op: str, other: Any, reverse: bool = False) -> "BinaryOpExpression":
        other = wrap_expression(other)
        if reverse:
            return BinaryOpExpression(op, other, self)
        return BinaryOpExpression(op, self, other)

    def __add__(self, other: Any) -> "ColumnExpression":
        return self._bin("+", other)

    def __radd__(self, other: Any) -> "ColumnExpression":
        return self._bin("+", other, reverse=True)

    def __sub__(self, other: Any) -> "ColumnExpression":
        return self._bin("-", other)

    def __rsub__(self, other: Any) -> "ColumnExpression":
        return self._bin("-", other, reverse=True)

    def __mul__(self, other: Any) -> "ColumnExpression":
        return self._bin("*", other)

    def __rmul__(self, other: Any) -> "ColumnExpression":
        return self._bin("*", other, reverse=True)

    def __truediv__(self, other: Any) -> "ColumnExpression":
        return self._bin("/", other)

    def __rtruediv__(self, other: Any) -> "ColumnExpression":
        return self._bin("/", other, reverse=True)

    def __floordiv__(self, other: Any) -> "ColumnExpression":
        return self._bin("//", other)

    def __rfloordiv__(self, other: Any) -> "ColumnExpression":
        return self._bin("//", other, reverse=True)

    def __mod__(self, other: Any) -> "ColumnExpression":
        return self._bin("%", other)

    def __rmod__(self, other: Any) -> "ColumnExpression":
        return self._bin("%", other, reverse=True)

    def __pow__(self, other: Any) -> "ColumnExpression":
        return self._bin("**", other)

    def __rpow__(self, other: Any) -> "ColumnExpression":
        return self._bin("**", other, reverse=True)

    def __matmul__(self, other: Any) -> "ColumnExpression":
        return self._bin("@", other)

    def __eq__(self, other: Any) -> "ColumnExpression":  # type: ignore[override]
        return self._bin("==", other)

    def __ne__(self, other: Any) -> "ColumnExpression":  # type: ignore[override]
        return self._bin("!=", other)

    def __lt__(self, other: Any) -> "ColumnExpression":
        return self._bin("<", other)

    def __le__(self, other: Any) -> "ColumnExpression":
        return self._bin("<=", other)

    def __gt__(self, other: Any) -> "ColumnExpression":
        return self._bin(">", other)

    def __ge__(self, other: Any) -> "ColumnExpression":
        return self._bin(">=", other)

    def __and__(self, other: Any) -> "ColumnExpression":
        return BooleanExpression("and", [self, wrap_expression(other)])

    def __rand__(self, other: Any) -> "ColumnExpression":
        return BooleanExpression("and", [wrap_expression(other), self])

    def __or__(self, other: Any) -> "ColumnExpression":
        return BooleanExpression("or", [wrap_expression(other), self]) if not isinstance(other, ColumnExpression) else BooleanExpression("or", [self, wrap_expression(other)])

    def __ror__(self, other: Any) -> "ColumnExpression":
        return BooleanExpression("or", [wrap_expression(other), self])

    def __xor__(self, other: Any) -> "ColumnExpression":
        return self._bin("^", other)

    def __neg__(self) -> "ColumnExpression":
        return UnaryOpExpression("-", self)

    def __invert__(self) -> "ColumnExpression":
        return UnaryOpExpression("not", self)

    def __abs__(self) -> "ColumnExpression":
        return UnaryOpExpression("abs", self)

    def __hash__(self) -> int:
        return id(self)

    def __bool__(self) -> bool:
        raise RuntimeError(
            "a ColumnExpression is not a bool; use &, |, ~ instead of and/or/not"
        )

    # -- methods ------------------------------------------------------------

    def is_none(self) -> "ColumnExpression":
        return IsNoneExpression(self, negated=False)

    def is_not_none(self) -> "ColumnExpression":
        return IsNoneExpression(self, negated=True)

    def __getitem__(self, index: Any) -> "ColumnExpression":
        return GetExpression(self, wrap_expression(index), default=None, checked=False)

    def get(self, index: Any, default: Any = None) -> "ColumnExpression":
        return GetExpression(
            self, wrap_expression(index), default=wrap_expression(default), checked=True
        )

    def as_int(self, unwrap: bool = False) -> "ColumnExpression":
        return ConvertExpression(self, "Int", unwrap)

    def as_float(self, unwrap: bool = False) -> "ColumnExpression":
        return ConvertExpression(self, "Float", unwrap)

    def as_str(self, unwrap: bool = False) -> "ColumnExpression":
        return ConvertExpression(self, "String", unwrap)

    def as_bool(self, unwrap: bool = False) -> "ColumnExpression":
        return ConvertExpression(self, "Bool", unwrap)

    def to_string(self) -> "ColumnExpression":
        return CastExpression(self, dt.STR)

    @property
    def dt(self) -> Any:
        from pathway_tpu.internals.expressions.date_time import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def str(self) -> Any:
        from pathway_tpu.internals.expressions.string import StringNamespace

        return StringNamespace(self)

    @property
    def num(self) -> Any:
        from pathway_tpu.internals.expressions.numerical import NumericalNamespace

        return NumericalNamespace(self)

    def _dependencies(self) -> "Iterable[ColumnReference]":
        """All ColumnReferences in this tree."""
        for child in self._children():
            yield from child._dependencies()

    def _children(self) -> "Iterable[ColumnExpression]":
        return ()


class ColumnConstExpression(ColumnExpression):
    def __init__(self, value: Any) -> None:
        self._value = dt.normalize_value(value)
        self._dtype = dt.dtype_of_value(self._value)

    def __repr__(self) -> str:
        return f"{self._value!r}"


class ColumnReference(ColumnExpression):
    """A reference to a column of a concrete table (``t.colname`` / ``t.id``)."""

    def __init__(self, table: "Table", name: str) -> None:
        self._table = table
        self._name = name
        if name == "id":
            self._dtype = dt.Pointer()
        else:
            self._dtype = table._dtypes.get(name, dt.ANY)

    @property
    def table(self) -> "Table":
        return self._table

    @property
    def name(self) -> str:
        return self._name

    def _dependencies(self) -> Iterable["ColumnReference"]:
        yield self

    def __repr__(self) -> str:
        return f"<{self._table._name}>.{self._name}"


class BinaryOpExpression(ColumnExpression):
    _COMPARISONS = {"==", "!=", "<", "<=", ">", ">="}

    def __init__(self, op: str, left: ColumnExpression, right: ColumnExpression) -> None:
        self._op = op
        self._left = left
        self._right = right
        if op in self._COMPARISONS:
            self._dtype = dt.BOOL
        elif op == "/":
            self._dtype = dt.FLOAT if left._dtype.strip_optional() in (dt.INT, dt.FLOAT, dt.BOOL) else dt.ANY
        else:
            self._dtype = dt.lca(left._dtype, right._dtype)

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._left, self._right)

    def __repr__(self) -> str:
        return f"({self._left!r} {self._op} {self._right!r})"


class UnaryOpExpression(ColumnExpression):
    def __init__(self, op: str, arg: ColumnExpression) -> None:
        self._op = op
        self._arg = arg
        self._dtype = dt.BOOL if op == "not" else arg._dtype

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._arg,)


class BooleanExpression(ColumnExpression):
    _dtype = dt.BOOL

    def __init__(self, op: str, args: list[ColumnExpression]) -> None:
        # flatten nested chains of the same op
        flat: list[ColumnExpression] = []
        for a in args:
            if isinstance(a, BooleanExpression) and a._op == op:
                flat.extend(a._args)
            else:
                flat.append(a)
        self._op = op
        self._args = flat

    def _children(self) -> Iterable[ColumnExpression]:
        return tuple(self._args)


class IsNoneExpression(ColumnExpression):
    _dtype = dt.BOOL

    def __init__(self, arg: ColumnExpression, negated: bool) -> None:
        self._arg = arg
        self._negated = negated

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._arg,)


class IfElseExpression(ColumnExpression):
    def __init__(
        self,
        cond: ColumnExpression,
        then: ColumnExpression,
        otherwise: ColumnExpression,
    ) -> None:
        self._cond = cond
        self._then = then
        self._otherwise = otherwise
        self._dtype = dt.lca(then._dtype, otherwise._dtype)

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._cond, self._then, self._otherwise)


class CoalesceExpression(ColumnExpression):
    def __init__(self, args: list[ColumnExpression]) -> None:
        self._args = args
        dtype = args[0]._dtype
        for a in args[1:]:
            dtype = dt.lca(dtype, a._dtype)
        self._dtype = dtype.strip_optional() if len(args) > 1 and args[-1]._dtype == dt.NONE is False else dtype

    def _children(self) -> Iterable[ColumnExpression]:
        return tuple(self._args)


class RequireExpression(ColumnExpression):
    def __init__(self, value: ColumnExpression, deps: list[ColumnExpression]) -> None:
        self._value = value
        self._deps = deps
        self._dtype = dt.Optional_(value._dtype.strip_optional())

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._value, *self._deps)


class ApplyExpression(ColumnExpression):
    def __init__(
        self,
        fn: Callable[..., Any],
        return_type: Any,
        args: tuple,
        kwargs: dict,
        *,
        propagate_none: bool = False,
        deterministic: bool = True,
    ) -> None:
        self._fn = fn
        self._args = [wrap_expression(a) for a in args]
        self._kwargs = {k: wrap_expression(v) for k, v in kwargs.items()}
        self._dtype = dt.wrap(return_type) if return_type is not None else dt.ANY
        self._propagate_none = propagate_none
        self._deterministic = deterministic

    def _children(self) -> Iterable[ColumnExpression]:
        return (*self._args, *self._kwargs.values())


class BatchApplyExpression(ColumnExpression):
    """A UDF call executed by the engine in commit-batches (BatchApplyNode).

    ``rows_fn`` is ``UDF.execute_rows``: list of arg tuples in, list of
    (ok, value) out. Must appear as a top-level select expression.
    """

    def __init__(
        self,
        rows_fn: Callable[[list], list],
        return_type: Any,
        args: tuple,
        kwargs: dict,
        *,
        propagate_none: bool = False,
        deterministic: bool = False,
        name: str = "udf",
    ) -> None:
        self._rows_fn = rows_fn
        self._args = [wrap_expression(a) for a in args]
        self._kwargs = {k: wrap_expression(v) for k, v in kwargs.items()}
        self._dtype = dt.wrap(return_type) if return_type is not None else dt.ANY
        self._propagate_none = propagate_none
        self._deterministic = deterministic
        self._name = name

    def _children(self) -> Iterable[ColumnExpression]:
        return (*self._args, *self._kwargs.values())


class CastExpression(ColumnExpression):
    def __init__(self, arg: ColumnExpression, target: Any) -> None:
        self._arg = arg
        self._dtype = dt.wrap(target)

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._arg,)


class DeclareTypeExpression(ColumnExpression):
    def __init__(self, arg: ColumnExpression, target: Any) -> None:
        self._arg = arg
        self._dtype = dt.wrap(target)

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._arg,)


class ConvertExpression(ColumnExpression):
    def __init__(self, arg: ColumnExpression, target: str, unwrap: bool = False) -> None:
        self._arg = arg
        self._target = target
        self._unwrap = unwrap
        mapping = {"Int": dt.INT, "Float": dt.FLOAT, "Bool": dt.BOOL, "String": dt.STR}
        base = mapping.get(target, dt.ANY)
        self._dtype = base if unwrap else dt.Optional_(base)

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._arg,)


class UnwrapExpression(ColumnExpression):
    def __init__(self, arg: ColumnExpression) -> None:
        self._arg = arg
        self._dtype = arg._dtype.strip_optional()

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._arg,)


class FillErrorExpression(ColumnExpression):
    def __init__(self, arg: ColumnExpression, fallback: ColumnExpression) -> None:
        self._arg = arg
        self._fallback = fallback
        self._dtype = dt.lca(arg._dtype, fallback._dtype)

    def _children(self) -> Iterable[ColumnExpression]:
        return (self._arg, self._fallback)


class MakeTupleExpression(ColumnExpression):
    def __init__(self, args: list[ColumnExpression]) -> None:
        self._args = args
        self._dtype = dt.Tuple(*[a._dtype for a in args])

    def _children(self) -> Iterable[ColumnExpression]:
        return tuple(self._args)


class GetExpression(ColumnExpression):
    def __init__(
        self,
        arg: ColumnExpression,
        index: ColumnExpression,
        default: ColumnExpression | None,
        checked: bool,
    ) -> None:
        self._arg = arg
        self._index = index
        self._default = default
        self._checked = checked
        base = arg._dtype.strip_optional()
        if base == dt.JSON:
            self._dtype = dt.Optional_(dt.JSON) if checked else dt.JSON
        elif isinstance(base, dt.List):
            self._dtype = base.wrapped
        else:
            self._dtype = dt.ANY

    def _children(self) -> Iterable[ColumnExpression]:
        children = [self._arg, self._index]
        if self._default is not None:
            children.append(self._default)
        return tuple(children)


class PointerExpression(ColumnExpression):
    """``table.pointer_from(*exprs)``."""

    def __init__(
        self,
        args: list[ColumnExpression],
        instance: ColumnExpression | None = None,
        target: Any = None,
    ) -> None:
        self._args = args
        self._instance = instance
        self._dtype = dt.Pointer(target)

    def _children(self) -> Iterable[ColumnExpression]:
        if self._instance is not None:
            return (*self._args, self._instance)
        return tuple(self._args)


class ReducerExpression(ColumnExpression):
    """A reducer call inside ``.reduce(...)`` (pw.reducers.*)."""

    def __init__(self, kind: Any, args: list[ColumnExpression], **options: Any) -> None:
        from pathway_tpu.engine.reducers import ReducerKind

        self._kind: ReducerKind = kind
        self._args = args
        self._options = options
        if kind in (ReducerKind.COUNT, ReducerKind.COUNT_DISTINCT):
            self._dtype = dt.INT
        elif kind in (ReducerKind.ARG_MIN, ReducerKind.ARG_MAX):
            self._dtype = dt.Pointer()
        elif args:
            self._dtype = args[0]._dtype
        else:
            self._dtype = dt.ANY

    def _children(self) -> Iterable[ColumnExpression]:
        return tuple(self._args)


def wrap_expression(value: Any) -> ColumnExpression:
    if isinstance(value, ColumnExpression):
        return value
    return ColumnConstExpression(value)


# -- module-level constructors (exported as pw.*) ---------------------------


def if_else(cond: Any, then: Any, otherwise: Any) -> ColumnExpression:
    return IfElseExpression(
        wrap_expression(cond), wrap_expression(then), wrap_expression(otherwise)
    )


def coalesce(*args: Any) -> ColumnExpression:
    return CoalesceExpression([wrap_expression(a) for a in args])


def require(value: Any, *deps: Any) -> ColumnExpression:
    return RequireExpression(wrap_expression(value), [wrap_expression(d) for d in deps])


def apply(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> ColumnExpression:
    return ApplyExpression(fn, None, args, kwargs)


def apply_with_type(
    fn: Callable[..., Any], ret_type: Any, *args: Any, **kwargs: Any
) -> ColumnExpression:
    return ApplyExpression(fn, ret_type, args, kwargs)


def apply_async(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> ColumnExpression:
    """Concurrent per-row apply (reference: pw.apply_async) — lowered to the
    engine batch node with an async executor."""
    import inspect

    from pathway_tpu.internals.udfs import UDF
    from pathway_tpu.internals.udfs.executors import AsyncExecutor

    if not inspect.iscoroutinefunction(fn):
        sync_fn = fn

        async def async_fn(*a: Any, **kw: Any) -> Any:
            return sync_fn(*a, **kw)

        fn = async_fn
    return UDF(fn, executor=AsyncExecutor())(*args, **kwargs)


def cast(target: Any, expr: Any) -> ColumnExpression:
    return CastExpression(wrap_expression(expr), target)


def declare_type(target: Any, expr: Any) -> ColumnExpression:
    return DeclareTypeExpression(wrap_expression(expr), target)


def unwrap(expr: Any) -> ColumnExpression:
    return UnwrapExpression(wrap_expression(expr))


def fill_error(expr: Any, fallback: Any) -> ColumnExpression:
    return FillErrorExpression(wrap_expression(expr), wrap_expression(fallback))


def make_tuple(*args: Any) -> ColumnExpression:
    return MakeTupleExpression([wrap_expression(a) for a in args])
