"""`pw.Table` — the declarative table API.

New implementation of the reference Table
(reference: python/pathway/internals/table.py, 2,675 LoC — select :382,
filter :490, groupby :942, reduce :1025, join :1164, concat :1439,
update_rows/cells :1524+, with_id_from :2089, flatten, sort, ix). Tables are
lazy: each holds a :class:`TableSpec` describing the operator that produces
it; :mod:`pathway_tpu.internals.runner` lowers reachable specs onto the
engine scope at run time.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping, Sequence

from pathway_tpu.engine.reducers import ReducerKind
from pathway_tpu.engine.value import Pointer, ref_scalar
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.desugaring import resolve_this, substitute
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    PointerExpression,
    ReducerExpression,
    wrap_expression,
)
from pathway_tpu.internals.trace import current_trace
from pathway_tpu.internals.universe import Universe, solver

_table_counter = itertools.count()


@dataclass
class TableSpec:
    """How to produce this table: operator kind + inputs + parameters."""

    kind: str
    inputs: list["Table"] = field(default_factory=list)
    params: dict[str, Any] = field(default_factory=dict)


class JoinMode:
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


class Table:
    def __init__(
        self,
        spec: TableSpec,
        column_names: Sequence[str],
        dtypes: Mapping[str, dt.DType],
        universe: Universe | None = None,
        name: str | None = None,
    ) -> None:
        self._spec = spec
        self._column_names = list(column_names)
        self._dtypes = dict(dtypes)
        self._universe = universe if universe is not None else Universe()
        self._id = next(_table_counter)
        self._name = name or f"table_{self._id}"
        self._trace = current_trace()
        from pathway_tpu.internals import errors as _errors

        self._error_log_id = _errors.current_log_id()

    # -- introspection ------------------------------------------------------

    @property
    def schema(self) -> schema_mod.SchemaMetaclass:
        return schema_mod.schema_from_dict(
            {n: self._dtypes[n] for n in self._column_names}, name=f"{self._name}_schema"
        )

    def column_names(self) -> list[str]:
        return list(self._column_names)

    def typehints(self) -> dict[str, Any]:
        return {n: self._dtypes[n].typehint for n in self._column_names}

    def keys(self) -> list[str]:
        return list(self._column_names)

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}: {self._dtypes[n]!r}" for n in self._column_names)
        return f"<pw.Table {self._name}({cols})>"

    # -- column access ------------------------------------------------------

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(self, "id")

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self.__dict__.get("_column_names", ()):
            raise AttributeError(
                f"table {self._name!r} has no column {name!r}; "
                f"columns: {self._column_names}"
            )
        return ColumnReference(self, name)

    def __getitem__(self, arg: Any) -> Any:
        if isinstance(arg, str):
            if arg == "id":
                return self.id
            return ColumnReference(self, arg)
        if isinstance(arg, (list, tuple)):
            return self.select(*[self[a] for a in arg])
        if isinstance(arg, ColumnReference):
            return ColumnReference(self, arg.name)
        raise TypeError(f"cannot index table with {arg!r}")

    def __iter__(self) -> Iterable[ColumnReference]:
        return iter(ColumnReference(self, n) for n in self._column_names)

    def _ref(self, name: str) -> ColumnReference:
        return ColumnReference(self, name)

    def pointer_from(
        self, *args: Any, instance: Any = None, optional: bool = False
    ) -> PointerExpression:
        resolved = [resolve_this(a, self) for a in args]
        inst = resolve_this(instance, self) if instance is not None else None
        return PointerExpression(resolved, instance=inst)

    # -- helpers ------------------------------------------------------------

    def _resolve_kwargs(
        self, args: tuple, kwargs: dict
    ) -> dict[str, ColumnExpression]:
        from pathway_tpu.internals.thisclass import ThisStar

        out: dict[str, ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, str):
                out[arg] = ColumnReference(self, arg)
                continue
            if isinstance(arg, ThisStar):
                from pathway_tpu.internals.thisclass import this

                if arg._owner is not this:
                    raise ValueError(
                        f"{arg!r} cannot be used here; use *pw.this"
                    )
                # ``*pw.this``: every column of the bound table
                for n in self._column_names:
                    out[n] = ColumnReference(self, n)
                continue
            resolved = resolve_this(arg, self)
            if isinstance(resolved, ColumnReference):
                if resolved.name == "id":
                    raise ValueError("cannot select id as a positional column")
                out[resolved.name] = resolved
            else:
                raise ValueError(
                    f"positional select arguments must be column references, got {arg!r}"
                )
        for name, value in kwargs.items():
            out[name] = resolve_this(value, self)
        return out

    def _derived(
        self,
        spec: TableSpec,
        columns: Mapping[str, dt.DType],
        universe: Universe | None = None,
        name_hint: str | None = None,
    ) -> "Table":
        return Table(
            spec,
            list(columns.keys()),
            columns,
            universe=universe,
            name=name_hint,
        )

    # -- core ops -----------------------------------------------------------

    def select(self, *args: Any, **kwargs: Any) -> "Table":
        exprs = self._resolve_kwargs(args, kwargs)
        return self._derived(
            TableSpec("select", [self], {"exprs": exprs}),
            {n: e._dtype for n, e in exprs.items()},
            universe=self._universe,
        )

    def with_columns(self, *args: Any, **kwargs: Any) -> "Table":
        exprs = self._resolve_kwargs(args, kwargs)
        combined: dict[str, ColumnExpression] = {
            n: ColumnReference(self, n) for n in self._column_names
        }
        combined.update(exprs)
        return self._derived(
            TableSpec("select", [self], {"exprs": combined}),
            {n: e._dtype for n, e in combined.items()},
            universe=self._universe,
        )

    def without(self, *columns: Any) -> "Table":
        names = set()
        for col in columns:
            if isinstance(col, str):
                names.add(col)
            else:
                resolved = resolve_this(col, self)
                assert isinstance(resolved, ColumnReference)
                names.add(resolved.name)
        keep = {
            n: ColumnReference(self, n) for n in self._column_names if n not in names
        }
        return self._derived(
            TableSpec("select", [self], {"exprs": keep}),
            {n: e._dtype for n, e in keep.items()},
            universe=self._universe,
        )

    def rename(self, names_mapping: Mapping[Any, str] | None = None, **kwargs: str) -> "Table":
        def colname(ref: Any) -> str:
            if isinstance(ref, ColumnReference):
                return ref.name
            # pw.this.x sentinel (ThisColumnReference) carries _name
            this_name = getattr(ref, "_name", None)
            return this_name if this_name is not None else str(ref)

        mapping: dict[str, str] = {}
        if names_mapping:
            for old, new in names_mapping.items():
                mapping[colname(old)] = new
        # kwargs follow reference convention: new_name=old_column
        for new, old in kwargs.items():
            mapping[colname(old)] = new
        exprs = {
            mapping.get(n, n): ColumnReference(self, n) for n in self._column_names
        }
        return self._derived(
            TableSpec("select", [self], {"exprs": exprs}),
            {name: e._dtype for name, e in exprs.items()},
            universe=self._universe,
        )

    rename_columns = rename

    def rename_by_dict(self, names_mapping: Mapping[Any, str]) -> "Table":
        return self.rename(names_mapping)

    def with_prefix(self, prefix: str) -> "Table":
        return self.rename({n: prefix + n for n in self._column_names})

    def with_suffix(self, suffix: str) -> "Table":
        return self.rename({n: n + suffix for n in self._column_names})

    def filter(self, filter_expression: Any) -> "Table":
        cond = resolve_this(filter_expression, self)
        return self._derived(
            TableSpec("filter", [self], {"condition": cond}),
            {n: self._dtypes[n] for n in self._column_names},
            universe=self._universe.subset(),
        )

    def split(self, expression: Any) -> tuple["Table", "Table"]:
        cond = resolve_this(expression, self)
        pos = self.filter(cond)
        neg = self.filter(expr_mod.UnaryOpExpression("not", cond))
        return pos, neg

    def copy(self) -> "Table":
        return self.select(
            **{n: ColumnReference(self, n) for n in self._column_names}
        )

    def cast_to_types(self, **kwargs: Any) -> "Table":
        exprs: dict[str, ColumnExpression] = {}
        for n in self._column_names:
            if n in kwargs:
                exprs[n] = expr_mod.CastExpression(ColumnReference(self, n), kwargs[n])
            else:
                exprs[n] = ColumnReference(self, n)
        return self._derived(
            TableSpec("select", [self], {"exprs": exprs}),
            {n: e._dtype for n, e in exprs.items()},
            universe=self._universe,
        )

    def update_types(self, **kwargs: Any) -> "Table":
        exprs: dict[str, ColumnExpression] = {}
        for n in self._column_names:
            if n in kwargs:
                exprs[n] = expr_mod.DeclareTypeExpression(
                    ColumnReference(self, n), kwargs[n]
                )
            else:
                exprs[n] = ColumnReference(self, n)
        return self._derived(
            TableSpec("select", [self], {"exprs": exprs}),
            {n: e._dtype for n, e in exprs.items()},
            universe=self._universe,
        )

    # -- groupby / reduce ---------------------------------------------------

    def groupby(
        self,
        *args: Any,
        id: Any = None,  # noqa: A002 — mirrors reference signature
        instance: Any = None,
        **kwargs: Any,
    ) -> "GroupedTable":
        from pathway_tpu.internals.groupbys import GroupedTable

        by: list[ColumnReference] = []
        if id is not None:
            resolved = resolve_this(id, self)
            assert isinstance(resolved, ColumnReference)
            return GroupedTable(self, [resolved], set_id=True)
        for arg in args:
            resolved = resolve_this(arg, self)
            if not isinstance(resolved, ColumnReference):
                raise ValueError("groupby arguments must be column references")
            by.append(resolved)
        if instance is not None:
            inst = resolve_this(instance, self)
            assert isinstance(inst, ColumnReference)
            by.append(inst)
        return GroupedTable(self, by, instance_last=instance is not None)

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        from pathway_tpu.internals.groupbys import GroupedTable

        return GroupedTable(self, []).reduce(*args, **kwargs)

    def deduplicate(
        self,
        *,
        value: Any,
        instance: Any = None,
        acceptor: Callable[[Any, Any], bool],
        name: str | None = None,
    ) -> "Table":
        value_ref = resolve_this(value, self)
        instance_refs: list[ColumnExpression] = []
        if instance is not None:
            instance_refs.append(resolve_this(instance, self))
        return self._derived(
            TableSpec(
                "deduplicate",
                [self],
                {"value": value_ref, "instance": instance_refs, "acceptor": acceptor,
                 "name": name},
            ),
            {n: self._dtypes[n] for n in self._column_names},
        )

    # -- joins --------------------------------------------------------------

    def join(
        self, other: "Table", *on: Any, id: Any = None, how: str = JoinMode.INNER  # noqa: A002
    ) -> "JoinResult":
        from pathway_tpu.internals.joins import JoinResult

        return JoinResult(self, other, on, how=how, id=id)

    def join_inner(self, other: "Table", *on: Any, id: Any = None) -> "JoinResult":  # noqa: A002
        return self.join(other, *on, id=id, how=JoinMode.INNER)

    def join_left(self, other: "Table", *on: Any, id: Any = None) -> "JoinResult":  # noqa: A002
        return self.join(other, *on, id=id, how=JoinMode.LEFT)

    def join_right(self, other: "Table", *on: Any, id: Any = None) -> "JoinResult":  # noqa: A002
        return self.join(other, *on, id=id, how=JoinMode.RIGHT)

    def join_outer(self, other: "Table", *on: Any, id: Any = None) -> "JoinResult":  # noqa: A002
        return self.join(other, *on, id=id, how=JoinMode.OUTER)

    # -- set ops ------------------------------------------------------------

    def concat(self, *others: "Table") -> "Table":
        tables = [self, *others]
        dtypes: dict[str, dt.DType] = {}
        for n in self._column_names:
            dtype = self._dtypes[n]
            for o in others:
                if n not in o._dtypes:
                    raise ValueError(f"column {n!r} missing in concat operand")
                dtype = dt.lca(dtype, o._dtypes[n])
            dtypes[n] = dtype
        return self._derived(
            TableSpec("concat", tables, {}),
            dtypes,
            # concat's key set IS the union of the operands': the SAT
            # solver then proves each operand ⊆ result (cross-table
            # selects against an operand keep working)
            universe=solver.get_union(*(t._universe for t in tables)),
        )

    def concat_reindex(self, *others: "Table") -> "Table":
        reindexed = [
            t.with_id_from(t.id, expr_mod.ColumnConstExpression(i))
            for i, t in enumerate([self, *others])
        ]
        return reindexed[0].concat(*reindexed[1:])

    def update_rows(self, other: "Table") -> "Table":
        if set(other._column_names) != set(self._column_names):
            raise ValueError("update_rows requires matching columns")
        dtypes = {
            n: dt.lca(self._dtypes[n], other._dtypes[n]) for n in self._column_names
        }
        return self._derived(TableSpec("update_rows", [self, other], {}), dtypes)

    def update_cells(self, other: "Table") -> "Table":
        extra = set(other._column_names) - set(self._column_names)
        if extra:
            raise ValueError(f"update_cells: unknown columns {extra}")
        dtypes = {
            n: dt.lca(self._dtypes[n], other._dtypes[n]) if n in other._dtypes else self._dtypes[n]
            for n in self._column_names
        }
        return self._derived(
            TableSpec("update_cells", [self, other], {}),
            dtypes,
            universe=self._universe,
        )

    def __lshift__(self, other: "Table") -> "Table":
        return self.update_cells(other)

    def intersect(self, *tables: "Table") -> "Table":
        return self._derived(
            TableSpec("intersect", [self, *tables], {}),
            {n: self._dtypes[n] for n in self._column_names},
            universe=solver.get_intersection(
                self._universe, *(t._universe for t in tables)
            ),
        )

    def difference(self, other: "Table") -> "Table":
        return self._derived(
            TableSpec("subtract", [self, other], {}),
            {n: self._dtypes[n] for n in self._column_names},
            universe=solver.get_difference(self._universe, other._universe),
        )

    def restrict(self, other: "Table") -> "Table":
        return self._derived(
            TableSpec("restrict", [self, other], {}),
            {n: self._dtypes[n] for n in self._column_names},
            universe=other._universe,
        )

    def promise_universes_are_equal(self, other: "Table") -> "Table":
        """Assert both tables share a key set (reference
        Table.promise_universes_are_equal)."""
        solver.register_equal(self._universe, other._universe)
        return self

    def promise_universe_is_subset_of(self, other: "Table") -> "Table":
        solver.register_subset(self._universe, other._universe)
        return self

    def promise_universe_is_equal_to(self, other: "Table") -> "Table":
        return self.promise_universes_are_equal(other)

    def with_universe_of(self, other: "Table") -> "Table":
        solver.register_equal(self._universe, other._universe)
        return self._derived(
            TableSpec("override_universe", [self, other], {}),
            {n: self._dtypes[n] for n in self._column_names},
            universe=other._universe,
        )

    def _external_index_as_of_now(
        self,
        query_table: "Table",
        index_column: ColumnExpression,
        query_column: ColumnExpression,
        index_factory: Any,
        number_of_matches: Any = 3,
    ) -> "Table":
        """As-of-now external-index lookup (reference: Table._external_index_
        _as_of_now internals/table.py:584 → use_external_index_as_of_now).

        ``self`` is the indexed data table. Returns a table keyed by query id
        with columns ``_pw_index_reply_ids`` / ``_pw_index_reply_scores``.
        ``number_of_matches`` is an int or a ColumnExpression on the query
        table (per-query limit).
        """
        index_expr = resolve_this(index_column, self)
        query_expr = resolve_this(query_column, query_table)
        limit_expr: ColumnExpression | None = None
        k = 3
        if isinstance(number_of_matches, ColumnExpression):
            limit_expr = resolve_this(number_of_matches, query_table)
            k = 16
        else:
            k = int(number_of_matches)
        return self._derived(
            TableSpec(
                "external_index",
                [self, query_table],
                {
                    "index_expr": index_expr,
                    "query_expr": query_expr,
                    "limit_expr": limit_expr,
                    "k": k,
                    "factory": index_factory,
                },
            ),
            {
                "_pw_index_reply_ids": dt.ANY,
                "_pw_index_reply_scores": dt.ANY,
            },
            universe=query_table._universe.subset(),
        )

    # -- temporal -----------------------------------------------------------

    def windowby(
        self,
        time_expr: Any,
        *,
        window: Any,
        instance: Any = None,
        behavior: Any = None,
    ) -> Any:
        from pathway_tpu.stdlib.temporal import windowby as _windowby

        return _windowby(
            self, time_expr, window=window, instance=instance, behavior=behavior
        )

    def interval_join(
        self,
        other: "Table",
        self_time: Any,
        other_time: Any,
        interval: Any,
        *on: Any,
        how: str = JoinMode.INNER,
    ) -> Any:
        from pathway_tpu.stdlib.temporal import interval_join as _ij

        return _ij(self, other, self_time, other_time, interval, *on, how=how)

    def asof_join(
        self,
        other: "Table",
        self_time: Any,
        other_time: Any,
        *on: Any,
        how: str = JoinMode.INNER,
        direction: str = "backward",
    ) -> Any:
        from pathway_tpu.stdlib.temporal import asof_join as _aj

        return _aj(
            self, other, self_time, other_time, *on, how=how, direction=direction
        )

    def asof_now_join(
        self, other: "Table", *on: Any, how: str = JoinMode.INNER
    ) -> Any:
        from pathway_tpu.stdlib.temporal import asof_now_join as _anj

        return _anj(self, other, *on, how=how)

    # -- re-keying ----------------------------------------------------------

    def with_id_from(self, *args: Any, instance: Any = None) -> "Table":
        resolved = [resolve_this(a, self) for a in args]
        inst = resolve_this(instance, self) if instance is not None else None
        pointer = PointerExpression(resolved, instance=inst)
        return self._derived(
            TableSpec("reindex", [self], {"new_id": pointer}),
            {n: self._dtypes[n] for n in self._column_names},
        )

    def with_id(self, new_id: Any) -> "Table":
        pointer = resolve_this(new_id, self)
        return self._derived(
            TableSpec("reindex", [self], {"new_id": pointer}),
            {n: self._dtypes[n] for n in self._column_names},
        )

    # -- pointer lookup -----------------------------------------------------

    def ix(
        self, expression: Any, *, optional: bool = False, context: Any = None
    ) -> "Table":
        expression = wrap_expression(expression)
        if context is not None:
            keys_table = context
        else:
            deps = list(expression._dependencies())
            if not deps:
                raise ValueError(
                    "ix expression must reference a column (or pass "
                    "context=)"
                )
            keys_table = deps[0].table
        keys = keys_table.select(_pw_ix_key=expression)
        return self._derived(
            TableSpec("ix", [keys, self], {"optional": optional}),
            {n: self._dtypes[n] for n in self._column_names},
            universe=keys_table._universe,
        )

    def ix_ref(
        self,
        *args: Any,
        optional: bool = False,
        instance: Any = None,
        context: "Table | None" = None,
        allow_misses: bool = False,
    ) -> "Table":
        """Reindex this table by primary-key expressions: desugars to
        ``self.ix(keys_table.pointer_from(*args))``, inferring the keys
        table from the expressions' column references (reference
        Table.ix_ref, python/pathway/internals/table.py:2400-2455).
        ``context`` pins the keys table when the arguments are literals
        only; ``pw.this.ix_ref(...)`` inside select supplies it
        automatically."""
        from pathway_tpu.internals.expression import wrap_expression

        keys_table = context
        if keys_table is None:
            exprs = [wrap_expression(a) for a in args]
            if instance is not None:
                exprs.append(wrap_expression(instance))
            deps = [d for e in exprs for d in e._dependencies()]
            if not deps:
                raise ValueError(
                    "ix_ref with literal-only keys cannot infer the keys "
                    "table; pass context= or use pw.this.ix_ref(...) "
                    "inside select"
                )
            keys_table = deps[0].table
        # plain strings are literal KEY VALUES here (ix_ref("Alice")),
        # unlike select's string-as-column-name convention
        resolved = [
            wrap_expression(a)
            if isinstance(a, str)
            else resolve_this(a, keys_table)
            for a in args
        ]
        inst = (
            resolve_this(instance, keys_table)
            if instance is not None
            else None
        )
        pointer = PointerExpression(resolved, instance=inst)
        return self.ix(
            pointer, optional=optional or allow_misses, context=keys_table
        )

    # -- misc ops -----------------------------------------------------------

    def flatten(
        self, to_flatten: Any, *, origin_id: str | None = None, **kwargs: Any
    ) -> "Table":
        """Explode a sequence column; ``origin_id`` names an extra column
        holding the source row's id (reference flatten origin_id)."""
        ref = resolve_this(to_flatten, self)
        assert isinstance(ref, ColumnReference)
        inner = self._dtypes.get(ref.name, dt.ANY)
        base = inner.strip_optional()
        if isinstance(base, dt.List):
            flat_dtype: dt.DType = base.wrapped
        elif isinstance(base, dt.Tuple) and base.args:
            flat_dtype = base.args[0]
        elif base == dt.STR:
            flat_dtype = dt.STR
        else:
            flat_dtype = dt.ANY
        dtypes = {
            n: (flat_dtype if n == ref.name else self._dtypes[n])
            for n in self._column_names
        }
        if origin_id is not None:
            dtypes[origin_id] = dt.Pointer()
        return self._derived(
            TableSpec(
                "flatten", [self], {"column": ref.name, "origin_id": origin_id}
            ),
            dtypes,
        )

    def _gradual_broadcast(
        self,
        threshold_table: "Table",
        lower_column: Any,
        value_column: Any,
        upper_column: Any,
    ) -> "Table":
        """Attach ``apx_value`` moving between lower and upper per row as
        the broadcast value advances (reference table.py:631 over
        operators/gradual_broadcast.rs; used by louvain)."""
        lower = resolve_this(lower_column, threshold_table)
        value = resolve_this(value_column, threshold_table)
        upper = resolve_this(upper_column, threshold_table)
        triplet = threshold_table.select(
            _pw_lower=lower, _pw_value=value, _pw_upper=upper
        )
        return self._derived(
            TableSpec("gradual_broadcast", [self, triplet], {}),
            {
                **{n: self._dtypes[n] for n in self._column_names},
                "apx_value": dt.ANY,
            },
            universe=self._universe,
        )

    def window_join(
        self,
        other: "Table",
        self_time: Any,
        other_time: Any,
        window: Any,
        *on: Any,
        how: str = "inner",
        **kwargs: Any,
    ) -> Any:
        """Reference Table.window_join (_window_join.py:156)."""
        from pathway_tpu.stdlib.temporal import window_join as _wj

        return _wj(
            self, other, self_time, other_time, window, *on, how=how, **kwargs
        )

    @property
    def slice(self) -> "Table":
        """Reference Table.slice — a column-access view; our tables already
        support ``t[...]`` slicing directly."""
        return self

    def having(self, *indexers: Any) -> "Table":
        """Restrict to rows whose id appears among each indexer expression's
        pointer values (reference Table.having, used with ix_ref)."""
        out = self
        for ix in indexers:
            resolved = resolve_this(ix, self)
            keys = resolved.table.select(_pw_p=resolved)
            keys = keys.with_id(keys["_pw_p"])
            out = out.intersect(keys)
        return out

    def sort(self, key: Any, instance: Any = None) -> "Table":
        key_expr = resolve_this(key, self)
        inst_expr = resolve_this(instance, self) if instance is not None else None
        return self._derived(
            TableSpec("sort", [self], {"key": key_expr, "instance": inst_expr}),
            {"prev": dt.Optional_(dt.Pointer()), "next": dt.Optional_(dt.Pointer())},
            universe=self._universe,
        )

    def remove_errors(self) -> "Table":
        return self._derived(
            TableSpec("remove_errors", [self], {}),
            {n: self._dtypes[n] for n in self._column_names},
            universe=self._universe.subset(),
        )

    def await_futures(self) -> "Table":
        # Future columns resolve at commit boundaries in the async executor;
        # at the API level this is a dtype-level unwrap.
        exprs = {
            n: (
                expr_mod.DeclareTypeExpression(
                    ColumnReference(self, n), self._dtypes[n].wrapped
                )
                if isinstance(self._dtypes[n], dt.Future)
                else ColumnReference(self, n)
            )
            for n in self._column_names
        }
        return self._derived(
            TableSpec("select", [self], {"exprs": exprs}),
            {n: e._dtype for n, e in exprs.items()},
            universe=self._universe,
        )

    # -- static constructors ------------------------------------------------

    @staticmethod
    def empty(**kwargs: Any) -> "Table":
        dtypes = {n: dt.wrap(t) for n, t in kwargs.items()}
        return Table(
            TableSpec("static", [], {"rows": []}),
            list(dtypes.keys()),
            dtypes,
        )

    @staticmethod
    def from_rows(
        rows: Sequence[tuple],
        schema: schema_mod.SchemaMetaclass,
        keys: Sequence[Pointer] | None = None,
    ) -> "Table":
        names = schema.column_names()
        dtypes = schema.dtypes()
        pk = schema.primary_key_columns()
        out_rows: list[tuple[Pointer, tuple]] = []
        for i, row in enumerate(rows):
            normalized = tuple(
                dt.normalize_value(v, dtypes[n]) for v, n in zip(row, names)
            )
            if keys is not None:
                key = keys[i]
            elif pk:
                key_vals = tuple(normalized[names.index(p)] for p in pk)
                key = ref_scalar(*key_vals)
            else:
                key = ref_scalar(i)
            out_rows.append((key, normalized))
        return Table(
            TableSpec("static", [], {"rows": out_rows}),
            names,
            dtypes,
        )
