"""Cross-graph table exchange: ``pw.export_table`` / ``pw.import_table``.

Reference: src/engine/dataflow/export.rs + ExportedTable (graph.rs:609),
surfaced in Python through ImportDataSource/ExportDataSink
(graph_runner/operator_handler.py:151-206). A producing graph exports a
table as a live handle (snapshot + update callbacks); a separate consuming
graph imports the handle as an input source — the snapshot replays first,
then updates stream through while both graphs run.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.connectors import INSERT, DELETE, ParsedEvent, Parser, QueueReader
from pathway_tpu.engine.graph import ExportedTable
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table


def export_table(table: Table) -> ExportedTable:
    """Register ``table`` for export; the handle fills when its graph runs
    (reference export_table python_api.rs:3205)."""
    exported = ExportedTable(len(table.column_names()))
    exported.column_names = table.column_names()  # type: ignore[attr-defined]

    def attach(scope: Any, node: Any):
        scope.export_table(node, handle=exported)
        return None

    G.add_sink(table, attach)
    return exported


class _ExportedParser(Parser):
    def parse(self, payload: Any) -> list[ParsedEvent]:
        kind, key, row = payload
        return [ParsedEvent(kind, row, key=(key,))]


def import_table(exported: ExportedTable) -> Table:
    """Bring an exported handle into THIS graph as an input source
    (reference import_table python_api.rs:3217)."""
    names = getattr(
        exported, "column_names", None
    ) or [f"c{i}" for i in range(exported.arity)]
    schema = schema_mod.schema_from_types(**{n: Any for n in names})

    def make_reader():
        # fresh reader per graph build (a shared one would be drained by
        # whichever build ran first)
        reader = QueueReader()

        def on_update(key, row, time, diff):
            if key is None:  # producer finished
                reader.close()
                exported.unsubscribe(on_update)  # no leak across builds
                return
            reader.push(
                (INSERT if diff > 0 else DELETE, key, row), source_id="import"
            )

        # atomic subscribe+snapshot: updates committed after the snapshot
        # arrive via the callback, none are lost or duplicated
        snapshot, finished = exported.subscribe_with_snapshot(on_update)
        for key, row in snapshot.items():
            reader.push((INSERT, key, row), source_id="import")
        if finished:
            reader.close()
            exported.unsubscribe(on_update)
        return reader

    return input_table(
        schema,
        make_reader,
        lambda _names: _ExportedParser(names),
        source_name="import",
    )
