"""Sampled per-commit distributed tracing: spans, critical path, export.

The metrics plane (internals/metrics.py) answers *how much*; this module
answers *why*: for a sampled delta-batch commit it records a tree of
spans — connector ingest wait, every operator ``process()`` (including
FusedChainNode sweeps), exchange encode/apply, mesh recv waits, sink
emit — across every worker of a TCP mesh, and assembles them on the
leader into one trace with per-worker tracks.

Design constraints, matching the metrics plane:

- **lock-cheap, allocation-free when idle** — tracing is off unless
  ``PATHWAY_TPU_TRACE=1``; when on, only every Nth commit is sampled
  (``PATHWAY_TPU_TRACE_SAMPLE``, default 16) and the hot-path guard for
  an unsampled commit is one attribute load (:func:`current` returning
  ``None``).  Assembled traces live in a bounded ring like the
  :class:`~pathway_tpu.internals.metrics.FlightRecorder`.
- **mesh-transparent** — the leader decides sampling at commit start
  and piggybacks the trace context on the round frames it already
  sends (the 8th element, next to the metrics snapshot slot); quiet
  followers piggyback their span lists back on frames bound for the
  leader.  No extra frames, no extra round trips.
- **epoch-fenced** — the context tuple carries the mesh recovery
  epoch; a context stamped by a fenced-out zombie leader is ignored
  (:meth:`TraceRecorder.adopt`), and recovery/failover paths drop the
  in-flight context after the flight-recorder dump (which references
  its trace id — see ``metrics.set_trace_id_provider``).
- **self-limiting** — the recorder measures its own per-sampled-commit
  bookkeeping cost and doubles the sampling interval when the
  amortized overhead approaches the 5%% observability gate, decaying
  back toward the configured base when it is comfortably under
  (:meth:`TraceRecorder._adapt`).

Span timestamps are microseconds since the epoch, derived from one
per-process wall anchor plus ``perf_counter`` deltas — monotonic per
worker track by construction, which is exactly the invariant the
Chrome trace-event export (:func:`chrome_trace`) needs and
:func:`validate_chrome_trace` enforces.

Critical-path attribution (:func:`critical_path`) buckets each traced
commit's wall time into ``queue_wait`` (connector ingest wait plus any
``cat="wait"`` spans), ``exchange`` (PWCF encode + decode/apply, mesh
recv blocking during commit exchange rounds, and the collective
exchange's pack/unpack marshalling), ``device`` (native ``kernel_ns``
deltas), and ``host_compute`` (the residual) — the four sum to the
commit wall exactly, so downstream consumers (bench JSON, the
async-device-pipeline work) can trust the decomposition.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time as _time
from collections import deque
from typing import Any

from pathway_tpu.internals import metrics as _metrics

__all__ = [
    "TraceContext",
    "RequestTrace",
    "TraceRecorder",
    "TRACER",
    "TRACE_HEADER",
    "SPANS_HEADER",
    "current",
    "parse_trace_header",
    "encode_spans",
    "decode_spans",
    "critical_path",
    "chrome_trace",
    "validate_chrome_trace",
]

#: spans kept per commit per worker before dropping (bounds frame size)
MAX_SPANS = 2048

#: amortized (overhead / interval) share of commit wall that triggers an
#: interval doubling — half the 5% observability gate, for headroom
OVERHEAD_TARGET = 0.02

#: request header carrying the read-tier trace context across HTTP hops:
#: ``"<trace_id>;<parent_span_id>;<0|1 sampling bit>"``
TRACE_HEADER = "X-Pathway-Trace"

#: response header piggybacking a remote hop's span list back to its
#: caller (compact JSON; dropped rather than split when oversized)
SPANS_HEADER = "X-Pathway-Trace-Spans"

#: span-piggyback budget — one HTTP header line; an oversized payload is
#: dropped (the caller keeps its own leg span, so the trace stays valid)
MAX_SPANS_HEADER_BYTES = 16384


def parse_trace_header(value: str | None) -> tuple[str, str, bool] | None:
    """Decode an ``X-Pathway-Trace`` value into
    ``(trace_id, parent_span_id, sampled)``; ``None`` when absent or
    garbled — a skewed peer must never break the request path."""
    if not value:
        return None
    parts = str(value).split(";")
    if len(parts) != 3 or not parts[0] or not parts[1]:
        return None
    return parts[0], parts[1], parts[2] == "1"


def encode_spans(spans: list[dict]) -> str | None:
    """Compact JSON for the response-header span piggyback, or ``None``
    when there is nothing to send or the payload would blow the header
    budget."""
    if not spans:
        return None
    try:
        payload = json.dumps(spans, separators=(",", ":"), default=repr)
    except (TypeError, ValueError):
        return None
    if len(payload) > MAX_SPANS_HEADER_BYTES:
        return None
    return payload


def decode_spans(value: str | None) -> list[dict]:
    """Parse a piggybacked span list defensively: malformed input yields
    ``[]``, and only dict entries with a string name and numeric ``ts``
    survive (the shape :func:`chrome_trace` depends on)."""
    if not value:
        return []
    try:
        spans = json.loads(value)
    except (TypeError, ValueError):
        return []
    if not isinstance(spans, list):
        return []
    out: list[dict] = []
    for s in spans:
        if (
            isinstance(s, dict)
            and isinstance(s.get("name"), str)
            and isinstance(s.get("ts"), (int, float))
        ):
            out.append(s)
    return out

# one per-process clock anchor: wall time is captured once, every span
# timestamp is the anchor plus a perf_counter/monotonic delta — so per-
# worker timestamps are strictly monotonic even if the system clock steps
_ANCHOR_WALL = _time.time()
_ANCHOR_PERF = _time.perf_counter()
_ANCHOR_MONO = _time.monotonic()


def perf_to_wall(t: float) -> float:
    return _ANCHOR_WALL + (t - _ANCHOR_PERF)


def mono_to_wall(t: float) -> float:
    return _ANCHOR_WALL + (t - _ANCHOR_MONO)


def _us(wall: float) -> int:
    return int(wall * 1e6)


def _kernel_ns_snapshot() -> dict | None:
    """Per-kernel cumulative ns across every kernel plane: the C++ host
    kernels (native.kernel_ns) and the JAX device operator kernels
    (engine.device_ops), the latter prefixed ``device_ops.`` — span
    deltas over this snapshot feed the critical-path ``kernel_ns``
    bucket, so device-resident operators show up as device time."""
    out: dict | None = None
    try:
        from pathway_tpu import native

        kernel_ns = getattr(native, "kernel_ns", None)
        if kernel_ns is not None:
            out = dict(kernel_ns())
    except Exception:
        out = None
    try:
        from pathway_tpu.engine import device_ops

        dns = device_ops.kernel_ns()
        if dns:
            out = dict(out) if out else {}
            for name, ns in dns.items():
                out["device_ops." + name] = ns
    except Exception:
        pass
    return out


class TraceContext:
    """The in-flight sampled commit: identity plus the span accumulator.

    Created by the leader (:meth:`TraceRecorder.begin`) or adopted from
    the leader's round-frame context tuple on a follower
    (:meth:`TraceRecorder.adopt`, ``remote=True``)."""

    __slots__ = (
        "trace_id",
        "commit_time",
        "origin_wall",
        "epoch",
        "pid",
        "remote",
        "begin_wall",
        "spans",
        "dropped",
        "sink_rows",
        "native_ns0",
        "overhead_s",
    )

    def __init__(
        self,
        trace_id: str,
        commit_time: int,
        origin_wall: float,
        epoch: int,
        pid: int,
        remote: bool = False,
    ) -> None:
        self.trace_id = trace_id
        self.commit_time = int(commit_time)
        self.origin_wall = float(origin_wall)
        self.epoch = int(epoch)
        self.pid = int(pid)
        self.remote = remote
        self.begin_wall = perf_to_wall(_time.perf_counter())
        self.spans: list[dict] = []
        self.dropped = 0
        self.sink_rows = 0
        self.native_ns0: dict | None = None
        self.overhead_s = 0.0

    def span(
        self, name: str, cat: str, t0: float, t1: float, **args: Any
    ) -> None:
        """Record one completed span from perf_counter stamps ``t0``/``t1``
        (taken by the instrumented call site around the work)."""
        if len(self.spans) >= MAX_SPANS:
            self.dropped += 1
            return
        ev: dict = {
            "name": name,
            "cat": cat,
            "ts": _us(perf_to_wall(t0)),
            "dur": max(0, int((t1 - t0) * 1e6)),
            "pid": self.pid,
        }
        if args:
            ev["args"] = args
        self.spans.append(ev)

    def note_sink(self, rows: int) -> None:
        self.sink_rows += int(rows)


class RequestTrace:
    """One in-flight read-tier request: identity plus span accumulator.

    Unlike :class:`TraceContext` (single-slot, pump-thread-private), a
    request trace is born on an HTTP handler thread and accumulates
    spans from the federation scatter pool concurrently, so its span
    list and span-id counter are lock-guarded.  ``track`` is the OS
    pid: every process a request crosses renders on its own Chrome
    track, so per-track timestamps stay monotonic even though each
    process stamps spans off its own clock anchor."""

    __slots__ = (
        "trace_id",
        "parent_span",
        "endpoint",
        "remote",
        "track",
        "origin_wall",
        "begin_wall",
        "spans",
        "dropped",
        "overhead_s",
        "_lock",
        "_sid",
    )

    def __init__(
        self,
        trace_id: str,
        endpoint: str,
        parent_span: str | None = None,
        remote: bool = False,
    ) -> None:
        self.trace_id = trace_id
        self.parent_span = parent_span
        self.endpoint = endpoint
        self.remote = remote
        self.track = os.getpid()
        self.begin_wall = perf_to_wall(_time.perf_counter())
        self.origin_wall = self.begin_wall
        self._lock = threading.Lock()
        self.spans: list[dict] = []  # guarded-by: self._lock
        self._sid = 0  # guarded-by: self._lock
        self.dropped = 0  # guarded-by: self._lock
        self.overhead_s = 0.0

    def alloc_sid(self) -> str:
        """Reserve a span id BEFORE the RPC it will name, so the
        outbound trace header can carry it as the callee's parent."""
        with self._lock:
            self._sid += 1
            return f"{self.track:x}.{self._sid}"

    def span(
        self,
        name: str,
        cat: str,
        t0: float,
        t1: float,
        sid: str | None = None,
        **args: Any,
    ) -> None:
        """Record one completed span from perf_counter stamps; safe to
        call from any thread holding a reference to this context."""
        ev: dict = {
            "name": name,
            "cat": cat,
            "ts": _us(perf_to_wall(t0)),
            "dur": max(0, int((t1 - t0) * 1e6)),
            "pid": self.track,
        }
        if sid is not None:
            args["sid"] = sid
        if self.parent_span is not None:
            args.setdefault("parent", self.parent_span)
        if args:
            ev["args"] = args
        with self._lock:
            if len(self.spans) >= MAX_SPANS:
                self.dropped += 1
                return
            self.spans.append(ev)

    def add_remote_spans(
        self, spans: list[dict], parent_sid: str
    ) -> None:
        """Merge a callee's piggybacked spans.  Each span keeps the
        ``pid`` track its own process stamped; spans that did not carry
        a parent (older peers) are adopted under this leg's sid."""
        with self._lock:
            for s in spans:
                if len(self.spans) >= MAX_SPANS:
                    self.dropped += 1
                    continue
                args = dict(s.get("args") or {})
                args.setdefault("parent", parent_sid)
                self.spans.append(dict(s, args=args))

    def header(self, parent_sid: str) -> str:
        """The outbound ``X-Pathway-Trace`` value for one hop — only
        sampled requests ever propagate, so the bit is always 1."""
        return f"{self.trace_id};{parent_sid};1"

    def take_spans(self) -> list[dict]:
        with self._lock:
            return list(self.spans)


class TraceRecorder:
    """Process-wide sampling trace recorder (singleton: :data:`TRACER`).

    The engine's only hot-path contact points are :func:`current` (one
    attribute read, ``None`` when the running commit is unsampled) and
    :meth:`begin` (a counter bump + modulo when tracing is enabled, a
    single boolean test when it is not)."""

    def __init__(
        self,
        enabled: bool | None = None,
        sample: int | None = None,
        maxlen: int | None = None,
    ) -> None:
        if maxlen is None:
            try:
                maxlen = int(os.environ.get("PATHWAY_TPU_TRACE_RING", "64"))
            except ValueError:
                maxlen = 64
        self._lock = threading.Lock()
        #: the ring and the query counter are the cross-thread surface:
        #: serving workers record queries and exporters snapshot the ring
        #: while the pump appends.  _ctx/_count/_export_seq/_overhead_ema
        #: are pump-thread-private and deliberately unguarded.
        self._traces: deque = deque(maxlen=max(1, maxlen))  # guarded-by: self._lock
        self._ctx: TraceContext | None = None
        self._count = 0
        self._query_count = 0  # guarded-by: self._lock
        self._request_count = 0  # guarded-by: self._lock
        #: per-HTTP-handler-thread request context slot; thread-local so
        #: concurrent requests on the serving pool never share a trace
        self._req_local = threading.local()
        self._export_seq = 0
        self._overhead_ema: float | None = None
        self._req_overhead_ema: float | None = None
        self.epoch = 0
        self.configure(enabled=enabled, sample=sample)

    # -- configuration -------------------------------------------------------

    def configure(
        self,
        enabled: bool | None = None,
        sample: int | None = None,
        clear: bool = False,
        request_enabled: bool | None = None,
        request_sample: int | None = None,
    ) -> None:
        """(Re)read the knobs; tests and benches call this directly
        instead of mutating the environment."""
        if enabled is None:
            enabled = os.environ.get("PATHWAY_TPU_TRACE", "").lower() in (
                "1",
                "true",
                "yes",
            )
        if sample is None:
            try:
                sample = int(
                    os.environ.get("PATHWAY_TPU_TRACE_SAMPLE", "16")
                )
            except ValueError:
                sample = 16
        if request_enabled is None:
            request_enabled = os.environ.get(
                "PATHWAY_TPU_REQUEST_TRACE", ""
            ).lower() in ("1", "true", "yes")
        if request_sample is None:
            try:
                request_sample = int(
                    os.environ.get(
                        "PATHWAY_TPU_REQUEST_TRACE_SAMPLE", "16"
                    )
                )
            except ValueError:
                request_sample = 16
        self.enabled = bool(enabled)
        self.base_interval = max(1, int(sample))
        self.interval = self.base_interval
        self.request_enabled = bool(request_enabled)
        self.request_base_interval = max(1, int(request_sample))
        self.request_interval = self.request_base_interval
        try:
            self.worker_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        except ValueError:
            self.worker_id = 0
        self._ctx = None
        self._req_local = threading.local()
        self._overhead_ema = None
        self._req_overhead_ema = None
        if clear:
            with self._lock:
                self._traces.clear()
                self._query_count = 0
                self._request_count = 0
            self._count = 0
            self._export_seq = 0

    # -- commit lifecycle ----------------------------------------------------

    def begin(
        self,
        commit_time: int,
        origin_mono: float | None = None,
        sources: list[str] | None = None,
    ) -> TraceContext | None:
        """Leader/local-side sampling decision at commit start.

        ``origin_mono`` is the connector ingest stamp
        (``InputDriver.first_pending_wall``, a ``time.monotonic`` value)
        popped by the runner — the trace's time zero.  Returns the
        active context when this commit is sampled, else ``None``."""
        if not self.enabled:
            return None
        self._count += 1
        if (self._count - 1) % self.interval:
            return None
        t0 = _time.perf_counter()
        now_wall = perf_to_wall(t0)
        origin_wall = (
            mono_to_wall(origin_mono) if origin_mono is not None else now_wall
        )
        origin_wall = min(origin_wall, now_wall)
        ctx = TraceContext(
            trace_id=(
                f"t{self.worker_id:02d}-{os.getpid():x}-{self._count:06x}"
            ),
            commit_time=commit_time,
            origin_wall=origin_wall,
            epoch=self.epoch,
            pid=self.worker_id,
        )
        ctx.native_ns0 = _kernel_ns_snapshot()
        if now_wall - origin_wall > 1e-6:
            # the connector-ingest wait, synthesized as the first span —
            # rendered on the track, but bucketed via the begin/origin
            # delta (not the "wait" category) to avoid double counting
            ev: dict = {
                "name": "ingest-wait",
                "cat": "queue",
                "ts": _us(origin_wall),
                "dur": max(0, int((now_wall - origin_wall) * 1e6)),
                "pid": self.worker_id,
            }
            if sources:
                ev["args"] = {"sources": sources}
            ctx.spans.append(ev)
        self._ctx = ctx
        ctx.overhead_s += _time.perf_counter() - t0
        return ctx

    def ctx_frame(self) -> tuple | None:
        """The context tuple the leader piggybacks on round frames —
        ``("ctx", trace_id, commit_time, origin_wall, epoch)``."""
        ctx = self._ctx
        if ctx is None or ctx.remote:
            return None
        return ("ctx", ctx.trace_id, ctx.commit_time, ctx.origin_wall,
                ctx.epoch)

    def adopt(self, payload: tuple) -> TraceContext | None:
        """Follower-side: activate the leader's trace context from a
        round-frame tuple.  A context stamped with an epoch below this
        process's fence floor is a zombie ex-leader's — ignored."""
        epoch = int(payload[4])
        if epoch < self.epoch:
            return None
        self.epoch = epoch
        ctx = self._ctx
        if ctx is not None and ctx.trace_id == payload[1]:
            return ctx
        ctx = TraceContext(
            trace_id=str(payload[1]),
            commit_time=int(payload[2]),
            origin_wall=float(payload[3]),
            epoch=epoch,
            pid=self.worker_id,
            remote=True,
        )
        self._ctx = ctx
        return ctx

    def take_spans(self) -> list[dict]:
        """Copy of the active context's spans so far — what a quiet
        follower piggybacks to the leader (the leader keeps the latest
        copy per peer, so the final quiescent round wins)."""
        ctx = self._ctx
        return list(ctx.spans) if ctx is not None else []

    def drop(self) -> None:
        """Abandon the in-flight context (followers at commit end;
        every process on recovery/failover — call AFTER the flight
        dump so forensics still reference the trace id)."""
        self._ctx = None

    def end(
        self, commit_time: int, peer_spans: dict | None = None
    ) -> dict | None:
        """Leader/local-side commit end: assemble the trace (local +
        piggybacked peer spans), attribute the critical path, ring it,
        and feed the adaptive sampler."""
        ctx = self._ctx
        self._ctx = None
        if ctx is None or ctx.remote:
            return None
        t_end = _time.perf_counter()
        end_wall = perf_to_wall(t_end)
        kernels: dict[str, int] = {}
        device_s = 0.0
        if ctx.native_ns0 is not None:
            now_ns = _kernel_ns_snapshot() or {}
            for k, ns in now_ns.items():
                d = int(ns) - int(ctx.native_ns0.get(k, 0))
                if d > 0:
                    kernels[k] = d
            device_s = sum(kernels.values()) / 1e9
        workers: dict[int, list] = {}
        if peer_spans:
            for peer, spans in sorted(peer_spans.items()):
                if spans:
                    workers[int(peer)] = list(spans)
        trace: dict = {
            "trace_id": ctx.trace_id,
            "commit_time": int(commit_time),
            "epoch": ctx.epoch,
            "worker": ctx.pid,
            "origin_wall": ctx.origin_wall,
            "begin_wall": ctx.begin_wall,
            "end_wall": end_wall,
            "spans": ctx.spans,
            "workers": workers,
            "sink_rows": ctx.sink_rows,
            "dropped_spans": ctx.dropped,
            "device_kernel_ns": kernels,
            "device_s": device_s,
        }
        trace["critical_path"] = critical_path(trace)
        with self._lock:
            self._traces.append(trace)
        overhead = ctx.overhead_s + (_time.perf_counter() - t_end)
        self._adapt(overhead, max(end_wall - ctx.begin_wall, 0.0))
        return trace

    def _adapt(self, overhead_s: float, commit_wall_s: float) -> None:
        """Keep the amortized tracing cost under the overhead target by
        doubling the sampling interval when a sampled commit's
        bookkeeping is too large a share of the (interval-amortized)
        commit wall, decaying back toward the configured base when the
        cost is comfortably below it."""
        amortized = overhead_s / max(1, self.interval)
        ratio = amortized / max(commit_wall_s, 1e-6)
        ema = self._overhead_ema
        self._overhead_ema = ratio if ema is None else 0.5 * ema + 0.5 * ratio
        if self._overhead_ema > OVERHEAD_TARGET:
            self.interval = min(self.interval * 2, 4096)
            self._overhead_ema /= 2.0  # doubling halves the amortized cost
        elif (
            self.interval > self.base_interval
            and self._overhead_ema < OVERHEAD_TARGET / 4.0
        ):
            self.interval = max(self.base_interval, self.interval // 2)
            self._overhead_ema *= 2.0

    # -- serving-plane query traces ------------------------------------------

    def record_query(
        self,
        name: str,
        t0: float,
        t1: float,
        commit_time: int = 0,
        **args: Any,
    ) -> dict | None:
        """Record one served query (or query micro-batch) as a standalone
        ``kind="serving"`` trace in the same ring.

        Queries run on serving threads CONCURRENTLY with commits, so
        they never touch the single-slot commit context (``_ctx``) —
        each call assembles its own one-span trace.  Sampling uses its
        own counter at the same interval, so query volume cannot starve
        commit traces (and vice versa).  ``commit_time`` is the served
        snapshot's commit time: ``cli trace`` correlates query spans
        with the commit that published their view."""
        if not self.enabled:
            return None
        with self._lock:
            self._query_count += 1
            if (self._query_count - 1) % self.interval:
                return None
        origin_wall = perf_to_wall(t0)
        end_wall = perf_to_wall(t1)
        span: dict = {
            "name": name,
            "cat": "serving",
            "ts": _us(origin_wall),
            "dur": max(0, int((t1 - t0) * 1e6)),
            "pid": self.worker_id,
        }
        if args:
            span["args"] = dict(args)
        trace: dict = {
            "kind": "serving",
            "trace_id": (
                f"q{self.worker_id:02d}-{os.getpid():x}"
                f"-{self._query_count:06x}"
            ),
            "commit_time": int(commit_time),
            "epoch": self.epoch,
            "worker": self.worker_id,
            "origin_wall": origin_wall,
            "begin_wall": origin_wall,
            "end_wall": end_wall,
            "spans": [span],
            "workers": {},
            "sink_rows": 0,
            "dropped_spans": 0,
            "device_kernel_ns": {},
            "device_s": 0.0,
        }
        trace["critical_path"] = critical_path(trace)
        with self._lock:
            self._traces.append(trace)
        return trace

    # -- read-tier request traces --------------------------------------------

    def begin_request(self, endpoint: str) -> RequestTrace | None:
        """Root-side sampling decision for one read-tier request.

        The first request is always sampled (a single smoke query must
        yield a trace), then every ``request_interval``-th; the counter
        is lock-guarded because requests land on concurrent handler
        threads.  The context lives in a thread-local slot for the
        handler's duration."""
        if not self.request_enabled:
            return None
        t0 = _time.perf_counter()
        with self._lock:
            self._request_count += 1
            count = self._request_count
        if (count - 1) % self.request_interval:
            return None
        ctx = RequestTrace(
            trace_id=f"r{self.worker_id:02d}-{os.getpid():x}-{count:06x}",
            endpoint=endpoint,
        )
        self._req_local.ctx = ctx
        ctx.overhead_s += _time.perf_counter() - t0
        return ctx

    def adopt_request(
        self, header_value: str | None, endpoint: str = ""
    ) -> RequestTrace | None:
        """Downstream-hop side: adopt the caller's trace context from an
        ``X-Pathway-Trace`` header.  The ROOT owns the sampling
        decision, so a sampled header is honored even when this
        process's own request tracing is off (a traced federation
        front can stitch through untraced workers)."""
        parsed = parse_trace_header(header_value)
        if parsed is None or not parsed[2]:
            return None
        ctx = RequestTrace(
            trace_id=parsed[0],
            endpoint=endpoint,
            parent_span=parsed[1],
            remote=True,
        )
        self._req_local.ctx = ctx
        return ctx

    def current_request(self) -> RequestTrace | None:
        """This thread's in-flight request trace, or None — the guard
        every read-tier instrumentation site checks first."""
        return getattr(self._req_local, "ctx", None)

    def take_request_spans(self) -> list[dict]:
        """A remote hop's accumulated spans, for the response-header
        piggyback back to the caller."""
        ctx = self.current_request()
        return ctx.take_spans() if ctx is not None else []

    def drop_request(self) -> None:
        """Clear this thread's request slot — called unconditionally in
        handler ``finally`` blocks so pooled serving threads never leak
        a context into the next request they pick up."""
        self._req_local.ctx = None

    def end_request(
        self, ctx: RequestTrace | None, status: int = 200, **fields: Any
    ) -> dict | None:
        """Root-side request end: assemble the trace (local + merged
        remote spans, each on its own per-process track), attribute the
        critical path, ring it, and feed the request sampler."""
        self._req_local.ctx = None
        if ctx is None or ctx.remote:
            return None
        t_end = _time.perf_counter()
        end_wall = perf_to_wall(t_end)
        with ctx._lock:
            spans = list(ctx.spans)
            dropped = ctx.dropped
        trace: dict = {
            "kind": "request",
            "trace_id": ctx.trace_id,
            "endpoint": ctx.endpoint,
            "status": int(status),
            "commit_time": int(fields.pop("commit_time", 0) or 0),
            "epoch": self.epoch,
            "worker": ctx.track,
            "origin_wall": ctx.origin_wall,
            "begin_wall": ctx.begin_wall,
            "end_wall": end_wall,
            "spans": spans,
            "workers": {},
            "sink_rows": 0,
            "dropped_spans": dropped,
            "device_kernel_ns": {},
            "device_s": 0.0,
        }
        if fields:
            trace["request"] = dict(fields)
        trace["critical_path"] = critical_path(trace)
        with self._lock:
            self._traces.append(trace)
        overhead = ctx.overhead_s + (_time.perf_counter() - t_end)
        self._adapt_request(
            overhead, max(end_wall - ctx.begin_wall, 0.0)
        )
        return trace

    def _adapt_request(self, overhead_s: float, wall_s: float) -> None:
        """Same EMA-doubling discipline as :meth:`_adapt`, on the
        request sampler's own interval so query floods cannot push the
        commit sampler around (and vice versa)."""
        amortized = overhead_s / max(1, self.request_interval)
        ratio = amortized / max(wall_s, 1e-6)
        ema = self._req_overhead_ema
        self._req_overhead_ema = (
            ratio if ema is None else 0.5 * ema + 0.5 * ratio
        )
        if self._req_overhead_ema > OVERHEAD_TARGET:
            self.request_interval = min(self.request_interval * 2, 4096)
            self._req_overhead_ema /= 2.0
        elif (
            self.request_interval > self.request_base_interval
            and self._req_overhead_ema < OVERHEAD_TARGET / 4.0
        ):
            self.request_interval = max(
                self.request_base_interval, self.request_interval // 2
            )
            self._req_overhead_ema *= 2.0

    # -- read side -----------------------------------------------------------

    def traces(self) -> list[dict]:
        with self._lock:
            return list(self._traces)

    def active_trace_id(self) -> str | None:
        ctx = self._ctx
        return ctx.trace_id if ctx is not None else None

    def summary(self) -> dict:
        """Structured roll-up for bench JSON: trace count, span volume,
        the mean critical-path buckets, and the last commit's full
        breakdown.  Serving-plane query traces are rolled up separately
        (``query_traces`` / ``query_ms_mean``) so query latency cannot
        skew the commit critical-path means."""
        all_traces = self.traces()
        queries = [t for t in all_traces if t.get("kind") == "serving"]
        requests = [t for t in all_traces if t.get("kind") == "request"]
        traces = [
            t
            for t in all_traces
            if t.get("kind") not in ("serving", "request")
        ]
        query_summary: dict = {}
        if queries:
            query_summary = {
                "query_traces": len(queries),
                "query_ms_mean": round(
                    sum(
                        (t["end_wall"] - t["origin_wall"]) for t in queries
                    )
                    / len(queries)
                    * 1000.0,
                    3,
                ),
            }
        if requests:
            query_summary["request_traces"] = len(requests)
            query_summary["request_ms_mean"] = round(
                sum((t["end_wall"] - t["origin_wall"]) for t in requests)
                / len(requests)
                * 1000.0,
                3,
            )
            query_summary["request_sample_interval"] = self.request_interval
        if not traces:
            return {
                "traces": 0,
                "sample_interval": self.interval,
                **query_summary,
            }
        n = len(traces)
        keys = (
            "wall_s",
            "host_compute_s",
            "exchange_s",
            "queue_wait_s",
            "device_s",
        )
        mean = {
            k: round(sum(t["critical_path"][k] for t in traces) / n, 6)
            for k in keys
        }
        # mean bucket shares as fractions of the mean wall — computed
        # from the means (not averaged per-trace) so older ring entries
        # without a "shares" field cannot skew the roll-up
        mean["shares"] = _bucket_shares(
            mean["wall_s"],
            mean["host_compute_s"],
            mean["exchange_s"],
            mean["queue_wait_s"],
            mean["device_s"],
        )
        spans = sum(
            len(t["spans"]) + sum(len(v) for v in t["workers"].values())
            for t in traces
        )
        return {
            "traces": n,
            "spans": spans,
            "sample_interval": self.interval,
            "critical_path_mean": mean,
            "last": traces[-1]["critical_path"],
            **query_summary,
        }

    def export(self, directory: str | None = None) -> str | None:
        """Dump the ring as one Chrome trace-event JSON file
        (``pathway_trace_p<worker>_pid<pid>_<n>.json``) into
        ``directory`` / ``PATHWAY_TPU_TRACE_DIR`` / the system temp
        dir.  Returns the path, or None when there is nothing to dump
        or the dump itself fails (export must never mask a run)."""
        traces = self.traces()
        if not traces:
            return None
        try:
            directory = (
                directory
                or os.environ.get("PATHWAY_TPU_TRACE_DIR")
                or tempfile.gettempdir()
            )
            os.makedirs(directory, exist_ok=True)
            self._export_seq += 1
            path = os.path.join(
                directory,
                f"pathway_trace_p{self.worker_id}"
                f"_pid{os.getpid()}_{self._export_seq:03d}.json",
            )
            payload = chrome_trace(traces)
            payload["otherData"] = {
                "worker": self.worker_id,
                "pid": os.getpid(),
                "traces": [
                    {
                        "trace_id": t["trace_id"],
                        "kind": t.get("kind", "commit"),
                        "commit_time": t["commit_time"],
                        "epoch": t["epoch"],
                        "sink_rows": t["sink_rows"],
                        "critical_path": t["critical_path"],
                        **(
                            {"spans": t["spans"]}
                            if t.get("kind") in ("serving", "request")
                            else {}
                        ),
                        **(
                            {
                                "endpoint": t.get("endpoint", ""),
                                "status": t.get("status", 0),
                                "request": t.get("request", {}),
                            }
                            if t.get("kind") == "request"
                            else {}
                        ),
                    }
                    for t in traces
                ],
            }
            with open(path, "w") as fh:
                json.dump(payload, fh, default=repr)
            return path
        except Exception:
            return None


# -- critical-path attribution ------------------------------------------------


def critical_path(trace: dict) -> dict:
    """Bucket a trace's wall time (origin -> commit end) into
    queue-wait / exchange / device / host-compute, plus the serialized
    chain of significant spans in timestamp order.

    The buckets sum to ``wall_s`` exactly by construction: queue-wait is
    the ingest wait (begin - origin) plus ``cat="wait"`` spans, exchange
    is measured encode/apply/marshalling time plus mesh recv blocking
    during commit exchange rounds (wire latency is exchange cost — the
    device collective has no wire, which is exactly what the
    collective_exchange bench leg compares), device is the native
    ``kernel_ns`` delta, and host-compute is the residual (clamped at
    zero, flagged via ``clamped``)."""
    wall = max(1e-9, trace["end_wall"] - trace["origin_wall"])
    queue = max(0.0, trace["begin_wall"] - trace["origin_wall"])
    exchange = 0.0
    for s in trace["spans"]:
        cat = s.get("cat")
        dur = s.get("dur", 0) / 1e6
        if cat == "wait":
            queue += dur
        elif cat == "exchange":
            exchange += dur
    device = float(trace.get("device_s", 0.0))
    host = wall - queue - exchange - device
    clamped = host < 0.0
    host = max(0.0, host)
    chain: list[dict] = []
    for s in sorted(trace["spans"], key=lambda s: s["ts"]):
        if s.get("cat") == "commit":
            continue
        dur_ms = s.get("dur", 0) / 1000.0
        if dur_ms >= wall * 1000.0 * 0.01 or s.get("cat") in (
            "wait",
            "exchange",
            "queue",
        ):
            chain.append(
                {
                    "name": s["name"],
                    "cat": s.get("cat", ""),
                    "ms": round(dur_ms, 3),
                }
            )
            if len(chain) >= 64:
                break
    return {
        "wall_s": round(wall, 6),
        "host_compute_s": round(host, 6),
        "exchange_s": round(exchange, 6),
        "queue_wait_s": round(queue, 6),
        "device_s": round(device, 6),
        # per-bucket shares as fractions of commit wall: the docs/s
        # trajectory and the bucket trajectory stay comparable across
        # BENCH_r* files regardless of absolute commit duration
        "shares": _bucket_shares(wall, host, exchange, queue, device),
        "clamped": clamped,
        "chain": chain,
    }


def _bucket_shares(
    wall: float, host: float, exchange: float, queue: float, device: float
) -> dict:
    w = max(wall, 1e-9)
    return {
        "host_compute": round(host / w, 4),
        "exchange": round(exchange / w, 4),
        "queue_wait": round(queue / w, 4),
        "device": round(device / w, 4),
    }


# -- Chrome trace-event export ------------------------------------------------


def chrome_trace(traces: list[dict]) -> dict:
    """Render assembled traces as a Chrome trace-event JSON object
    (Perfetto/chrome://tracing loadable): complete ``"X"`` events on one
    track per worker (``pid``/``tid`` = worker id), a root ``commit``
    span per worker per trace for containment parentage, and ``"M"``
    metadata events naming the tracks.  Events are sorted by timestamp,
    so each track's sequence is monotonic — the invariant
    :func:`validate_chrome_trace` checks."""
    events: list[dict] = []
    pids: set[int] = set()
    for trace in traces:
        groups: dict[int, list[dict]] = {}
        for s in trace["spans"]:
            groups.setdefault(int(s.get("pid", trace["worker"])), []).append(s)
        for peer, spans in trace["workers"].items():
            for s in spans:
                groups.setdefault(int(s.get("pid", peer)), []).append(s)
        for wid, spans in sorted(groups.items()):
            if not spans:
                continue
            pids.add(wid)
            start = min(s["ts"] for s in spans)
            end = max(s["ts"] + s.get("dur", 0) for s in spans)
            root_args: dict = {
                "trace": trace["trace_id"],
                "commit_time": trace["commit_time"],
            }
            if wid == trace["worker"]:
                root_args["critical_path"] = {
                    k: v
                    for k, v in trace["critical_path"].items()
                    if k != "chain"
                }
                if trace["device_kernel_ns"]:
                    root_args["device_kernel_ns"] = trace["device_kernel_ns"]
            events.append(
                {
                    "name": (
                        f"query @{trace['commit_time']}"
                        if trace.get("kind") == "serving"
                        else f"request {trace.get('endpoint') or '?'}"
                        if trace.get("kind") == "request"
                        else f"commit {trace['commit_time']}"
                    ),
                    "cat": "commit",
                    "ph": "X",
                    "ts": start,
                    "dur": max(0, end - start),
                    "pid": wid,
                    "tid": wid,
                    "args": root_args,
                }
            )
            for s in spans:
                ev = {
                    "name": s["name"],
                    "cat": s.get("cat", ""),
                    "ph": "X",
                    "ts": s["ts"],
                    "dur": s.get("dur", 0),
                    "pid": wid,
                    "tid": wid,
                    "args": dict(
                        s.get("args") or {}, trace=trace["trace_id"]
                    ),
                }
                events.append(ev)
    # a root span shares its start ts with its first child: emit the
    # longer (enclosing) event first so viewers nest them correctly
    events.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": wid,
            "tid": wid,
            "args": {"name": f"worker {wid}"},
        }
        for wid in sorted(pids)
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def validate_chrome_trace(obj: Any) -> list[dict]:
    """Strict Chrome trace-event conformance check (the trace-export
    gate in tools/check.py): the object is a ``{"traceEvents": [...]}``
    dict or a bare event list; every event is ``"X"`` (with a numeric
    non-negative ``dur``), a matched ``"B"``/``"E"`` pair, or ``"M"``
    metadata; and timestamps are monotonic non-decreasing per
    ``(pid, tid)`` track.  Returns the event list; raises
    ``ValueError`` on any violation."""
    if isinstance(obj, list):
        events = obj
    elif isinstance(obj, dict):
        events = obj.get("traceEvents")
        if not isinstance(events, list):
            raise ValueError("trace object has no traceEvents list")
    else:
        raise ValueError(f"not a trace object: {type(obj).__name__}")
    last_ts: dict[tuple, float] = {}
    open_begins: dict[tuple, list] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i}: not an object")
        ph = ev.get("ph")
        if ph == "M":
            continue
        if ph not in ("X", "B", "E"):
            raise ValueError(f"event {i}: unsupported phase {ph!r}")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            raise ValueError(f"event {i}: missing/non-numeric ts")
        track = (ev.get("pid"), ev.get("tid"))
        if ts < last_ts.get(track, float("-inf")):
            raise ValueError(
                f"event {i}: non-monotonic ts on track {track}"
            )
        last_ts[track] = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    f"event {i}: X event needs a non-negative dur"
                )
        elif ph == "B":
            open_begins.setdefault(track, []).append(ev.get("name"))
        else:  # "E"
            stack = open_begins.get(track)
            if not stack:
                raise ValueError(
                    f"event {i}: E without a matching B on track {track}"
                )
            stack.pop()
    for track, stack in open_begins.items():
        if stack:
            raise ValueError(
                f"track {track}: unclosed B events {stack!r}"
            )
    return events


#: the process-wide recorder every instrumented hot path consults
TRACER = TraceRecorder()


def current() -> TraceContext | None:
    """The active sampled-commit context, or None — THE hot-path guard;
    call once per batch/sweep, not per row."""
    return TRACER._ctx


def _active_trace_id() -> str | None:
    rctx = TRACER.current_request()
    if rctx is not None:
        return rctx.trace_id
    ctx = TRACER._ctx
    return ctx.trace_id if ctx is not None else None


# flight-recorder integration: every event recorded (and every dump
# written) while a sampled commit is in flight references its trace id
_metrics.set_trace_id_provider(_active_trace_id)
