"""Python framework internals (declarative API, graph capture, lowering)."""
