"""User-facing reducer constructors: ``pw.reducers.*``.

(reference: python/pathway/internals/reducers.py, 723 LoC + custom_reducers.py)
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.reducers import ReducerKind
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    BinaryOpExpression,
    CastExpression,
    ColumnExpression,
    ReducerExpression,
    wrap_expression,
)


def count(*args: Any) -> ReducerExpression:
    return ReducerExpression(ReducerKind.COUNT, [])


def sum(expr: Any) -> ReducerExpression:  # noqa: A001 — mirrors pw.reducers.sum
    return ReducerExpression(ReducerKind.SUM, [wrap_expression(expr)])


def min(expr: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(ReducerKind.MIN, [wrap_expression(expr)])


def max(expr: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(ReducerKind.MAX, [wrap_expression(expr)])


def argmin(expr: Any) -> ReducerExpression:
    return ReducerExpression(ReducerKind.ARG_MIN, [wrap_expression(expr)])


def argmax(expr: Any) -> ReducerExpression:
    return ReducerExpression(ReducerKind.ARG_MAX, [wrap_expression(expr)])


def unique(expr: Any) -> ReducerExpression:
    return ReducerExpression(ReducerKind.UNIQUE, [wrap_expression(expr)])


def any(expr: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(ReducerKind.ANY, [wrap_expression(expr)])


def sorted_tuple(expr: Any, *, skip_nones: bool = False) -> ReducerExpression:
    return ReducerExpression(
        ReducerKind.SORTED_TUPLE, [wrap_expression(expr)], skip_nones=skip_nones
    )


def tuple(expr: Any, *, skip_nones: bool = False) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(
        ReducerKind.TUPLE, [wrap_expression(expr)], skip_nones=skip_nones
    )


def ndarray(expr: Any) -> ReducerExpression:
    return ReducerExpression(ReducerKind.NDARRAY, [wrap_expression(expr)])


def earliest(expr: Any) -> ReducerExpression:
    return ReducerExpression(ReducerKind.EARLIEST, [wrap_expression(expr)])


def latest(expr: Any) -> ReducerExpression:
    return ReducerExpression(ReducerKind.LATEST, [wrap_expression(expr)])


def count_distinct(expr: Any) -> ReducerExpression:
    return ReducerExpression(ReducerKind.COUNT_DISTINCT, [wrap_expression(expr)])


def avg(expr: Any) -> ColumnExpression:
    """Average — desugars to sum/count at reduce time."""
    expr = wrap_expression(expr)
    s = ReducerExpression(ReducerKind.SUM, [expr])
    c = ReducerExpression(ReducerKind.COUNT, [])
    out = BinaryOpExpression("/", s, c)
    out._dtype = dt.FLOAT
    return out


def stateful_single(
    combine: Callable[..., Any], *exprs: Any
) -> ReducerExpression:
    """Custom reducer recomputed over the group's retained multiset.

    ``combine(values: list) -> value`` receives the current (flattened)
    multiset of argument values.
    """
    wrapped = [wrap_expression(e) for e in exprs]

    def combine_entries(entries: list) -> Any:
        values: list[Any] = []
        for args, cnt in entries:
            v = args if len(args) > 1 else args[0]
            values.extend([v] * cnt)
        return combine(values)

    return ReducerExpression(
        ReducerKind.STATEFUL,
        wrapped,
        combine=combine_entries,
        n_args=len(wrapped),
    )
