"""HF-checkpoint → JAX pytree importer for the BERT-family encoders.

The reference loads real sentence-transformers models via torch
(reference: xpacks/llm/embedders.py:270). Here weights import once into the
functional param tree of models/transformer.py, after which everything runs
as jit JAX on TPU. Accepts a torch ``state_dict`` (or a dict of numpy
arrays, or a file saved by torch/np.savez) in HF BERT naming, with or
without the ``bert.`` prefix.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pathway_tpu.models.transformer import EncoderConfig, Params


def _to_np(t: Any) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _normalize_state_dict(state: Any) -> dict[str, np.ndarray]:
    if isinstance(state, (str, bytes)):
        path = str(state)
        if path.endswith(".npz"):
            return {k: np.asarray(v) for k, v in np.load(path).items()}
        import torch

        return {
            k: _to_np(v)
            for k, v in torch.load(path, map_location="cpu").items()
        }
    return {k: _to_np(v) for k, v in dict(state).items()}


def _strip_prefix(state: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    for prefix in ("bert.", "model.", "encoder.bert."):
        if any(k.startswith(prefix) for k in state):
            state = {
                (k[len(prefix):] if k.startswith(prefix) else k): v
                for k, v in state.items()
            }
    return state


def config_from_state_dict(state: Any) -> EncoderConfig:
    """Infer the architecture from tensor shapes."""
    sd = _strip_prefix(_normalize_state_dict(state))
    vocab, hidden = sd["embeddings.word_embeddings.weight"].shape
    max_len = sd["embeddings.position_embeddings.weight"].shape[0]
    type_vocab = sd["embeddings.token_type_embeddings.weight"].shape[0]
    intermediate = sd["encoder.layer.0.intermediate.dense.weight"].shape[0]
    layers = 0
    while f"encoder.layer.{layers}.intermediate.dense.weight" in sd:
        layers += 1
    # heads: HF stores it in config only; every BERT-family checkpoint the
    # reference defaults to uses head_dim 32 or 64 — prefer 64 when it divides
    heads = hidden // 64 if hidden % 64 == 0 else hidden // 32
    return EncoderConfig(
        vocab_size=vocab,
        hidden=hidden,
        layers=layers,
        heads=heads,
        intermediate=intermediate,
        max_len=max_len,
        type_vocab=type_vocab,
    )


def import_hf_encoder(
    state: Any, cfg: EncoderConfig | None = None
) -> tuple[Params, EncoderConfig]:
    """-> (params pytree for encoder_forward, config). HF Linear stores
    ``weight [out, in]``; our forward computes ``x @ W`` so weights
    transpose on import."""
    import jax.numpy as jnp

    sd = _strip_prefix(_normalize_state_dict(state))
    if cfg is None:
        cfg = config_from_state_dict(sd)

    def j(name: str, transpose: bool = False) -> Any:
        arr = sd[name]
        if transpose:
            arr = arr.T
        return jnp.asarray(arr, jnp.float32)

    def ln(prefix: str) -> dict:
        return {
            "scale": j(f"{prefix}.weight"),
            "bias": j(f"{prefix}.bias"),
        }

    params: Params = {
        "tok_emb": j("embeddings.word_embeddings.weight"),
        "pos_emb": j("embeddings.position_embeddings.weight"),
        "type_emb": j("embeddings.token_type_embeddings.weight"),
        "emb_ln": ln("embeddings.LayerNorm"),
        "layers": [],
    }
    for i in range(cfg.layers):
        pre = f"encoder.layer.{i}"
        qkv_w = np.concatenate(
            [
                sd[f"{pre}.attention.self.query.weight"].T,
                sd[f"{pre}.attention.self.key.weight"].T,
                sd[f"{pre}.attention.self.value.weight"].T,
            ],
            axis=1,
        )
        qkv_b = np.concatenate(
            [
                sd[f"{pre}.attention.self.query.bias"],
                sd[f"{pre}.attention.self.key.bias"],
                sd[f"{pre}.attention.self.value.bias"],
            ]
        )
        params["layers"].append(
            {
                "qkv_w": jnp.asarray(qkv_w, jnp.float32),
                "qkv_b": jnp.asarray(qkv_b, jnp.float32),
                "out_w": j(f"{pre}.attention.output.dense.weight", transpose=True),
                "out_b": j(f"{pre}.attention.output.dense.bias"),
                "attn_ln": ln(f"{pre}.attention.output.LayerNorm"),
                "fc1_w": j(f"{pre}.intermediate.dense.weight", transpose=True),
                "fc1_b": j(f"{pre}.intermediate.dense.bias"),
                "fc2_w": j(f"{pre}.output.dense.weight", transpose=True),
                "fc2_b": j(f"{pre}.output.dense.bias"),
                "mlp_ln": ln(f"{pre}.output.LayerNorm"),
            }
        )
    return params, cfg


def load_sentence_transformer(
    model_path: str,
    *,
    pooling: str = "mean",
) -> tuple[Params, EncoderConfig, Any]:
    """Load a locally cached sentence-transformers/HF directory:
    weights (pytorch_model.bin / model.npz) + vocab.txt WordPiece.
    -> (params, config, tokenizer)."""
    import os

    from pathway_tpu.xpacks.llm._tokenizer import WordPieceTokenizer

    state_path = None
    for candidate in ("pytorch_model.bin", "model.npz", "model.pt"):
        p = os.path.join(model_path, candidate)
        if os.path.exists(p):
            state_path = p
            break
    if state_path is None:
        raise FileNotFoundError(
            f"no pytorch_model.bin / model.npz under {model_path}"
        )
    params, cfg = import_hf_encoder(state_path)
    overrides: dict[str, Any] = {"pooling": pooling}
    cfg_json = os.path.join(model_path, "config.json")
    if os.path.exists(cfg_json):
        # head count is invisible in tensor shapes (MiniLM: 384 hidden =
        # 12 heads x 32, not the inferred 6 x 64) — config.json is
        # authoritative when present
        import json

        with open(cfg_json, encoding="utf-8") as f:
            hf_cfg = json.load(f)
        if "num_attention_heads" in hf_cfg:
            overrides["heads"] = int(hf_cfg["num_attention_heads"])
    cfg = EncoderConfig(
        **{
            **{f.name: getattr(cfg, f.name) for f in cfg.__dataclass_fields__.values()},
            **overrides,
        }
    )
    vocab_path = os.path.join(model_path, "vocab.txt")
    tokenizer = (
        WordPieceTokenizer(vocab_path) if os.path.exists(vocab_path) else None
    )
    return params, cfg, tokenizer
