"""ViT/CLIP-style image encoder — the vision leg of the multimodal stack.

The reference's multimodal path sends slide/image bytes to a remote vision
LLM (reference: python/pathway/xpacks/llm/parsers.py:396,569 and the CLIP
embedders of vector_store.py:588). This environment has no egress, so the
vision seam's DEFAULT is this TPU-native ViT: patchify -> pre-LN
transformer -> CLS -> projection -> L2-normalised embedding, the CLIP
image-tower shape (patch 16, learned positions, quick-GELU lineage kept as
plain GELU).

Design notes (TPU-first):
- patchify is a reshape + one [p*p*3, hidden] matmul — no conv primitive,
  so XLA sees a single MXU-friendly GEMM per image batch.
- pre-LN blocks share layer_norm/dense_attention with transformer.py; all
  activations in cfg.dtype (bf16 by default) with f32 layer norms.
- params carry PartitionSpec rules (vision_param_spec) so the tower
  tensor-shards over the model axis exactly like the text encoders.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from pathway_tpu.models.transformer import (
    Params,
    dense_attention,
    layer_norm,
)
from pathway_tpu.parallel.mesh import MODEL_AXIS
from pathway_tpu.parallel.sharding import P


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch: int = 16
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    intermediate: int = 3072
    out_dim: int = 512
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2


def clip_vit_b16() -> VisionConfig:
    """CLIP ViT-B/16 image tower shape."""
    return VisionConfig()


def vit_tiny() -> VisionConfig:
    """Small config for tests/dry runs."""
    return VisionConfig(
        image_size=32,
        patch=8,
        hidden=64,
        layers=2,
        heads=4,
        intermediate=128,
        out_dim=32,
    )


def init_vision_params(rng: jax.Array, cfg: VisionConfig) -> Params:
    def dense(key, shape, scale=0.02):
        return scale * jax.random.normal(key, shape, jnp.float32)

    def ln():
        return {
            "scale": jnp.ones((cfg.hidden,), jnp.float32),
            "bias": jnp.zeros((cfg.hidden,), jnp.float32),
        }

    keys = iter(jax.random.split(rng, 5 + 8 * cfg.layers))
    patch_dim = cfg.patch * cfg.patch * 3
    p: Params = {
        "patch_w": dense(next(keys), (patch_dim, cfg.hidden)),
        "cls": dense(next(keys), (cfg.hidden,)),
        "pos_emb": dense(next(keys), (cfg.n_patches + 1, cfg.hidden)),
        "pre_ln": ln(),
        "final_ln": ln(),
        "proj": dense(next(keys), (cfg.hidden, cfg.out_dim)),
        "layers": [],
    }
    for _ in range(cfg.layers):
        p["layers"].append(
            {
                "ln1": ln(),
                "qkv_w": dense(next(keys), (cfg.hidden, 3 * cfg.hidden)),
                "qkv_b": jnp.zeros((3 * cfg.hidden,), jnp.float32),
                "out_w": dense(next(keys), (cfg.hidden, cfg.hidden)),
                "out_b": jnp.zeros((cfg.hidden,), jnp.float32),
                "ln2": ln(),
                "fc1_w": dense(next(keys), (cfg.hidden, cfg.intermediate)),
                "fc1_b": jnp.zeros((cfg.intermediate,), jnp.float32),
                "fc2_w": dense(next(keys), (cfg.intermediate, cfg.hidden)),
                "fc2_b": jnp.zeros((cfg.hidden,), jnp.float32),
            }
        )
    return p


def vision_param_spec(path: tuple, leaf: Any) -> P:
    """Megatron-style split over the model axis, matching
    transformer.encoder_param_spec."""
    name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
    if name in ("qkv_w", "fc1_w", "proj"):
        return P(None, MODEL_AXIS)
    if name in ("out_w", "fc2_w"):
        return P(MODEL_AXIS, None)
    # pos_emb is replicated: its row count (n_patches + 1, e.g. 197) is
    # prime, so a model-axis split can never divide it
    return P()


def patchify(pixels: jax.Array, cfg: VisionConfig) -> jax.Array:
    """``[b, H, W, 3]`` -> ``[b, n_patches, patch*patch*3]`` by reshape
    (rows of patches, then columns) — the conv-free patch embed feed."""
    b = pixels.shape[0]
    s, p = cfg.image_size, cfg.patch
    g = s // p
    x = pixels.reshape(b, g, p, g, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [b, g, g, p, p, 3]
    return x.reshape(b, g * g, p * p * 3)


def vision_forward(
    params: Params,
    pixels: jax.Array,
    cfg: VisionConfig,
    attn_fn=None,
) -> jax.Array:
    """``pixels [b, H, W, 3]`` (normalised floats) -> L2-normalised
    embeddings ``[b, out_dim]``. ``attn_fn=None`` picks the backend
    default (the Pallas flash kernel on TPU, dense elsewhere)."""
    if attn_fn is None:
        from pathway_tpu.models.transformer import default_attn_fn

        attn_fn = default_attn_fn()
    b = pixels.shape[0]
    patches = patchify(pixels.astype(cfg.dtype), cfg)
    x = patches @ params["patch_w"].astype(cfg.dtype)
    cls = jnp.broadcast_to(
        params["cls"].astype(cfg.dtype)[None, None], (b, 1, cfg.hidden)
    )
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_emb"].astype(cfg.dtype)[None]
    x = layer_norm(x, params["pre_ln"], cfg.layer_norm_eps)
    t = x.shape[1]
    for lp in params["layers"]:
        h = layer_norm(x, lp["ln1"], cfg.layer_norm_eps)
        qkv = h @ lp["qkv_w"].astype(cfg.dtype) + lp["qkv_b"].astype(cfg.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, cfg.heads, cfg.head_dim)
        k = k.reshape(b, t, cfg.heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.heads, cfg.head_dim)
        a = attn_fn(q, k, v, None).reshape(b, t, cfg.hidden)
        x = x + a @ lp["out_w"].astype(cfg.dtype) + lp["out_b"].astype(cfg.dtype)
        h = layer_norm(x, lp["ln2"], cfg.layer_norm_eps)
        h = h @ lp["fc1_w"].astype(cfg.dtype) + lp["fc1_b"].astype(cfg.dtype)
        h = jax.nn.gelu(h, approximate=True)
        x = x + h @ lp["fc2_w"].astype(cfg.dtype) + lp["fc2_b"].astype(cfg.dtype)
    x = layer_norm(x, params["final_ln"], cfg.layer_norm_eps)
    emb = (x[:, 0] @ params["proj"].astype(cfg.dtype)).astype(jnp.float32)
    return emb / jnp.maximum(
        jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-12
    )


#: CLIP preprocessing constants (OpenAI CLIP mean/std)
CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
CLIP_STD = (0.26862954, 0.26130258, 0.27577711)


def preprocess_image(img: Any, cfg: VisionConfig):
    """PIL image -> normalised ``[H, W, 3]`` float32 numpy (resize +
    centre-value scaling, CLIP statistics)."""
    import numpy as np

    arr = preprocess_image_u8(img, cfg).astype(np.float32) / 255.0
    return (arr - np.asarray(CLIP_MEAN, np.float32)) / np.asarray(
        CLIP_STD, np.float32
    )


def preprocess_image_u8(img: Any, cfg: VisionConfig):
    """PIL image -> resized ``[H, W, 3]`` uint8. Host keeps bytes small;
    CLIP normalisation happens on device (normalize_u8) — a 4x smaller
    host->device transfer than shipping f32 pixels (38 MB -> 9.6 MB per
    64-image batch at 224px, the difference between tunnel-bound and
    compute-bound ingest)."""
    import numpy as np

    img = img.convert("RGB").resize(
        (cfg.image_size, cfg.image_size), resample=2  # bilinear
    )
    return np.asarray(img, np.uint8)


def normalize_u8(pixels_u8: jax.Array) -> jax.Array:
    """Device-side CLIP normalisation of uint8 pixels ``[b, H, W, 3]``."""
    x = pixels_u8.astype(jnp.float32) / 255.0
    mean = jnp.asarray(CLIP_MEAN, jnp.float32)
    std = jnp.asarray(CLIP_STD, jnp.float32)
    return (x - mean) / std
